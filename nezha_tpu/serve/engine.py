"""The continuous-batching engine: a frozen set of programs, reused forever.

Steady-state serving is exactly ``1 + len(prefill_buckets)`` XLA
programs regardless of request mix — the property that keeps TPU serving
latency flat:

- **prefill** — one compiled program per PREFILL BUCKET (static prompt
  pad widths, default powers of two up to ``max_prefill_len``). A
  prompt's tokens are padded to the smallest bucket that fits, the
  slot's pooled cache rows are sliced out (``read_slot``), the chunk
  runs through the model at its TRACED position offset via the masked
  attention path (which attends everything previously written to the
  slot), and the updated rows are written back (``write_slot``).
  Prompts longer than ``max_prefill_len`` are no longer rejected: they
  prefill in successive chunks — full ``max_prefill_len``-wide chunks,
  then a bucketed tail — reusing the same bucket programs at advancing
  offsets, so CHUNKING ADDS NO PROGRAMS. Bucket pads beyond the prompt
  write garbage K/V that is never attended (the masks stop at the
  written prefix, and decode overwrites pad positions before its mask
  reaches them). The traced offset is the trade the chunk contract
  buys: a traced ``pos`` cannot take the static-pos-0 flash-prefill
  path, so chunk attention is masked-dense over the slot's ``L_max``
  rows — paid once per request, versus the per-token decode win; a
  diagonal-offset flash prefill kernel would recover it without
  touching the program count and is the obvious next kernel.
- **step** — one batched decode BLOCK over all ``B_max`` rows: a
  ``lax.scan`` of ``decode_horizon`` single-token steps, the whole
  horizon inside one compiled program. Each scan step samples per row
  from the carried last-logits (per-row traced temperature / top-k /
  top-p — serve/sampling.py), forwards through the model with PER-ROW
  cache positions (models/gpt2.py per-row pos path), and feeds the
  sampled token straight into the next step's embedding — tokens never
  visit the host mid-block, so the per-token Python→XLA dispatch +
  device→host sync cost is paid once per H tokens instead of once per
  token. Completion is decided ON DEVICE: per-row ``eos_ids`` and
  remaining-``budgets`` (engine state set at prefill) flip a carried
  ``done`` mask the moment a row emits EOS or exhausts its budget, and
  the carried ``ok`` health mask (NaN/inf tripwire, ANDed per scan
  step) freezes a poisoned row from the bad step on — either way the
  row stops sampling AND stops writing K/V for the rest of the block,
  because the per-step ``active ∧ ¬done ∧ ok`` emit mask is what
  threads into the model as ``active``. On TPU the attention inside
  each scan step is the Pallas flash-decode kernel
  (ops/pallas/decode_attention.py): per-row ``lengths`` skip KV blocks
  above each row's depth, and non-emitting rows (inactive slots, done
  rows, frozen rows) skip every block instead of computing masked
  garbage (host-side masking still applies — their state is frozen by
  ``where(emit, ...)``). The program returns a ``[B, H]`` token block
  plus per-row ``emitted`` counts; overshoot columns past a row's
  count are pad and never reach the client. ``decode_horizon=1``
  (default) runs the scan body once inline — bit-identical to the
  classic one-token step.

Both KV layouts run through the SAME program set: on the default
block-paged pool (``ServeConfig.kv_layout="paged"``) every program takes
one extra static-shaped operand — the per-slot block tables, uploaded
from the pool's host mirror each dispatch — and the model's cache path
scatters K/V through the table into shared block pools instead of
slicing slot rows (prefix-hit requests prefill only their un-cached
suffix, through the same bucket programs at a nonzero start offset;
lazy block binding and copy-on-write happen host-side BEFORE each
dispatch, so in-program writes always land in exclusively-owned
blocks, with non-emitting rows routed to the reserved scratch block).

All programs route through the runtime ``Executor`` (compile-cache keyed
on function identity + full arg shape signature), so the program-count
claim is enforced by the ``compile_cache.*`` obs counters: a shape drift
would show up as an extra miss, and tests pin the count at
``1 + len(prefill_buckets)`` with misses frozen after warmup (a bucket
program compiles the first time a prompt lands in its bucket).

All per-request scalars cross into the programs as 0-d ARRAYS, never
Python numbers — the executor's signature (and jax.jit's) would
otherwise key on the literal value and recompile per request.

Token-range validation lives in the scheduler's admission path
(``Scheduler.submit``), NOT here: the engine trusts its caller so the
per-prefill host work is one ``np.zeros`` + copy per chunk, and a bad
request is bounced before it ever holds a slot.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from nezha_tpu import faults, obs
from nezha_tpu.models.generate import _caches_from_states
from nezha_tpu.runtime.executor import Executor
from nezha_tpu.serve.sampling import (accept_mask, categorical_rows,
                                      filter_logits, filtered_probs,
                                      finite_rows, residual_logits,
                                      sample_tokens, split_and_sample)
from nezha_tpu.serve.slots import (KVBlocksExhausted, PagedSlotPool,
                                   SlotPool, read_slot, write_slot)


def default_prefill_buckets(max_prefill_len: int) -> Tuple[int, ...]:
    """Powers of two from 8 up to (and always ending exactly at)
    ``max_prefill_len`` — e.g. 32 -> (8, 16, 32), 24 -> (8, 16, 24),
    8 -> (8,). Small prompts pad to a small program instead of the full
    width, so short-prompt TTFT stops paying the long-prompt pad tax."""
    buckets: List[int] = []
    b = 8
    while b < max_prefill_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_prefill_len)
    return tuple(buckets)


@dataclasses.dataclass(frozen=True)
class SpeculativeConfig:
    """Speculative-decoding knobs (``ServeConfig.speculative``).

    ``draft_k`` is the number of draft tokens proposed per verify
    window: one verify forward scores all ``draft_k + 1`` positions, so
    a window emits between 1 (every proposal rejected) and
    ``draft_k + 1`` tokens per verify while staying exactly the target
    model's output (greedy: bit-identical; sampled: the lossless
    rejection-sampling law). ``draft_layers`` selects SELF-DRAFTING:
    the draft model is the target's first N layers sharing the
    target's own weights (early-exit drafting — no second checkpoint),
    with ``None`` meaning full depth, an identity draft whose accept
    rate is ~1 (the machinery-overhead measurement point, and the
    bench's guaranteed->1-token-per-verify configuration). Both are
    ignored for the draft's ARCHITECTURE when an explicit
    ``draft_model`` is handed to :class:`Engine` (``draft_k`` still
    applies)."""

    draft_k: int = 4
    draft_layers: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Static serving shapes — everything a compiled program is keyed on.

    ``max_batch_size`` is the slot count (rows decoded per step),
    ``max_len`` the per-slot KV capacity (prompt + generated),
    ``max_prefill_len`` the widest single prefill chunk — longer prompts
    (up to ``max_len``) are prefilled in successive chunks, not
    rejected. ``prefill_buckets`` are the static prompt pad widths (one
    compiled prefill program each; ``()`` selects the powers-of-two
    default from :func:`default_prefill_buckets` — the last bucket must
    equal ``max_prefill_len``). ``k_max`` is the static top-k cap
    per-row ks are clamped to. ``queue_capacity`` bounds the scheduler's
    FIFO (backpressure); ``pad_id`` is the token fed for inactive rows.
    ``decode_impl`` (None = keep the model's own ``GPT2Config.
    decode_impl``) overrides the decode-attention choice for this
    engine: "auto" | "kernel" | "xla" — the serving-side toggle for the
    flash-decode kernel. ``decode_horizon`` is the number of tokens one
    compiled step program decodes per dispatch (the fused device-
    resident sampling loop): 1 (default) is the classic one-token step,
    bit-identical to pre-horizon behavior; H > 1 amortizes the
    per-dispatch host gap over H tokens at the cost of coarser
    deadline/drain granularity (one horizon) — EOS/budget completion
    moves on device, so a row finishing mid-block stops sampling and
    K/V writes immediately and its overshoot is dropped before the
    block reaches the host.
    """

    max_batch_size: int = 4
    max_len: int = 128
    max_prefill_len: int = 32
    prefill_buckets: Tuple[int, ...] = ()
    k_max: int = 64
    queue_capacity: int = 16
    pad_id: int = 0
    cache_dtype: Any = jnp.bfloat16
    decode_impl: Optional[str] = None
    # Paged prefill-chunk attention override (None = keep the model
    # config's prefill_impl): "auto" resolves the flash-prefill kernel
    # by backend, "kernel" forces it (interpret off-TPU — the parity
    # path; on int8 pools the block write fuses into the kernel
    # epilogue), "xla" forces the composed masked path.
    # NEZHA_NO_PREFILL_KERNEL=1 is the env escape hatch.
    prefill_impl: Optional[str] = None
    # Long-context prefill (PR 20). prefill_mode="sequence" shards each
    # prefill chunk's attention over the serve mesh (ShardedEngine
    # only — the single-device engine rejects it): ulysses all-to-all
    # when H % M == 0 (bitwise parity with the replicated path) or
    # ppermute ring hops (serve/sharded/seq_prefill.py).
    # "replicated" is the pre-PR-20 path, bit for bit.
    # NEZHA_NO_SEQ_PREFILL=1 is the env escape hatch (the sharded
    # engine silently falls back to replicated — long buckets keep
    # serving the same prompts either way).
    prefill_mode: str = "replicated"
    # Extra static chunk widths ABOVE max_prefill_len (each >
    # max_prefill_len, <= max_len, strictly increasing): one more
    # compiled prefill program each, letting an 8k-32k document prompt
    # prefill in a handful of wide dispatches instead of hundreds of
    # max_prefill_len strides. () keeps the classic plan byte-for-byte.
    # Under prefill_mode="sequence" every bucket width (short AND long)
    # must divide by the mesh size.
    long_prefill_buckets: Tuple[int, ...] = ()
    # Sequence-sharding layout: "auto" (ulysses when H % M == 0, which
    # the sharded engine's head-divisibility requirement guarantees),
    # "ulysses", or "ring" (docs/RUNBOOK.md §8 selection table).
    seq_prefill_variant: str = "auto"
    decode_horizon: int = 1
    # KV layout: "paged" (default) is the block-paged pool — per-layer
    # [kv_num_blocks, H, kv_block_size, D] buffers, ref-counted blocks
    # bound lazily as positions advance, per-slot block tables threaded
    # into the compiled programs, and (with prefix_cache) shared-prefix
    # prefill reuse. "dense" is the classic [B_max, H, max_len, D]
    # worst-case-reservation pool. kv_num_blocks None = dense-equivalent
    # capacity (1 scratch + max_batch_size * ceil(max_len/block_size)),
    # so the default paged pool can serve everything dense could;
    # smaller values make block budget (tokens actually resident) the
    # admission limit instead of slot count. kv_eviction governs what
    # happens when the free list runs dry: "lru" evicts prefix-cache
    # blocks held only by the trie, "none" goes straight to typed
    # backpressure (KVBlocksExhausted).
    kv_layout: str = "paged"
    kv_block_size: int = 16
    kv_num_blocks: Optional[int] = None
    prefix_cache: bool = True
    kv_eviction: str = "lru"
    # Host tier (0 = off): when kv_eviction="lru" reclaims a trie-only
    # block, demote its int8 payload + per-block scales into a
    # host-RAM LRU of up to this many blocks instead of discarding it;
    # a later trie hit whose blocks were demoted promotes them back
    # with an async host->device copy dispatched ahead of the bucketed
    # prefill, so a returning chat user pays one tail chunk instead of
    # a full cold prefill. Requires the paged layout, kv_dtype="int8"
    # (demotion moves the lossless wire-format bytes verbatim), and
    # prefix_cache — host RAM typically holds ~100x the device's
    # resident conversations at int8 (docs/RUNBOOK.md §8).
    kv_host_blocks: int = 0
    # KV storage dtype. "bf16" (default) stores blocks in cache_dtype —
    # bit-identical to the pre-quantization engine. "int8" (paged
    # layout only) stores K/V blocks as int8 with one fp32 absmax
    # scale per (block, head) (ops/quant.py — the EQuARX recipe the
    # wire collectives already use): ~2x the resident blocks at the
    # same device budget (scale overhead 4/(block_size*D) per
    # element), at a bounded per-block dequant error the
    # serve.kv.quant_error histogram samples. The dequant is fused
    # into the flash-decode kernel's block loop (and applied
    # identically on the gathered XLA fallback), so int8 blocks never
    # round-trip through a dense bf16 cache.
    kv_dtype: str = "bf16"
    # Speculative decoding (None = off, bit-identical to the classic
    # horizon engine): a cheap DRAFT model proposes draft_k tokens per
    # window, one batched target forward verifies all draft_k + 1
    # positions, and an in-program accept mask emits the longest
    # agreeing prefix — so one step dispatch can emit up to
    # decode_horizon * (draft_k + 1) tokens while every emitted token
    # remains exactly the target model's (greedy bit-identical;
    # sampled via standard rejection sampling with a carried residual
    # distribution). The draft's KV lives in a mirrored pool of the
    # same paged machinery (int8 welcome); accepted tokens flow into
    # the existing block-consumption path as ordinary emits.
    speculative: Optional[SpeculativeConfig] = None
    # Multi-tenant scheduling (PR 19). priority_weights selects the
    # weighted-fair-queueing share of admission grants each priority
    # lane gets (virtual-time WFQ — lower-priority lanes are SLOWED,
    # never starved); None keeps the built-in 4:2:1
    # interactive:batch:background split. Accepts a mapping or a
    # ("name", weight) pair sequence; normalized to a canonical tuple.
    # With every request in one lane (the default — Request.priority
    # defaults to "interactive") WFQ degenerates to the exact bounded
    # FIFO of the pre-PR-19 scheduler, bit for bit.
    priority_weights: Optional[Any] = None
    # Per-tenant admission bound (None = off): a tenant with this many
    # requests already queued gets the typed TenantOverLimit
    # (subclass of QueueFull, so HTTP still answers 503) instead of
    # consuming the shared queue_capacity — one bursty tenant cannot
    # wedge the door shut for everyone else.
    tenant_queue_cap: Optional[int] = None
    # Preemption (off by default — bit-for-bit prior behavior): under
    # slot/block pressure (or a burning interactive SLO) the scheduler
    # suspends the lowest-priority running decode, indexes its bound
    # blocks into the prefix trie (re-promotable; eviction demotes
    # them through the host tier when one is configured) and resumes
    # it when pressure clears — admission degrades gracefully instead
    # of rejecting at the door. preemption_budget bounds how many
    # times one request may be preempted (anti-thrash).
    preemption: bool = False
    preemption_budget: int = 2

    @property
    def all_prefill_buckets(self) -> Tuple[int, ...]:
        """Every compiled prefill width, ascending: the classic buckets
        (<= max_prefill_len) followed by the long-context buckets. The
        frozen program contract counts these: steady state is
        ``1 step + len(all_prefill_buckets)`` programs per engine."""
        return tuple(self.prefill_buckets) + tuple(
            self.long_prefill_buckets)

    def __post_init__(self):
        if self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if self.kv_layout not in ("paged", "dense"):
            raise ValueError(
                f"kv_layout must be 'paged' or 'dense', got "
                f"{self.kv_layout!r}")
        if self.kv_block_size < 1:
            raise ValueError(
                f"kv_block_size must be >= 1, got {self.kv_block_size}")
        if self.kv_num_blocks is not None and self.kv_num_blocks < 2:
            raise ValueError(
                f"kv_num_blocks must be >= 2 (block 0 is scratch), got "
                f"{self.kv_num_blocks}")
        if self.kv_eviction not in ("lru", "none"):
            raise ValueError(
                f"kv_eviction must be 'lru' or 'none', got "
                f"{self.kv_eviction!r}")
        if self.kv_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"kv_dtype must be 'bf16' or 'int8', got "
                f"{self.kv_dtype!r}")
        if self.kv_dtype == "int8" and self.kv_layout != "paged":
            raise ValueError(
                "kv_dtype='int8' requires kv_layout='paged' (scales "
                "are per-block state; the dense pool has no blocks)")
        if self.kv_host_blocks < 0:
            raise ValueError(
                f"kv_host_blocks must be >= 0, got "
                f"{self.kv_host_blocks}")
        if self.kv_host_blocks:
            if self.kv_layout != "paged" or self.kv_dtype != "int8":
                raise ValueError(
                    "kv_host_blocks requires kv_layout='paged' and "
                    "kv_dtype='int8' — the host tier demotes the "
                    "int8+scales block payload verbatim (lossless); "
                    "a bf16 tier would serve quantize-dequant blocks "
                    "that differ from a fresh prefill")
            if not self.prefix_cache:
                raise ValueError(
                    "kv_host_blocks requires prefix_cache (demotion "
                    "feeds off trie eviction)")
            if self.kv_eviction != "lru":
                raise ValueError(
                    "kv_host_blocks requires kv_eviction='lru' "
                    "(demotion IS the eviction path; 'none' never "
                    "evicts, so the tier would be inert)")
        if self.decode_horizon < 1:
            raise ValueError(
                f"decode_horizon must be >= 1, got {self.decode_horizon}")
        if self.speculative is not None:
            spec = self.speculative
            if isinstance(spec, dict):
                # Convenience for argv/JSON config paths.
                spec = SpeculativeConfig(**spec)
                object.__setattr__(self, "speculative", spec)
            if spec.draft_k < 1:
                raise ValueError(
                    f"speculative.draft_k must be >= 1, got "
                    f"{spec.draft_k}")
            if spec.draft_layers is not None and spec.draft_layers < 1:
                raise ValueError(
                    f"speculative.draft_layers must be >= 1 or None, "
                    f"got {spec.draft_layers}")
        if not 1 <= self.max_prefill_len <= self.max_len:
            raise ValueError(
                f"need 1 <= max_prefill_len <= max_len, got "
                f"{self.max_prefill_len} / {self.max_len}")
        if self.k_max < 1:
            raise ValueError("k_max must be >= 1")
        if self.queue_capacity < 1:
            raise ValueError("queue_capacity must be >= 1")
        if self.decode_impl not in (None, "auto", "kernel", "xla"):
            raise ValueError(
                f"decode_impl must be None, 'auto', 'kernel', or 'xla'; "
                f"got {self.decode_impl!r}")
        if self.prefill_impl not in (None, "auto", "kernel", "xla"):
            raise ValueError(
                f"prefill_impl must be None, 'auto', 'kernel', or 'xla'; "
                f"got {self.prefill_impl!r}")
        buckets = tuple(self.prefill_buckets) or default_prefill_buckets(
            self.max_prefill_len)
        if list(buckets) != sorted(set(buckets)):
            raise ValueError(
                f"prefill_buckets must be strictly increasing, got "
                f"{buckets}")
        if buckets[0] < 1 or buckets[-1] != self.max_prefill_len:
            # The last bucket IS the chunk width: every admissible tail
            # must fit some bucket, and chunking advances in
            # max_prefill_len strides.
            raise ValueError(
                f"prefill_buckets must be >= 1 and end exactly at "
                f"max_prefill_len={self.max_prefill_len}, got {buckets}")
        object.__setattr__(self, "prefill_buckets", buckets)
        if self.prefill_mode not in ("replicated", "sequence"):
            raise ValueError(
                f"prefill_mode must be 'replicated' or 'sequence', got "
                f"{self.prefill_mode!r}")
        if self.seq_prefill_variant not in ("auto", "ulysses", "ring"):
            raise ValueError(
                f"seq_prefill_variant must be 'auto', 'ulysses', or "
                f"'ring', got {self.seq_prefill_variant!r}")
        lb = tuple(self.long_prefill_buckets)
        if lb:
            if list(lb) != sorted(set(lb)):
                raise ValueError(
                    f"long_prefill_buckets must be strictly increasing, "
                    f"got {lb}")
            if lb[0] <= self.max_prefill_len or lb[-1] > self.max_len:
                raise ValueError(
                    f"long_prefill_buckets must lie in "
                    f"(max_prefill_len={self.max_prefill_len}, "
                    f"max_len={self.max_len}], got {lb}")
        object.__setattr__(self, "long_prefill_buckets", lb)
        if self.tenant_queue_cap is not None and self.tenant_queue_cap < 1:
            raise ValueError(
                f"tenant_queue_cap must be >= 1 or None, got "
                f"{self.tenant_queue_cap}")
        if self.preemption_budget < 0:
            raise ValueError(
                f"preemption_budget must be >= 0, got "
                f"{self.preemption_budget}")
        if self.priority_weights is not None:
            pw = self.priority_weights
            pairs = list(pw.items()) if isinstance(pw, dict) else list(pw)
            try:
                norm = {str(name): int(w) for name, w in pairs}
            except (TypeError, ValueError):
                raise ValueError(
                    f"priority_weights must map priority names to "
                    f"integer weights, got {pw!r}")
            classes = ("interactive", "batch", "background")
            if set(norm) != set(classes):
                raise ValueError(
                    f"priority_weights must name exactly "
                    f"{classes}, got {sorted(norm)}")
            if any(w < 1 for w in norm.values()):
                raise ValueError(
                    f"priority_weights must all be >= 1, got {norm}")
            object.__setattr__(self, "priority_weights",
                               tuple((c, norm[c]) for c in classes))


def self_draft(model, variables, num_layers: Optional[int] = None):
    """Build an early-exit SELF-DRAFT from the target: the same
    architecture truncated to its first ``num_layers`` transformer
    blocks (None = full depth), SHARING the target's embedding / trunk
    / final-norm weights — the no-second-checkpoint draft source
    ROADMAP item 3 names. -> ``(draft_model, draft_variables)``; the
    variables dict references the target's own leaves (no copy).
    Draft quality only moves the ACCEPT RATE — every emitted token is
    verified against the target, so a bad draft costs speed, never
    correctness."""
    cfg = model.cfg
    layers = cfg.num_layers if num_layers is None else int(num_layers)
    if not 1 <= layers <= cfg.num_layers:
        raise ValueError(
            f"draft_layers must be in [1, {cfg.num_layers}], got "
            f"{layers}")
    draft = type(model)(dataclasses.replace(cfg, num_layers=layers),
                        policy=model.policy)
    params = variables["params"]
    if cfg.scan_layers:
        dparams = {k: v for k, v in params.items() if k != "h_scan"}
        dparams["h_scan"] = jax.tree_util.tree_map(
            lambda p: p[:layers], params["h_scan"])
    else:
        dparams = {}
        for key, val in params.items():
            if key.startswith("h") and key[1:].isdigit():
                if int(key[1:]) < layers:
                    dparams[key] = val
            else:
                dparams[key] = val
    return draft, {"params": dparams, "state": variables.get("state", {})}


class Engine:
    """Device-side serving state + the frozen program set.

    The engine is deliberately request-blind: it knows slots, not
    requests. Admission policy, deadlines, retirement, and the
    request-level telemetry (TTFT/TPOT, queue depth, spans) live in the
    scheduler; the engine emits only what it alone can see — the
    bucket/chunk instruments (``serve.prefill.bucket_len`` /
    ``serve.prefill.chunks_total``), since the bucket choice is made
    here. The contract is ``prefill(slot, ...)`` to load one slot
    (however many chunks that takes — including the row's EOS id and
    new-token budget, which become device state) and ``step(active)``
    to decode one BLOCK of up to ``decode_horizon`` tokens for every
    row and hand the ``[B, H]`` batch back to the host along with
    per-row emitted counts. ``step_calls`` counts host dispatches of
    the step program — the denominator of the dispatch-per-token
    amortization this engine exists to improve.
    """

    # Whether this engine class can serve prefill_mode="sequence".
    # Only the mesh-sharded engine can — sequence sharding needs a
    # multi-device "tp" axis to spread the chunk over.
    _seq_prefill_capable = False

    def __init__(self, model, variables, cfg: ServeConfig = ServeConfig(),
                 draft_model=None, draft_variables=None):
        if cfg.max_len > model.cfg.max_positions:
            raise ValueError(
                f"max_len {cfg.max_len} exceeds the model's max_positions "
                f"{model.cfg.max_positions}")
        if (cfg.prefill_mode == "sequence"
                and not self._seq_prefill_capable):
            raise ValueError(
                "prefill_mode='sequence' requires the mesh-sharded "
                "engine (nezha-serve --mesh M with M > 1) — the "
                "single-device engine has no sequence axis to shard "
                "over")
        # The decode/prefill attention choices are model-config knobs
        # (the attention module reads them at trace time); honor the
        # serving overrides by rebuilding the module tree around a
        # replaced config — pure structure, the caller's ``variables``
        # slot straight in.
        impl_overrides = {}
        if (cfg.decode_impl is not None
                and cfg.decode_impl != model.cfg.decode_impl):
            impl_overrides["decode_impl"] = cfg.decode_impl
        if (cfg.prefill_impl is not None
                and cfg.prefill_impl != getattr(model.cfg, "prefill_impl",
                                                None)):
            impl_overrides["prefill_impl"] = cfg.prefill_impl
        if impl_overrides:
            model = type(model)(
                dataclasses.replace(model.cfg, **impl_overrides),
                policy=model.policy)
        self.model = model
        self.variables = variables
        self.cfg = cfg
        self.vocab = model.cfg.vocab_size
        self.k_max = min(cfg.k_max, self.vocab)
        self.paged = cfg.kv_layout == "paged"
        self.kv_quant = cfg.kv_dtype == "int8"
        # Resolve ONCE whether paged prefill chunks dispatch through the
        # flash-prefill kernel. models.gpt2 re-resolves at trace time
        # from the same knobs (config + env) — this mirror only drives
        # telemetry: the pinned ``serve.prefill.kernel_active`` gauge
        # lets dashboards and `nezha-telemetry` label the prefill line
        # with the active impl without scraping model config, and it
        # selects the kernel span / fused-write accounting in
        # :meth:`prefill`. Guarded: a model without the prefill knobs
        # (non-GPT2) simply reports the XLA path.
        try:
            from nezha_tpu.models.gpt2 import _prefill_flash_ok
            self.prefill_kernel_active = bool(
                self.paged and _prefill_flash_ok(model.cfg))
        except Exception:
            self.prefill_kernel_active = False
        obs.gauge("serve.prefill.kernel_active").set(
            1.0 if self.prefill_kernel_active else 0.0)
        if self.paged:
            self.pool = self._make_paged_pool(
                model, num_blocks=cfg.kv_num_blocks,
                prefix_cache=cfg.prefix_cache, eviction=cfg.kv_eviction,
                quantized=self.kv_quant,
                host_blocks=cfg.kv_host_blocks)
            # Host mirrors of each row's next write position and
            # remaining token budget (set at prefill, advanced/decayed
            # by the block's emitted count): the lazy block binder must
            # size the write window BEFORE a dispatch without a device
            # sync, and must not bind blocks a nearly-finished row can
            # never write.
            self.host_positions = np.zeros((cfg.max_batch_size,),
                                           np.int64)
            self.host_budgets = np.zeros((cfg.max_batch_size,),
                                         np.int64)
        else:
            self.pool = self._make_dense_pool(model)
        b = cfg.max_batch_size
        self.last_logits = jnp.zeros((b, self.vocab), jnp.float32)
        # [B] bool from the latest step: False where that row's logits
        # (carried-in or freshly produced) went non-finite — the
        # scheduler's signal to retire the row with FinishReason.ERROR.
        self.step_ok: Optional[np.ndarray] = None
        self.positions = jnp.zeros((b,), jnp.int32)
        self.keys = jnp.zeros((b, 2), jnp.uint32)
        self.temps = jnp.zeros((b,), jnp.float32)
        self.top_ks = jnp.zeros((b,), jnp.int32)
        self.top_ps = jnp.ones((b,), jnp.float32)
        # On-device completion state, set per row at prefill: the EOS id
        # (-1 = none) and the remaining new-token budget. Inside a decode
        # block a row that emits its EOS or exhausts its budget flips the
        # scan's carried `done` mask and stops sampling + K/V writes for
        # the rest of the block — the host never sees overshoot.
        self.eos_ids = jnp.full((b,), -1, jnp.int32)
        self.budgets = jnp.zeros((b,), jnp.int32)
        # Host dispatches of the step program (1 dispatch = up to
        # decode_horizon tokens for every row) — tests assert the
        # dispatch-per-token amortization against this.
        self.step_calls = 0
        # Tokens the most recent prefill's compiled chunks pushed
        # through the target model (set per prefill call), and how many
        # chunk dispatches it took (the sequence-sharded engine's
        # ring-hop accounting multiplies by this).
        self.last_prefill_tokens = 0
        self.last_prefill_chunks = 0
        # Donate the pooled caches (positional arg 1 in EVERY program):
        # without donation every decoded token would copy the whole
        # [B_max, H, L_max, D] K/V pool per layer just to write one row —
        # double the KV memory and a full-pool bandwidth tax on the
        # latency-bound loop. The engine rebinds the returned buffers
        # immediately, so the invalidated inputs are never reused.
        self.executor = Executor(donate_argnums=(1,))
        # One prefill program per bucket width — long-context buckets
        # included (compiled lazily: the executor keys on the function
        # object, so each closure is its own cache entry the first time
        # a prompt lands in its bucket). The paged variants take the
        # block tables as one extra operand — shapes are static, so the
        # "1 step + len(all_prefill_buckets) programs" contract is
        # layout-invariant. Prefill programs route through the
        # dedicated _wrap_prefill_program hook: the sharded engine in
        # sequence mode nests the seq-prefill scope around the trace.
        self._prefill_fns = {w: self._wrap_prefill_program(
                                    _build_prefill(self.model, w,
                                                   paged=self.paged,
                                                   quantized=self.kv_quant))
                             for w in cfg.all_prefill_buckets}
        # Speculative decoding: a DRAFT engine rides along — its own
        # model (explicit, or an early-exit self-draft sharing the
        # target's weights), its own KV pool MIRRORING the target
        # pool's slot lifecycle (same paged machinery, int8 included),
        # its own executor for the bucket prefill programs. The draft's
        # decode never dispatches separately: it lives inside the ONE
        # fused draft→verify→accept step program, so the frozen
        # program-count contract is counted per engine — target:
        # 1 step + len(prefill_buckets); draft: len(prefill_buckets).
        self.spec = cfg.speculative
        self.draft_model = None
        self.draft_variables = None
        self.draft_pool = None
        self.draft_executor = None
        if self.spec is not None:
            if draft_model is not None:
                dm, dv = draft_model, draft_variables
                if dv is None:
                    raise ValueError(
                        "draft_model requires draft_variables")
                if (cfg.decode_impl is not None
                        and cfg.decode_impl != dm.cfg.decode_impl):
                    dm = type(dm)(
                        dataclasses.replace(dm.cfg,
                                            decode_impl=cfg.decode_impl),
                        policy=dm.policy)
            else:
                dm, dv = self_draft(self.model, self.variables,
                                    self.spec.draft_layers)
            if dm.cfg.vocab_size != self.model.cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {dm.cfg.vocab_size} != target vocab "
                    f"{self.model.cfg.vocab_size} — the accept test "
                    f"compares distributions over one vocabulary")
            if cfg.max_len > dm.cfg.max_positions:
                raise ValueError(
                    f"max_len {cfg.max_len} exceeds the draft model's "
                    f"max_positions {dm.cfg.max_positions}")
            self.draft_model, self.draft_variables = dm, dv
            if self.paged:
                # Dense-equivalent block budget + no prefix cache: the
                # draft pool is bookkeeping-cheap (draft blocks are a
                # fraction of target bytes) and must NEVER be the
                # backpressure source — admission budgets are sized
                # against the target pool alone.
                self.draft_pool = self._make_paged_pool(
                    dm, num_blocks=None, prefix_cache=False,
                    eviction="none", quantized=self.kv_quant)
            else:
                self.draft_pool = self._make_dense_pool(dm)
            self.pool.mirror = self.draft_pool
            self.draft_executor = Executor(donate_argnums=(1,))
            self._draft_prefill_fns = {
                w: self._wrap_prefill_program(
                    _build_draft_prefill(dm, w, paged=self.paged))
                for w in cfg.all_prefill_buckets}
            # Carried residual-distribution flag: True where the row's
            # last_logits hold the rejection residual (already-filtered
            # log-probs — sampled raw, never re-filtered).
            self.residual = jnp.zeros((b,), bool)
            # Host ledgers for the bench record / acceptance gates.
            self.spec_verifies = 0
            self.spec_draft_tokens = 0
            self.spec_accepted = 0
            self._step_fn = self._wrap_program(_build_spec_step(
                self.model, dm, self.k_max, cfg.pad_id,
                cfg.decode_horizon, self.spec.draft_k, paged=self.paged))
        else:
            self._step_fn = self._wrap_program(
                _build_step(self.model, self.k_max, cfg.pad_id,
                            cfg.decode_horizon, paged=self.paged))

    # ----------------------------------------------- subsystem hooks
    # The tensor-sharded engine (serve/sharded/engine.py) specializes
    # the engine at a handful of seams — where pools are built and where
    # built programs are handed to the executor — so every other line
    # of the admission/decode machinery stays layout-blind. Single-
    # device serving goes through the identity versions below.
    def _make_paged_pool(self, model, *, num_blocks, prefix_cache,
                         eviction, quantized, host_blocks=0):
        """Paged-pool constructor hook (target AND draft pools route
        through here — the draft always passes ``host_blocks=0``: its
        pool keeps no prefix cache, so there is nothing to demote).
        Overridden by the sharded engine to lay the block pools out
        head-sharded across its mesh."""
        cfg = self.cfg
        return PagedSlotPool(
            model, cfg.max_batch_size, cfg.max_len, cfg.cache_dtype,
            block_size=cfg.kv_block_size, num_blocks=num_blocks,
            prefix_cache=prefix_cache, eviction=eviction,
            quantized=quantized, host_blocks=host_blocks)

    def _make_dense_pool(self, model):
        """Dense-pool constructor hook (see :meth:`_make_paged_pool`)."""
        cfg = self.cfg
        return SlotPool(model, cfg.max_batch_size, cfg.max_len,
                        cfg.cache_dtype)

    def _wrap_program(self, fn):
        """Program hook: every built prefill/step program passes through
        here before it reaches the executor. The sharded engine wraps
        the trace in ``auto_partitioner_scope(mesh)`` so model code
        sees the mesh (nested shard_map kernels, no Mosaic under the
        auto-partitioner); the identity keeps single-device dispatch
        byte-for-byte what it was."""
        return fn

    def _wrap_prefill_program(self, fn):
        """Prefill-program hook (target AND draft bucket programs):
        defaults to :meth:`_wrap_program`, so every engine keeps its
        existing wrapping. The sharded engine in
        ``prefill_mode="sequence"`` overrides this to ALSO enter the
        seq-prefill scope around the trace — the model's prefill-chunk
        branch then builds the nested sequence-sharded shard_map
        (serve/sharded/seq_prefill.py) while step/decode programs stay
        untouched."""
        return self._wrap_program(fn)

    # -------------------------------------------------------- host API
    def bucket_for(self, n: int) -> int:
        """The static pad width the TAIL chunk of an ``n``-token prompt
        runs at (the smallest bucket >= n for single-chunk prompts;
        with long buckets configured, possibly a pad-up long tail — see
        :meth:`_plan_chunks`). Benchmarks group TTFT by this value."""
        return self._plan_chunks(n)[-1][2]

    def _plan_chunks(self, n: int,
                     start: int = 0) -> List[Tuple[int, int, int]]:
        """Chunk plan for prefilling positions ``[start, n)`` of an
        ``n``-token prompt: ``(offset, real_len, pad_width)`` triples.
        Greedy largest-fit over ALL buckets: while the remainder
        exceeds ``max_prefill_len``, either pad UP into the smallest
        bucket covering the whole remainder (only when the pad waste is
        below one stride — an 8 001-token prompt takes one 8192-wide
        dispatch, a 100-token remainder never balloons to 8k) or stride
        by the largest bucket that fits (long buckets stride in big
        steps); then the classic bucketed tail. With
        ``long_prefill_buckets=()`` this reduces EXACTLY to the old
        plan: full ``max_prefill_len`` strides then a bucketed tail.
        With a shared-prefix ``start`` only the un-cached suffix is
        planned (partial-prefix prefill reuses the same bucket
        machinery). A padded tail that would spill past ``max_len``
        slides back over real tokens (rewriting positions recomputes
        identical K/V; the paged pool COWs any shared block the slide
        re-enters)."""
        cfg = self.cfg
        p_max = cfg.max_prefill_len
        buckets = cfg.all_prefill_buckets
        chunks: List[Tuple[int, int, int]] = []
        off = start
        width = None
        while n - off > p_max:
            rem = n - off
            up = [w for w in buckets if w >= rem]
            stride = max(w for w in buckets if w <= rem)
            if up and up[0] - rem < stride:
                # Pad-up tail: one wide dispatch covers the whole
                # remainder and wastes less than one more stride would
                # have advanced.
                width = up[0]
                break
            chunks.append((off, stride, stride))
            off += stride
        rem = n - off
        if width is None:
            width = next(w for w in buckets if w >= rem)
        if off + width > cfg.max_len:
            # A padded tail would spill past the slot's KV capacity
            # (max_len not a multiple of the stride, prompt near
            # capacity) — and dynamic_update_slice would CLAMP the write
            # start, corrupting the already-written prefix. Slide the
            # window back to cover the last `width` REAL tokens instead:
            # rewriting those positions recomputes identical K/V (same
            # tokens, same prefix), and no pad lands past capacity.
            # (off can dip below `start` here — with a shared prefix
            # the paged pool COWs the re-entered blocks, keeping the
            # cached copies intact.)
            off, rem = max(n - width, 0), min(width, n)
        chunks.append((off, rem, width))
        return chunks

    def prefill_span(self, n: int) -> int:
        """The highest position (exclusive) a cold prefill of an
        ``n``-token prompt writes, bucket pads included — what the
        scheduler's free-block admission budget is sized against."""
        off, _, width = self._plan_chunks(n)[-1]
        return max(off + width, n)

    def prefill_blocks_needed(self, n: int) -> int:
        """Worst-case (no prefix hit) block count an ``n``-token prompt
        binds at prefill. Paged layout only."""
        return self.pool.blocks_for_span(self.prefill_span(n))

    def prefill(self, slot: int, tokens: Sequence[int], *, seed: int = 0,
                temperature: float = 0.0, top_k: Optional[int] = None,
                top_p: Optional[float] = None,
                eos_id: Optional[int] = None,
                max_new_tokens: Optional[int] = None) -> None:
        """Load one request into ``slot``: prompt K/V, position, PRNG
        key, sampling params, and the row's on-device completion state
        (``eos_id``, ``None`` = never stop on a token; and its
        new-token budget, ``None`` = everything the slot's KV capacity
        allows). ``tokens`` may be up to ``max_len - 1`` long (room for
        at least one generated token); prompts wider than
        ``max_prefill_len`` run as successive chunks through the same
        bucket programs. On the paged layout the prompt's full-block
        prefix is first matched against the prefix cache — matched
        blocks are REFERENCED, not recomputed, and only the suffix
        prefills (``KVBlocksExhausted`` from binding is typed
        backpressure the scheduler absorbs). Token ids are NOT
        validated here — admission (``Scheduler.submit``) is the
        validation boundary. The first generated token comes from the
        next :meth:`step`."""
        faults.point("serve.prefill")
        n = len(tokens)
        if not 1 <= n < self.cfg.max_len:
            raise ValueError(
                f"prompt length {n} not in [1, max_len-1="
                f"{self.cfg.max_len - 1}]")
        # The device budget is what stops a row mid-block; capping it at
        # the slot's remaining KV capacity means a block can never write
        # past max_len even for budget-less direct engine callers.
        cap = self.cfg.max_len - n
        budget = cap if max_new_tokens is None else min(max_new_tokens,
                                                        cap)
        tokens = np.asarray(tokens, np.int32)
        start = 0
        if self.paged:
            # Prefix reuse: take references on cached blocks covering
            # the prompt's full-block prefix (capped at n-1 — the last
            # token always re-runs so its logits seed decoding), then
            # bind/COW everything the planned chunks will write. With a
            # host tier the bind also PROMOTES host-demoted blocks: the
            # async host->device scatter is dispatched inside this call
            # — ahead of every chunk dispatch below — so the partial-
            # prefix chunk programs start from the promoted span and
            # queue behind the copy on the device stream (dataflow
            # through pool.caches orders them; no host sync anywhere).
            start = self.pool.bind_for_prompt(slot, tokens.tolist())
        chunks = self._plan_chunks(n, start)
        if self.paged:
            try:
                self.pool.prepare_write(
                    slot, min(off for off, _, _ in chunks),
                    max(off + width for off, _, width in chunks))
            except KVBlocksExhausted:
                if start == 0:
                    raise
                # Tight-pool edge: the hit's own references pinned the
                # evictable blocks its copy-on-write then needed. Fall
                # back to a COLD prefill — releasing our references
                # makes those blocks reclaimable again, and admission
                # sized its budget for exactly this no-hit footprint.
                self.pool.release_blocks(slot)
                start = 0
                chunks = self._plan_chunks(n, 0)
                self.pool.prepare_write(
                    slot, 0,
                    max(off + width for off, _, width in chunks))
            if start > 0:
                # Count the hit only once its binding MATERIALIZED —
                # the cold fallback above must not inflate cache wins.
                self.pool.count_prefix_hit()
            self.host_positions[slot] = n
            self.host_budgets[slot] = budget
        obs.counter("serve.prefill.chunks_total").inc(len(chunks))
        # Re-pin per call, not just at init: benchmark harnesses reset
        # the registry after warmup, and the impl label must survive
        # into the measured run's summary.
        obs.gauge("serve.prefill.kernel_active").set(
            1.0 if self.prefill_kernel_active else 0.0)
        # Tokens the compiled chunks will actually push through the
        # target model: bucket pads included, a prefix hit's cached
        # span excluded (and a cold fallback's full re-plan included).
        # The sharded engine's collective-payload estimate reads this
        # after the call — prefill_span() would overcount hits.
        self.last_prefill_tokens = sum(w for _, _, w in chunks)
        self.last_prefill_chunks = len(chunks)
        qerrs: List[Any] = []
        for off, ln, width in chunks:
            obs.histogram("serve.prefill.bucket_len").observe(width)
            # Per-chunk trace fragment: recorded only when the scheduler
            # wrapped this prefill in the request's trace context (it
            # nests under the serve.prefill span), so the stitched
            # timeline shows which bucket/offset each chunk DISPATCHED
            # at — untraced requests pay one contextvar read per chunk.
            with obs.traced_span("serve.prefill.chunk", width=width,
                                 offset=off, tokens=ln):
                padded = np.zeros((1, width), np.int32)
                padded[0, :ln] = tokens[off:off + ln]
                scalars = (np.int32(ln), np.int32(slot), np.int32(off),
                           np.int32(seed), np.float32(temperature),
                           np.int32(0 if top_k is None else top_k),
                           np.float32(1.0 if top_p is None else top_p),
                           np.int32(-1 if eos_id is None else eos_id),
                           np.int32(budget))
                state = (self.last_logits, self.positions, self.keys,
                         self.temps, self.top_ks, self.top_ps,
                         self.eos_ids, self.budgets)
                if self.paged and self.prefill_kernel_active:
                    # Pinned kernel span: brackets the chunk's DISPATCH
                    # through the flash-prefill kernel program (async
                    # under jit — wall time covers Python dispatch plus
                    # any blocking first-trace compile, the executor's
                    # usual measurement idiom). On an int8 pool every
                    # layer fused its K and V block writes into the
                    # kernel epilogue instead of the gather/requant
                    # round-trip — count them so the fused-write rate
                    # is auditable against chunk throughput.
                    with obs.span("serve.prefill.kernel_s", width=width):
                        out = self.executor.run(
                            self._prefill_fns[width], self.variables,
                            self.pool.caches,
                            jnp.asarray(self.pool.tables_host),
                            jnp.asarray(padded), *scalars, *state)
                    if self.kv_quant:
                        obs.counter(
                            "serve.prefill.fused_writes_total").inc(
                            getattr(self.model.cfg, "num_layers", 1))
                elif self.paged:
                    out = self.executor.run(
                        self._prefill_fns[width], self.variables,
                        self.pool.caches,
                        jnp.asarray(self.pool.tables_host),
                        jnp.asarray(padded), *scalars, *state)
                else:
                    out = self.executor.run(
                        self._prefill_fns[width], self.variables,
                        self.pool.caches, jnp.asarray(padded),
                        *scalars, *state)
                if self.kv_quant:
                    # The quantized prefill program's extra output: this
                    # chunk's max-abs dequant error. Collect the DEVICE
                    # scalar now, read after every chunk has been
                    # dispatched — the histogram observe must not
                    # serialize chunk k+1's dispatch behind chunk k's
                    # completion.
                    out, err = out[:-1], out[-1]
                    qerrs.append(err)
                (self.pool.caches, self.last_logits, self.positions,
                 self.keys, self.temps, self.top_ks, self.top_ps,
                 self.eos_ids, self.budgets) = out
        if self.kv_quant:
            hist = obs.histogram("serve.kv.quant_error")
            for err in qerrs:
                hist.observe(float(err))
        if self.spec is not None:
            # Draft-side prefill: the draft cache must hold the SAME
            # prompt before the first draft chain runs. Always a cold
            # plan from 0 — the draft pool keeps no prefix cache, and a
            # target-side prefix hit says nothing about draft KV. An
            # exception here (genuine or injected) unwinds through the
            # scheduler's admission handler, which retires only this
            # request and frees the slot — the mirror releases the
            # draft pool's partial binds in the same free().
            dchunks = self._plan_chunks(n, 0)
            if self.paged:
                self.draft_pool.prepare_write(
                    slot, 0,
                    max(off + width for off, _, width in dchunks))
            for off, ln, width in dchunks:
                padded = np.zeros((1, width), np.int32)
                padded[0, :ln] = tokens[off:off + ln]
                dscalars = (np.int32(ln), np.int32(slot), np.int32(off))
                if self.paged:
                    self.draft_pool.caches = self.draft_executor.run(
                        self._draft_prefill_fns[width],
                        self.draft_variables, self.draft_pool.caches,
                        jnp.asarray(self.draft_pool.tables_host),
                        jnp.asarray(padded), *dscalars)
                else:
                    self.draft_pool.caches = self.draft_executor.run(
                        self._draft_prefill_fns[width],
                        self.draft_variables, self.draft_pool.caches,
                        jnp.asarray(padded), *dscalars)
            # Fresh request: its carried logits are real target logits,
            # not a residual distribution.
            self.residual = self.residual.at[slot].set(False)
        if self.paged:
            # Index this prompt's full blocks for future prefix hits
            # (the trie takes its own references — the cache outlives
            # this request's slot).
            self.pool.register_prefix(slot, tokens.tolist())
        if faults.enabled():
            self.last_logits = faults.corrupt(
                "serve.prefill.logits", self.last_logits, rows=(slot,))

    def _bind_decode_windows(self, active: np.ndarray, cap: int,
                             pools) -> None:
        """Lazy binding (paged layout): make every active row's write
        window for this block — ``[pos, pos + min(cap, budget))``,
        clamped to capacity — exclusively owned in each of ``pools``
        BEFORE the dispatch. The bound is what the row can actually
        EMIT: once done (or for a degenerate budget-0 row) its
        non-emitting writes route to the scratch block, so nothing
        past the budget needs binding — a row one token from finishing
        must never be retired for blocks it would never write. A bind
        that finds no block (genuine exhaustion or an injected
        serve.kv.bind fault) surfaces as the typed KVBlocksExhausted
        carrying the victim slot — the scheduler retires that one
        request and redials; the batch never crashes."""
        for slot in np.flatnonzero(np.asarray(active, bool)):
            pos_h = int(self.host_positions[slot])
            need = min(cap, max(int(self.host_budgets[slot]), 0))
            if need == 0:
                continue
            start = min(pos_h, self.cfg.max_len - 1)
            end = max(min(pos_h + need, self.cfg.max_len), start + 1)
            try:
                for pool in pools:
                    pool.prepare_write(int(slot), start, end)
            except faults.InjectedFault as e:
                raise KVBlocksExhausted(str(e), slot=int(slot)) from e

    def step(self, active: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Decode one BLOCK of up to ``decode_horizon`` tokens for every
        row; ``active`` is a ``[B_max]`` bool mask. Returns
        ``(tokens, emitted)`` as host arrays: ``tokens`` is the
        ``[B_max, H]`` block — a row's valid tokens are
        ``tokens[r, :emitted[r]]``; everything past its count (overshoot
        after EOS / budget / a mid-block NaN freeze, or all H columns of
        an inactive row) is pad and must be ignored. After the call
        :attr:`step_ok` holds a ``[B_max]`` bool health mask: False
        where a row's logits went non-finite at any scan step (only
        meaningful for rows the caller knows are active) — such a row's
        pre-burst tokens are still counted in ``emitted``."""
        faults.point("serve.step")
        self.step_calls += 1
        if self.spec is not None:
            return self._spec_step(active)
        if self.paged:
            self._bind_decode_windows(active, self.cfg.decode_horizon,
                                      (self.pool,))
            out = self.executor.run(
                self._step_fn, self.variables, self.pool.caches,
                jnp.asarray(self.pool.tables_host),
                self.last_logits, self.positions,
                jnp.asarray(active, bool), self.keys,
                self.temps, self.top_ks, self.top_ps,
                self.eos_ids, self.budgets)
        else:
            out = self.executor.run(
                self._step_fn, self.variables, self.pool.caches,
                self.last_logits, self.positions,
                jnp.asarray(active, bool), self.keys,
                self.temps, self.top_ks, self.top_ps,
                self.eos_ids, self.budgets)
        tok, emitted, ok, caches, last, pos, keys, budgets = out
        # Start the block's device->host transfers NOW, before any host
        # bookkeeping (state rebinds here, retire/admit/stream in the
        # scheduler): the np.asarray reads below then find bytes already
        # in flight instead of paying the full sync serially.
        for arr in (tok, emitted, ok):
            copy_async = getattr(arr, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        self.pool.caches = caches
        if faults.enabled():
            last = faults.corrupt(
                "serve.step.logits", last,
                rows=lambda: np.flatnonzero(active))
        self.last_logits, self.positions, self.keys = last, pos, keys
        self.budgets = budgets
        self.step_ok = np.asarray(ok)
        tok_h, emitted_h = np.asarray(tok), np.asarray(emitted)
        if self.paged:
            # Advance the host position/budget mirrors by the block's
            # emitted counts (positions advance and budgets decay on
            # device exactly once per emitted token; a NaN-frozen row
            # may lag by one — it is retired this iteration, so its
            # window is never grown).
            self.host_positions += emitted_h.astype(np.int64)
            self.host_budgets -= emitted_h.astype(np.int64)
        return tok_h, emitted_h

    def _spec_step(self, active: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray]:
        """The speculative decode block (``step()`` dispatches here
        when ``cfg.speculative`` is set): one compiled program runs
        ``decode_horizon`` draft→verify→accept windows and returns the
        SAME ``(tokens, emitted)`` contract as the classic step — the
        emitted tokens are compacted to a left-aligned prefix of the
        ``[B, H*(k+1)]`` block, so the scheduler's slice-at-emitted
        consumption path is unchanged."""
        k = self.spec.draft_k
        cap = self.cfg.decode_horizon * (k + 1)
        if self.paged:
            # Both pools bind the same window: verify/draft writes past
            # it are garbage by construction and route to the scratch
            # block through the unbound table tail.
            self._bind_decode_windows(active, cap,
                                      (self.pool, self.draft_pool))
            out = self.executor.run(
                self._step_fn, self.variables,
                (self.pool.caches, self.draft_pool.caches),
                self.draft_variables,
                jnp.asarray(self.pool.tables_host),
                jnp.asarray(self.draft_pool.tables_host),
                self.last_logits, self.positions,
                jnp.asarray(active, bool), self.keys,
                self.temps, self.top_ks, self.top_ps,
                self.eos_ids, self.budgets, self.residual)
        else:
            out = self.executor.run(
                self._step_fn, self.variables,
                (self.pool.caches, self.draft_pool.caches),
                self.draft_variables,
                self.last_logits, self.positions,
                jnp.asarray(active, bool), self.keys,
                self.temps, self.top_ks, self.top_ps,
                self.eos_ids, self.budgets, self.residual)
        (tok, emitted, ok, win_emitted, caches_all, last, pos, keys,
         budgets, residual) = out
        for arr in (tok, emitted, ok, win_emitted):
            copy_async = getattr(arr, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        self.pool.caches, self.draft_pool.caches = caches_all
        if faults.enabled():
            # The pinned verify-step fault point: a nan/inf rule
            # poisons one active row's carried logits, which the next
            # dispatch's in-program tripwire converts into a
            # victim-only retirement (FinishReason.ERROR, zero leaks);
            # an error rule raises typed InjectedFault into the
            # scheduler's bounded-retry envelope.
            last = faults.corrupt(
                "serve.spec.verify", last,
                rows=lambda: np.flatnonzero(active))
        self.last_logits, self.positions, self.keys = last, pos, keys
        self.budgets, self.residual = budgets, residual
        self.step_ok = np.asarray(ok)
        tok_h, emitted_h = np.asarray(tok), np.asarray(emitted)
        win_h = np.asarray(win_emitted)
        # Speculation ledger: every window that emitted >= 1 token ran
        # one verify forward; its accepted-prefix length is (e_w - 1)
        # draft tokens (the t0 column is the classic carried-logits
        # sample, always exact). The drafted denominator charges all k
        # proposals per verify even when EOS/budget truncation made
        # some positions unacceptable — on short-completion loads the
        # reported accept_rate therefore UNDERSTATES draft fidelity
        # (tokens_per_verify, the headline, is unaffected: it counts
        # what was actually emitted per dispatch paid).
        ws = win_h[np.asarray(active, bool)]
        ran = ws[ws > 0]
        if ran.size:
            verifies = int(ran.size)
            accepted = int((ran - 1).sum())
            self.spec_verifies += verifies
            self.spec_draft_tokens += verifies * k
            self.spec_accepted += accepted
            obs.counter("serve.spec.draft_tokens_total").inc(
                verifies * k)
            obs.counter("serve.spec.accepted_total").inc(accepted)
            hist = obs.histogram("serve.spec.accepted_len")
            for v in (ran - 1).tolist():
                hist.observe(v)
        if self.paged:
            self.host_positions += emitted_h.astype(np.int64)
            self.host_budgets -= emitted_h.astype(np.int64)
        return tok_h, emitted_h

    @property
    def tokens_per_dispatch(self) -> int:
        """Ceiling on tokens one step dispatch can emit:
        ``decode_horizon`` windows of ``1 + draft_k`` tokens each
        (``decode_horizon`` exactly when speculative is off) — the
        value the ``serve.decode.horizon`` histogram observes."""
        h = self.cfg.decode_horizon
        return h * (1 + self.spec.draft_k) if self.spec else h

    def compile_stats(self) -> dict:
        """Executor cache stats — steady state is ``entries ==
        1 + len(prefill_buckets)`` (step + one prefill per bucket),
        misses frozen there after every bucket has been warmed while
        hits grow. Speculative mode keeps the SAME count: the
        draft→verify→accept loop is baked into the one step program
        (the draft engine's own bucket prefills are counted separately
        — :meth:`draft_compile_stats`)."""
        return self.executor.stats()

    def draft_compile_stats(self) -> Optional[dict]:
        """Draft-engine executor stats (None when speculative is off):
        steady state is ``entries == len(prefill_buckets)`` — the
        draft's bucket prefill programs; its decode never dispatches
        on its own."""
        return (self.draft_executor.stats()
                if self.draft_executor is not None else None)


def _build_prefill(model, width: int, paged: bool = False,
                   quantized: bool = False):
    def core(variables, caches, tables, tokens, length, slot, pos,
             seed, temperature, top_k, top_p, eos_id, budget,
             last_logits, positions, keys, temps, top_ks, top_ps,
             eos_ids, budgets):
        # One prompt chunk, padded to this bucket's static `width`, runs
        # against the slot's own cache storage at a traced offset: the
        # masked attention path sees the prefix earlier chunks wrote
        # (pos > 0) or nothing (pos == 0), so the same program serves
        # first chunks, middle chunks, and bucketed tails. Rows past
        # `length` are pad — their K/V lands above the prompt and is
        # overwritten by decode before any mask attends it. Dense: the
        # slot's pooled rows are sliced out (read_slot) and written
        # back (write_slot). Paged: the chunk runs against the slot's
        # TABLE ROW (one [1, M] slice of the uploaded tables) — the
        # model scatters K/V through it into the shared block pools and
        # attends the gathered prefix, so a shared-prefix request
        # starting at a nonzero `pos` sees the cached blocks it
        # referenced instead of recomputing them.
        if paged:
            zero = jnp.zeros((), jnp.int32)
            tab_row = lax.dynamic_slice(
                tables, (slot, zero), (1, tables.shape[1]))
            # Dict-merge keeps every pool leaf (int8 pools carry
            # k_scale/v_scale rows alongside k/v) riding into the model
            # and back out — the scales are cache state like any other.
            rows = [{**pool, "tables": tab_row} for pool in caches]
        else:
            rows = [{"k": read_slot(pool["k"], slot),
                     "v": read_slot(pool["v"], slot)}
                    for pool in caches]
        logits, states = model.apply(variables, tokens, training=False,
                                     cache=rows, pos=pos)
        new_rows = _caches_from_states(model, states, rows)
        if paged:
            keys_kept = tuple(caches[0].keys())
            new_caches = [{kk: r[kk] for kk in keys_kept}
                          for r in new_rows]
            qerr = None
            if quantized:
                # Max-abs dequant error across layers (each attention
                # write reported its chunk's error) — returned as one
                # extra scalar output the engine host-observes into
                # serve.kv.quant_error.
                errs = [r["qerr"] for r in new_rows if "qerr" in r]
                qerr = jnp.max(jnp.stack(errs)) if errs \
                    else jnp.zeros((), jnp.float32)
        else:
            new_caches = [
                {"k": write_slot(pool["k"], rk["k"], slot),
                 "v": write_slot(pool["v"], rk["v"], slot)}
                for pool, rk in zip(caches, new_rows)]
        row = lax.dynamic_slice(
            logits, (0, length - 1, jnp.zeros((), jnp.int32)),
            (1, 1, logits.shape[-1]))[:, 0, :]          # [1, V] last REAL row
        key = jax.random.PRNGKey(seed).astype(keys.dtype)

        def set_row(buf, val):
            return lax.dynamic_update_slice(
                buf, jnp.asarray(val, buf.dtype).reshape(
                    (1,) + buf.shape[1:]),
                (slot,) + (jnp.zeros((), jnp.int32),) * (buf.ndim - 1))

        # Every chunk overwrites the whole per-slot state; only the final
        # chunk's values survive to decode (positions advances to the
        # running prefix length either way).
        out = (new_caches,
               set_row(last_logits, row),
               set_row(positions, pos + length),
               set_row(keys, key),
               set_row(temps, temperature),
               set_row(top_ks, top_k),
               set_row(top_ps, top_p),
               set_row(eos_ids, eos_id),
               set_row(budgets, budget))
        if paged and quantized:
            return out + (qerr,)
        return out

    # One source for both layouts; only the operand list differs (the
    # paged variant takes the uploaded block tables after the caches).
    if paged:
        def prefill(variables, caches, tables, tokens, *rest):
            return core(variables, caches, tables, tokens, *rest)
    else:
        def prefill(variables, caches, tokens, *rest):
            return core(variables, caches, None, tokens, *rest)

    return prefill


def _build_step(model, k_max: int, pad_id: int, horizon: int,
                paged: bool = False):
    def body(active, temps, top_ks, top_ps, eos_ids, budgets,
             variables, tables, carry):
        """One fused decode step: the single-token body the horizon scan
        iterates. Everything request-terminating happens on device:

        - ``ok`` is the carried health mask (PR 4's NaN/inf tripwire),
          ANDed per step against the carried-in logits BEFORE sampling
          (a burst that landed between steps makes this step's sample
          garbage — never emit it) and against the fresh row AFTER the
          forward pass (matching the classic step's conservative
          discard). A row that trips freezes from that step on.
        - ``done`` flips when a row emits its EOS id or fills its
          remaining budget; ``emitted`` counts only genuinely emitted
          tokens, so the host can slice each row's valid prefix out of
          the block.
        - ``emit = active ∧ ¬done ∧ ok`` is the mask that threads into
          the model as ``active``: the flash-decode kernel zeroes
          non-emitting rows' lengths and skips their KV blocks, so a
          finished/frozen row stops writing K/V mid-block (the composed
          fallback ignores it; garbage rows are masked below either
          way). Keys advance only on emit — a request's RNG stream is a
          function of (seed, emitted count), horizon-invariant.

        ONE source for both KV layouts: with ``paged`` the per-slot
        block tables thread into each layer's cache dict — the model
        scatters emitted tokens' K/V through them (non-emitting rows
        write the scratch block) and the flash-decode kernel gathers KV
        blocks via the table with the per-row length skip intact.
        """
        caches, last_logits, positions, keys, done, ok, emitted = carry
        ok = ok & finite_rows(last_logits)
        # (emitted < budgets) is redundant with the done flip below for
        # every block the scheduler dispatches (live rows always carry
        # budget >= 1) — it guards the degenerate budget-0 row a direct
        # engine caller could create, which must emit nothing.
        emit = active & ~done & ok & (emitted < budgets)
        next_keys, tok = split_and_sample(keys, last_logits, temps,
                                          top_ks, top_ps, k_max)
        tok = jnp.where(emit, tok, pad_id)
        if paged:
            # Dict-merge: int8 pools' k_scale/v_scale leaves thread
            # through with k/v (the model's quantized write returns
            # updated scale buffers the scan must carry).
            rows = [{**c, "tables": tables} for c in caches]
        else:
            rows = caches
        logits, states = model.apply(variables, tok[:, None],
                                     training=False, cache=rows,
                                     pos=positions, active=emit)
        new_rows = _caches_from_states(model, states, rows)
        if paged:
            keys_kept = tuple(caches[0].keys())
            new_caches = [{kk: r[kk] for kk in keys_kept}
                          for r in new_rows]
        else:
            new_caches = new_rows
        row_logits = logits[:, -1, :]
        ok = jnp.where(emit, ok & finite_rows(row_logits), ok)
        counted = emit & ok
        emitted = emitted + counted.astype(jnp.int32)
        done = done | (counted & (eos_ids >= 0) & (tok == eos_ids)) \
                    | (counted & (emitted >= budgets))
        act = emit[:, None]
        return (new_caches,
                jnp.where(act, row_logits, last_logits),
                jnp.where(emit, positions + 1, positions),
                jnp.where(act, next_keys, keys),
                done, ok, emitted), tok

    def core(variables, caches, tables, last_logits, positions, active,
             keys, temps, top_ks, top_ps, eos_ids, budgets):
        b = positions.shape[0]
        init = (caches, last_logits, positions, keys,
                jnp.zeros((b,), bool),        # done (within this block)
                jnp.ones((b,), bool),         # ok   (health, carried)
                jnp.zeros((b,), jnp.int32))   # emitted (within block)

        def scan_body(carry, _):
            return body(active, temps, top_ks, top_ps, eos_ids, budgets,
                        variables, tables, carry)

        if horizon == 1:
            # Inline, not a length-1 scan: the default must stay
            # bit-identical to the classic single-token step program.
            carry, tok = scan_body(init, None)
            tok_block = tok[:, None]
        else:
            carry, toks = lax.scan(scan_body, init, None, length=horizon)
            tok_block = jnp.transpose(toks, (1, 0))        # [H,B]->[B,H]
        caches, last_logits, positions, keys, done, ok, emitted = carry
        return (tok_block, emitted, ok, caches, last_logits, positions,
                keys, jnp.maximum(budgets - emitted, 0))

    if paged:
        def step(variables, caches, tables, *rest):
            return core(variables, caches, tables, *rest)
    else:
        def step(variables, caches, *rest):
            return core(variables, caches, None, *rest)

    return step


def _build_draft_prefill(model, width: int, paged: bool = False):
    """The draft engine's bucket prefill: the same chunk-at-traced-
    offset move as the target's (:func:`_build_prefill`) minus all
    sampling/completion state — the draft only needs its KV loaded.
    Logits are discarded; a quantized pool's per-chunk ``qerr`` is
    dropped with the dict re-filter (draft quant error is not a
    serving metric — the accept test measures draft fidelity end to
    end)."""
    def core(variables, caches, tables, tokens, length, slot, pos):
        del length
        if paged:
            zero = jnp.zeros((), jnp.int32)
            tab_row = lax.dynamic_slice(
                tables, (slot, zero), (1, tables.shape[1]))
            rows = [{**pool, "tables": tab_row} for pool in caches]
        else:
            rows = [{"k": read_slot(pool["k"], slot),
                     "v": read_slot(pool["v"], slot)}
                    for pool in caches]
        _, states = model.apply(variables, tokens, training=False,
                                cache=rows, pos=pos)
        new_rows = _caches_from_states(model, states, rows)
        if paged:
            kept = tuple(caches[0].keys())
            return [{kk: r[kk] for kk in kept} for r in new_rows]
        return [{"k": write_slot(pool["k"], rk["k"], slot),
                 "v": write_slot(pool["v"], rk["v"], slot)}
                for pool, rk in zip(caches, new_rows)]

    if paged:
        def prefill(variables, caches, tables, tokens, *rest):
            return core(variables, caches, tables, tokens, *rest)
    else:
        def prefill(variables, caches, tokens, *rest):
            return core(variables, caches, None, tokens, *rest)

    return prefill


def _build_spec_step(model, draft_model, k_max: int, pad_id: int,
                     horizon: int, draft_k: int, paged: bool = False):
    """The fused speculative step: ONE compiled program scanning
    ``horizon`` draft→verify→accept windows, device-resident end to
    end. Each window:

    1. samples ``t0`` from the carried target logits — exactly the
       classic step's move (or, after a rejection, a raw categorical
       from the carried RESIDUAL logits — the deferred rejection
       resample of lossless speculative sampling);
    2. runs ``draft_k + 1`` single-token draft forwards (a ``lax.scan``
       chain feeding sampled proposals), collecting the k proposals and
       the filtered draft distributions each was drawn from — the last
       forward only keeps the draft cache complete for the
       all-accepted case;
    3. runs ONE ``draft_k + 1``-wide target forward over
       ``[t0, d_1..d_k]`` at per-row traced positions (the
       models/gpt2.py verify-window write path: per-position scatter,
       overshoot and non-emitting rows routed to scratch/drop);
    4. accepts the longest agreeing prefix in-program
       (serve/sampling.py accept_mask: greedy exact-match, sampled
       ``u·q <= p``), cuts it at EOS / budget / a non-finite verify
       row, emits ``e ∈ [0, k+1]`` tokens, advances positions and the
       per-row PRNG key by exactly ``e`` split steps (the carried key
       stream stays a function of (seed, emitted count) — spec outputs
       are horizon-invariant, and greedy rows are bit-identical to the
       classic engine), and carries either the next plain target
       logits (``P[e-1]``) or the rejection residual.

    The carried done/ok masks freeze rows mid-horizon exactly as the
    classic scan does; rejected/overshoot columns never reach the host
    — the program compacts each row's emitted tokens to a left-aligned
    prefix of the ``[B, horizon*(k+1)]`` block and returns per-window
    emitted counts for the acceptance histogram."""
    k = draft_k
    w = k + 1

    def window(active, temps, top_ks, top_ps, eos_ids, budgets,
               variables, dvariables, tables, dtables, carry):
        (caches, dcaches, last_logits, positions, keys, done, ok,
         emitted, residual) = carry
        b = positions.shape[0]
        ok = ok & finite_rows(last_logits)
        emit0 = active & ~done & ok & (emitted < budgets)
        greedy = temps <= 0.0
        splits = jax.vmap(lambda kk: jax.random.split(kk, 2))(keys)
        sub0 = splits[:, 1]
        # t0: the classic carried-logits sample — the same key the
        # classic engine would use at this emitted count, so sampled
        # spec streams stay aligned with the classic stream at every
        # window boundary. Residual rows draw a RAW categorical: their
        # carried logits are already-filtered log-probs.
        t_cls = sample_tokens(last_logits, sub0, temps, top_ks, top_ps,
                              k_max)
        t_res = categorical_rows(sub0, last_logits)
        t0 = jnp.where(residual, t_res, t_cls)
        t0 = jnp.where(emit0, t0, pad_id).astype(jnp.int32)

        def dstep(c, j):
            dc, tok_in = c
            if paged:
                rows = [{**cc, "tables": dtables} for cc in dc]
            else:
                rows = dc
            dlog, dstates = draft_model.apply(
                dvariables, tok_in[:, None], training=False,
                cache=rows, pos=positions + j, active=emit0)
            new_rows = _caches_from_states(draft_model, dstates, rows)
            if paged:
                kept = tuple(dc[0].keys())
                dc2 = [{kk: r[kk] for kk in kept} for r in new_rows]
            else:
                dc2 = new_rows
            row = dlog[:, -1, :]
            # The draft proposes from the row's FILTERED distribution
            # (same temperature/top-k/top-p as the target side): the
            # rejection law is lossless for any proposal q, but a
            # proposal outside the target's truncated support has
            # p = 0 and always rejects — matching the support is what
            # keeps sampled accept rates near the draft's actual
            # fidelity.
            fl = filter_logits(row, temps, top_ks, top_ps, k_max)
            dkey = jax.vmap(
                lambda kk: jax.random.fold_in(kk, 1 + j))(keys)
            d = jnp.where(greedy, jnp.argmax(row, axis=-1),
                          categorical_rows(dkey, fl)).astype(jnp.int32)
            d = jnp.where(emit0, d, pad_id)
            return (dc2, d), (d, jax.nn.softmax(fl, axis=-1))

        (dcaches, _), (d_all, q_all) = lax.scan(
            dstep, (dcaches, t0), jnp.arange(w))
        win = jnp.concatenate(
            [t0[:, None], jnp.transpose(d_all[:k], (1, 0))], axis=1)

        if paged:
            vrows = [{**cc, "tables": tables} for cc in caches]
        else:
            vrows = caches
        vlog, vstates = model.apply(variables, win, training=False,
                                    cache=vrows, pos=positions,
                                    active=emit0)
        new_rows = _caches_from_states(model, vstates, vrows)
        if paged:
            kept = tuple(caches[0].keys())
            new_caches = [{kk: r[kk] for kk in kept} for r in new_rows]
        else:
            new_caches = new_rows
        # Health: the whole verify window must be finite — a poisoned
        # window emits NOTHING (the conservative discard of the classic
        # step at window granularity); pre-window tokens were already
        # delivered, and the carried ok=False retires the row.
        okrow = jnp.isfinite(vlog).all(axis=(1, 2))
        ok = jnp.where(emit0, ok & okrow, ok)

        tmax = jnp.argmax(vlog, axis=-1).astype(jnp.int32)    # [B, w]
        pf = jax.vmap(
            lambda l: filtered_probs(l, temps, top_ks, top_ps, k_max),
            in_axes=1, out_axes=1)(vlog[:, :k, :])            # [B, k, V]
        qf = jnp.transpose(q_all[:k], (1, 0, 2))              # [B, k, V]
        u = jax.vmap(lambda kk: jax.random.uniform(
            jax.random.fold_in(kk, w + 1), (k,)))(keys)       # [B, k]
        acc = accept_mask(win[:, 1:], pf, qf, u, greedy, tmax[:, :k])

        jidx = jnp.arange(w)
        acc_full = jnp.concatenate([jnp.ones((b, 1), bool), acc],
                                   axis=1)                    # [B, w]
        acc_prefix = jnp.cumprod(acc_full.astype(jnp.int32),
                                 axis=1).astype(bool)
        is_eos = (eos_ids >= 0)[:, None] & (win == eos_ids[:, None])
        no_prior_eos = (jnp.cumsum(is_eos.astype(jnp.int32), axis=1)
                        - is_eos.astype(jnp.int32)) == 0
        within_budget = (emitted[:, None] + jidx[None, :]
                         < budgets[:, None])
        emit_w = ((emit0 & okrow)[:, None] & acc_prefix
                  & no_prior_eos & within_budget)             # [B, w]
        e = emit_w.sum(axis=1).astype(jnp.int32)
        tok_out = jnp.where(emit_w, win, pad_id)
        emitted_new = emitted + e
        done = done | (emit_w & is_eos).any(axis=1) \
            | (emit0 & okrow & (emitted_new >= budgets))

        # Carried distribution for the next window: the plain target
        # logits after the last emitted token — or, when the stop was a
        # REJECTION (sampled rows only), the residual norm(max(p-q, 0))
        # in log space, flagged so the next t0 samples it raw.
        e1 = jnp.clip(e, 1, w)
        sel = jnp.take_along_axis(vlog, (e1 - 1)[:, None, None],
                                  axis=1)[:, 0, :]
        stop = jnp.minimum(e, w - 1)
        gat = lambda m: jnp.take_along_axis(m, stop[:, None],
                                            axis=1)[:, 0]
        rej = (emit0 & okrow & (e < w) & ~greedy & gat(no_prior_eos)
               & gat(within_budget) & ~gat(acc_full))
        ek = jnp.clip(e, 1, k)
        pf_e = jnp.take_along_axis(pf, (ek - 1)[:, None, None],
                                   axis=1)[:, 0, :]
        qf_e = jnp.take_along_axis(qf, (ek - 1)[:, None, None],
                                   axis=1)[:, 0, :]
        rlog = residual_logits(pf_e, qf_e)
        upd = emit0 & okrow
        last_new = jnp.where(upd[:, None],
                             jnp.where(rej[:, None], rlog, sel),
                             last_logits)
        residual_new = jnp.where(upd, rej, residual)

        # Keys advance by exactly e split steps — the classic
        # one-split-per-emit chain, so the carried stream is a function
        # of (seed, emitted count) alone.
        def adv(kk, j):
            nxt = jax.vmap(lambda key: jax.random.split(key, 2)[0])(kk)
            return jnp.where((j < e)[:, None], nxt, kk), None

        keys_new, _ = lax.scan(adv, keys, jnp.arange(w))

        return ((new_caches, dcaches, last_new, positions + e, keys_new,
                 done, ok, emitted_new, residual_new),
                (tok_out, emit_w, e))

    def core(variables, caches_all, dvariables, tables, dtables,
             last_logits, positions, active, keys, temps, top_ks,
             top_ps, eos_ids, budgets, residual):
        caches, dcaches = caches_all
        b = positions.shape[0]
        init = (caches, dcaches, last_logits, positions, keys,
                jnp.zeros((b,), bool),        # done (within this block)
                jnp.ones((b,), bool),         # ok   (health, carried)
                jnp.zeros((b,), jnp.int32),   # emitted (within block)
                residual)

        def scan_body(carry, _):
            return window(active, temps, top_ks, top_ps, eos_ids,
                          budgets, variables, dvariables, tables,
                          dtables, carry)

        if horizon == 1:
            carry, (tok_w, emit_m, e_w) = scan_body(init, None)
            toks = tok_w[:, None, :]
            mask = emit_m[:, None, :]
            win_emitted = e_w[:, None]
        else:
            carry, (tok_s, emit_s, e_s) = lax.scan(scan_body, init,
                                                   None, length=horizon)
            toks = jnp.transpose(tok_s, (1, 0, 2))     # [B, H, w]
            mask = jnp.transpose(emit_s, (1, 0, 2))
            win_emitted = jnp.transpose(e_s, (1, 0))   # [B, H]
        (caches, dcaches, last_logits, positions, keys, done, ok,
         emitted, residual) = carry
        width = horizon * w
        tok_flat = toks.reshape(b, width)
        mask_flat = mask.reshape(b, width)
        # Compact each row's emitted tokens to a left-aligned prefix
        # (stable: emission order preserved) so the scheduler's
        # slice-at-emitted consumption works unchanged; everything past
        # a row's count is pad (masked to pad_id before the sort, so
        # the unemitted tail lands as pad already left-aligned).
        order = jnp.argsort(
            jnp.logical_not(mask_flat).astype(jnp.int32), axis=1,
            stable=True)
        tok_block = jnp.take_along_axis(
            jnp.where(mask_flat, tok_flat, pad_id), order, axis=1)
        return (tok_block, emitted, ok, win_emitted,
                (caches, dcaches), last_logits, positions, keys,
                jnp.maximum(budgets - emitted, 0), residual)

    if paged:
        def spec_step(variables, caches_all, dvariables, tables,
                      dtables, *rest):
            return core(variables, caches_all, dvariables, tables,
                        dtables, *rest)
    else:
        def spec_step(variables, caches_all, dvariables, *rest):
            return core(variables, caches_all, dvariables, None, None,
                        *rest)

    return spec_step
