"""The continuous-batching engine: a frozen set of programs, reused forever.

Steady-state serving is exactly ``1 + len(prefill_buckets)`` XLA
programs regardless of request mix — the property that keeps TPU serving
latency flat:

- **prefill** — one compiled program per PREFILL BUCKET (static prompt
  pad widths, default powers of two up to ``max_prefill_len``). A
  prompt's tokens are padded to the smallest bucket that fits, the
  slot's pooled cache rows are sliced out (``read_slot``), the chunk
  runs through the model at its TRACED position offset via the masked
  attention path (which attends everything previously written to the
  slot), and the updated rows are written back (``write_slot``).
  Prompts longer than ``max_prefill_len`` are no longer rejected: they
  prefill in successive chunks — full ``max_prefill_len``-wide chunks,
  then a bucketed tail — reusing the same bucket programs at advancing
  offsets, so CHUNKING ADDS NO PROGRAMS. Bucket pads beyond the prompt
  write garbage K/V that is never attended (the masks stop at the
  written prefix, and decode overwrites pad positions before its mask
  reaches them). The traced offset is the trade the chunk contract
  buys: a traced ``pos`` cannot take the static-pos-0 flash-prefill
  path, so chunk attention is masked-dense over the slot's ``L_max``
  rows — paid once per request, versus the per-token decode win; a
  diagonal-offset flash prefill kernel would recover it without
  touching the program count and is the obvious next kernel.
- **step** — one batched decode BLOCK over all ``B_max`` rows: a
  ``lax.scan`` of ``decode_horizon`` single-token steps, the whole
  horizon inside one compiled program. Each scan step samples per row
  from the carried last-logits (per-row traced temperature / top-k /
  top-p — serve/sampling.py), forwards through the model with PER-ROW
  cache positions (models/gpt2.py per-row pos path), and feeds the
  sampled token straight into the next step's embedding — tokens never
  visit the host mid-block, so the per-token Python→XLA dispatch +
  device→host sync cost is paid once per H tokens instead of once per
  token. Completion is decided ON DEVICE: per-row ``eos_ids`` and
  remaining-``budgets`` (engine state set at prefill) flip a carried
  ``done`` mask the moment a row emits EOS or exhausts its budget, and
  the carried ``ok`` health mask (NaN/inf tripwire, ANDed per scan
  step) freezes a poisoned row from the bad step on — either way the
  row stops sampling AND stops writing K/V for the rest of the block,
  because the per-step ``active ∧ ¬done ∧ ok`` emit mask is what
  threads into the model as ``active``. On TPU the attention inside
  each scan step is the Pallas flash-decode kernel
  (ops/pallas/decode_attention.py): per-row ``lengths`` skip KV blocks
  above each row's depth, and non-emitting rows (inactive slots, done
  rows, frozen rows) skip every block instead of computing masked
  garbage (host-side masking still applies — their state is frozen by
  ``where(emit, ...)``). The program returns a ``[B, H]`` token block
  plus per-row ``emitted`` counts; overshoot columns past a row's
  count are pad and never reach the client. ``decode_horizon=1``
  (default) runs the scan body once inline — bit-identical to the
  classic one-token step.

All programs route through the runtime ``Executor`` (compile-cache keyed
on function identity + full arg shape signature), so the program-count
claim is enforced by the ``compile_cache.*`` obs counters: a shape drift
would show up as an extra miss, and tests pin the count at
``1 + len(prefill_buckets)`` with misses frozen after warmup (a bucket
program compiles the first time a prompt lands in its bucket).

All per-request scalars cross into the programs as 0-d ARRAYS, never
Python numbers — the executor's signature (and jax.jit's) would
otherwise key on the literal value and recompile per request.

Token-range validation lives in the scheduler's admission path
(``Scheduler.submit``), NOT here: the engine trusts its caller so the
per-prefill host work is one ``np.zeros`` + copy per chunk, and a bad
request is bounced before it ever holds a slot.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from nezha_tpu import faults, obs
from nezha_tpu.models.generate import _caches_from_states
from nezha_tpu.runtime.executor import Executor
from nezha_tpu.serve.sampling import finite_rows, split_and_sample
from nezha_tpu.serve.slots import SlotPool, read_slot, write_slot


def default_prefill_buckets(max_prefill_len: int) -> Tuple[int, ...]:
    """Powers of two from 8 up to (and always ending exactly at)
    ``max_prefill_len`` — e.g. 32 -> (8, 16, 32), 24 -> (8, 16, 24),
    8 -> (8,). Small prompts pad to a small program instead of the full
    width, so short-prompt TTFT stops paying the long-prompt pad tax."""
    buckets: List[int] = []
    b = 8
    while b < max_prefill_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_prefill_len)
    return tuple(buckets)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Static serving shapes — everything a compiled program is keyed on.

    ``max_batch_size`` is the slot count (rows decoded per step),
    ``max_len`` the per-slot KV capacity (prompt + generated),
    ``max_prefill_len`` the widest single prefill chunk — longer prompts
    (up to ``max_len``) are prefilled in successive chunks, not
    rejected. ``prefill_buckets`` are the static prompt pad widths (one
    compiled prefill program each; ``()`` selects the powers-of-two
    default from :func:`default_prefill_buckets` — the last bucket must
    equal ``max_prefill_len``). ``k_max`` is the static top-k cap
    per-row ks are clamped to. ``queue_capacity`` bounds the scheduler's
    FIFO (backpressure); ``pad_id`` is the token fed for inactive rows.
    ``decode_impl`` (None = keep the model's own ``GPT2Config.
    decode_impl``) overrides the decode-attention choice for this
    engine: "auto" | "kernel" | "xla" — the serving-side toggle for the
    flash-decode kernel. ``decode_horizon`` is the number of tokens one
    compiled step program decodes per dispatch (the fused device-
    resident sampling loop): 1 (default) is the classic one-token step,
    bit-identical to pre-horizon behavior; H > 1 amortizes the
    per-dispatch host gap over H tokens at the cost of coarser
    deadline/drain granularity (one horizon) — EOS/budget completion
    moves on device, so a row finishing mid-block stops sampling and
    K/V writes immediately and its overshoot is dropped before the
    block reaches the host.
    """

    max_batch_size: int = 4
    max_len: int = 128
    max_prefill_len: int = 32
    prefill_buckets: Tuple[int, ...] = ()
    k_max: int = 64
    queue_capacity: int = 16
    pad_id: int = 0
    cache_dtype: Any = jnp.bfloat16
    decode_impl: Optional[str] = None
    decode_horizon: int = 1

    def __post_init__(self):
        if self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if self.decode_horizon < 1:
            raise ValueError(
                f"decode_horizon must be >= 1, got {self.decode_horizon}")
        if not 1 <= self.max_prefill_len <= self.max_len:
            raise ValueError(
                f"need 1 <= max_prefill_len <= max_len, got "
                f"{self.max_prefill_len} / {self.max_len}")
        if self.k_max < 1:
            raise ValueError("k_max must be >= 1")
        if self.queue_capacity < 1:
            raise ValueError("queue_capacity must be >= 1")
        if self.decode_impl not in (None, "auto", "kernel", "xla"):
            raise ValueError(
                f"decode_impl must be None, 'auto', 'kernel', or 'xla'; "
                f"got {self.decode_impl!r}")
        buckets = tuple(self.prefill_buckets) or default_prefill_buckets(
            self.max_prefill_len)
        if list(buckets) != sorted(set(buckets)):
            raise ValueError(
                f"prefill_buckets must be strictly increasing, got "
                f"{buckets}")
        if buckets[0] < 1 or buckets[-1] != self.max_prefill_len:
            # The last bucket IS the chunk width: every admissible tail
            # must fit some bucket, and chunking advances in
            # max_prefill_len strides.
            raise ValueError(
                f"prefill_buckets must be >= 1 and end exactly at "
                f"max_prefill_len={self.max_prefill_len}, got {buckets}")
        object.__setattr__(self, "prefill_buckets", buckets)


class Engine:
    """Device-side serving state + the frozen program set.

    The engine is deliberately request-blind: it knows slots, not
    requests. Admission policy, deadlines, retirement, and the
    request-level telemetry (TTFT/TPOT, queue depth, spans) live in the
    scheduler; the engine emits only what it alone can see — the
    bucket/chunk instruments (``serve.prefill.bucket_len`` /
    ``serve.prefill.chunks_total``), since the bucket choice is made
    here. The contract is ``prefill(slot, ...)`` to load one slot
    (however many chunks that takes — including the row's EOS id and
    new-token budget, which become device state) and ``step(active)``
    to decode one BLOCK of up to ``decode_horizon`` tokens for every
    row and hand the ``[B, H]`` batch back to the host along with
    per-row emitted counts. ``step_calls`` counts host dispatches of
    the step program — the denominator of the dispatch-per-token
    amortization this engine exists to improve.
    """

    def __init__(self, model, variables, cfg: ServeConfig = ServeConfig()):
        if cfg.max_len > model.cfg.max_positions:
            raise ValueError(
                f"max_len {cfg.max_len} exceeds the model's max_positions "
                f"{model.cfg.max_positions}")
        if (cfg.decode_impl is not None
                and cfg.decode_impl != model.cfg.decode_impl):
            # The decode-attention choice is a model-config knob (the
            # attention module reads it at trace time); honor the serving
            # override by rebuilding the module tree around a replaced
            # config — pure structure, the caller's ``variables`` slot
            # straight in.
            model = type(model)(
                dataclasses.replace(model.cfg, decode_impl=cfg.decode_impl),
                policy=model.policy)
        self.model = model
        self.variables = variables
        self.cfg = cfg
        self.vocab = model.cfg.vocab_size
        self.k_max = min(cfg.k_max, self.vocab)
        self.pool = SlotPool(model, cfg.max_batch_size, cfg.max_len,
                             cfg.cache_dtype)
        b = cfg.max_batch_size
        self.last_logits = jnp.zeros((b, self.vocab), jnp.float32)
        # [B] bool from the latest step: False where that row's logits
        # (carried-in or freshly produced) went non-finite — the
        # scheduler's signal to retire the row with FinishReason.ERROR.
        self.step_ok: Optional[np.ndarray] = None
        self.positions = jnp.zeros((b,), jnp.int32)
        self.keys = jnp.zeros((b, 2), jnp.uint32)
        self.temps = jnp.zeros((b,), jnp.float32)
        self.top_ks = jnp.zeros((b,), jnp.int32)
        self.top_ps = jnp.ones((b,), jnp.float32)
        # On-device completion state, set per row at prefill: the EOS id
        # (-1 = none) and the remaining new-token budget. Inside a decode
        # block a row that emits its EOS or exhausts its budget flips the
        # scan's carried `done` mask and stops sampling + K/V writes for
        # the rest of the block — the host never sees overshoot.
        self.eos_ids = jnp.full((b,), -1, jnp.int32)
        self.budgets = jnp.zeros((b,), jnp.int32)
        # Host dispatches of the step program (1 dispatch = up to
        # decode_horizon tokens for every row) — tests assert the
        # dispatch-per-token amortization against this.
        self.step_calls = 0
        # Donate the pooled caches (positional arg 1 in EVERY program):
        # without donation every decoded token would copy the whole
        # [B_max, H, L_max, D] K/V pool per layer just to write one row —
        # double the KV memory and a full-pool bandwidth tax on the
        # latency-bound loop. The engine rebinds the returned buffers
        # immediately, so the invalidated inputs are never reused.
        self.executor = Executor(donate_argnums=(1,))
        # One prefill program per bucket width (compiled lazily: the
        # executor keys on the function object, so each closure is its
        # own cache entry the first time a prompt lands in its bucket).
        self._prefill_fns = {w: _build_prefill(self.model, w)
                             for w in cfg.prefill_buckets}
        self._step_fn = _build_step(self.model, self.k_max, cfg.pad_id,
                                    cfg.decode_horizon)

    # -------------------------------------------------------- host API
    def bucket_for(self, n: int) -> int:
        """The static pad width the TAIL chunk of an ``n``-token prompt
        runs at: the smallest bucket >= n for single-chunk prompts,
        else the smallest bucket >= the chunked remainder. Benchmarks
        group TTFT by this value."""
        p_max = self.cfg.max_prefill_len
        rem = n if n <= p_max else (n % p_max or p_max)
        return next(w for w in self.cfg.prefill_buckets if w >= rem)

    def prefill(self, slot: int, tokens: Sequence[int], *, seed: int = 0,
                temperature: float = 0.0, top_k: Optional[int] = None,
                top_p: Optional[float] = None,
                eos_id: Optional[int] = None,
                max_new_tokens: Optional[int] = None) -> None:
        """Load one request into ``slot``: prompt K/V, position, PRNG
        key, sampling params, and the row's on-device completion state
        (``eos_id``, ``None`` = never stop on a token; and its
        new-token budget, ``None`` = everything the slot's KV capacity
        allows). ``tokens`` may be up to ``max_len - 1`` long (room for
        at least one generated token); prompts wider than
        ``max_prefill_len`` run as successive chunks through the same
        bucket programs. Token ids are NOT validated here — admission
        (``Scheduler.submit``) is the validation boundary. The first
        generated token comes from the next :meth:`step`."""
        faults.point("serve.prefill")
        n = len(tokens)
        if not 1 <= n < self.cfg.max_len:
            raise ValueError(
                f"prompt length {n} not in [1, max_len-1="
                f"{self.cfg.max_len - 1}]")
        # The device budget is what stops a row mid-block; capping it at
        # the slot's remaining KV capacity means a block can never write
        # past max_len even for budget-less direct engine callers.
        cap = self.cfg.max_len - n
        budget = cap if max_new_tokens is None else min(max_new_tokens,
                                                        cap)
        p_max = self.cfg.max_prefill_len
        tokens = np.asarray(tokens, np.int32)
        chunks: List[Tuple[int, int, int]] = []      # (offset, len, width)
        off = 0
        while n - off > p_max:
            chunks.append((off, p_max, p_max))
            off += p_max
        rem = n - off
        width = self.bucket_for(rem)
        if off + width > self.cfg.max_len:
            # A padded tail would spill past the slot's KV capacity
            # (max_len not a multiple of max_prefill_len, prompt near
            # capacity) — and dynamic_update_slice would CLAMP the write
            # start, corrupting the already-written prefix. Slide the
            # window back to cover the last `width` REAL tokens instead:
            # rewriting those positions recomputes identical K/V (same
            # tokens, same prefix), and no pad lands past capacity.
            # (Only reachable when chunked, where n > max_prefill_len
            # >= width, so off stays >= 0.)
            off, rem = n - width, width
        chunks.append((off, rem, width))
        obs.counter("serve.prefill.chunks_total").inc(len(chunks))
        for off, ln, width in chunks:
            obs.histogram("serve.prefill.bucket_len").observe(width)
            padded = np.zeros((1, width), np.int32)
            padded[0, :ln] = tokens[off:off + ln]
            out = self.executor.run(
                self._prefill_fns[width], self.variables, self.pool.caches,
                jnp.asarray(padded),
                np.int32(ln), np.int32(slot), np.int32(off),
                np.int32(seed), np.float32(temperature),
                np.int32(0 if top_k is None else top_k),
                np.float32(1.0 if top_p is None else top_p),
                np.int32(-1 if eos_id is None else eos_id),
                np.int32(budget),
                self.last_logits, self.positions, self.keys,
                self.temps, self.top_ks, self.top_ps,
                self.eos_ids, self.budgets)
            (self.pool.caches, self.last_logits, self.positions, self.keys,
             self.temps, self.top_ks, self.top_ps,
             self.eos_ids, self.budgets) = out
        if faults.enabled():
            self.last_logits = faults.corrupt(
                "serve.prefill.logits", self.last_logits, rows=(slot,))

    def step(self, active: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Decode one BLOCK of up to ``decode_horizon`` tokens for every
        row; ``active`` is a ``[B_max]`` bool mask. Returns
        ``(tokens, emitted)`` as host arrays: ``tokens`` is the
        ``[B_max, H]`` block — a row's valid tokens are
        ``tokens[r, :emitted[r]]``; everything past its count (overshoot
        after EOS / budget / a mid-block NaN freeze, or all H columns of
        an inactive row) is pad and must be ignored. After the call
        :attr:`step_ok` holds a ``[B_max]`` bool health mask: False
        where a row's logits went non-finite at any scan step (only
        meaningful for rows the caller knows are active) — such a row's
        pre-burst tokens are still counted in ``emitted``."""
        faults.point("serve.step")
        self.step_calls += 1
        out = self.executor.run(
            self._step_fn, self.variables, self.pool.caches,
            self.last_logits, self.positions,
            jnp.asarray(active, bool), self.keys,
            self.temps, self.top_ks, self.top_ps,
            self.eos_ids, self.budgets)
        tok, emitted, ok, caches, last, pos, keys, budgets = out
        # Start the block's device->host transfers NOW, before any host
        # bookkeeping (state rebinds here, retire/admit/stream in the
        # scheduler): the np.asarray reads below then find bytes already
        # in flight instead of paying the full sync serially.
        for arr in (tok, emitted, ok):
            copy_async = getattr(arr, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        self.pool.caches = caches
        if faults.enabled():
            last = faults.corrupt(
                "serve.step.logits", last,
                rows=lambda: np.flatnonzero(active))
        self.last_logits, self.positions, self.keys = last, pos, keys
        self.budgets = budgets
        self.step_ok = np.asarray(ok)
        return np.asarray(tok), np.asarray(emitted)

    def compile_stats(self) -> dict:
        """Executor cache stats — steady state is ``entries ==
        1 + len(prefill_buckets)`` (step + one prefill per bucket),
        misses frozen there after every bucket has been warmed while
        hits grow."""
        return self.executor.stats()


def _build_prefill(model, width: int):
    def prefill(variables, caches, tokens, length, slot, pos, seed,
                temperature, top_k, top_p, eos_id, budget,
                last_logits, positions, keys, temps, top_ks, top_ps,
                eos_ids, budgets):
        # One prompt chunk, padded to this bucket's static `width`, runs
        # against the SLOT'S OWN cache rows at a traced offset: the
        # masked attention path sees the prefix earlier chunks wrote
        # (pos > 0) or nothing (pos == 0), so the same program serves
        # first chunks, middle chunks, and bucketed tails. Rows past
        # `length` are pad — their K/V lands above the prompt and is
        # overwritten by decode before any mask attends it.
        rows = [{"k": read_slot(pool["k"], slot),
                 "v": read_slot(pool["v"], slot)} for pool in caches]
        logits, states = model.apply(variables, tokens, training=False,
                                     cache=rows, pos=pos)
        new_rows = _caches_from_states(model, states, rows)
        new_caches = [
            {"k": write_slot(pool["k"], rk["k"], slot),
             "v": write_slot(pool["v"], rk["v"], slot)}
            for pool, rk in zip(caches, new_rows)]
        row = lax.dynamic_slice(
            logits, (0, length - 1, jnp.zeros((), jnp.int32)),
            (1, 1, logits.shape[-1]))[:, 0, :]          # [1, V] last REAL row
        key = jax.random.PRNGKey(seed).astype(keys.dtype)

        def set_row(buf, val):
            return lax.dynamic_update_slice(
                buf, jnp.asarray(val, buf.dtype).reshape(
                    (1,) + buf.shape[1:]),
                (slot,) + (jnp.zeros((), jnp.int32),) * (buf.ndim - 1))

        # Every chunk overwrites the whole per-slot state; only the final
        # chunk's values survive to decode (positions advances to the
        # running prefix length either way).
        return (new_caches,
                set_row(last_logits, row),
                set_row(positions, pos + length),
                set_row(keys, key),
                set_row(temps, temperature),
                set_row(top_ks, top_k),
                set_row(top_ps, top_p),
                set_row(eos_ids, eos_id),
                set_row(budgets, budget))

    return prefill


def _build_step(model, k_max: int, pad_id: int, horizon: int):
    def body(active, temps, top_ks, top_ps, eos_ids, budgets,
             variables, carry):
        """One fused decode step: the single-token body the horizon scan
        iterates. Everything request-terminating happens on device:

        - ``ok`` is the carried health mask (PR 4's NaN/inf tripwire),
          ANDed per step against the carried-in logits BEFORE sampling
          (a burst that landed between steps makes this step's sample
          garbage — never emit it) and against the fresh row AFTER the
          forward pass (matching the classic step's conservative
          discard). A row that trips freezes from that step on.
        - ``done`` flips when a row emits its EOS id or fills its
          remaining budget; ``emitted`` counts only genuinely emitted
          tokens, so the host can slice each row's valid prefix out of
          the block.
        - ``emit = active ∧ ¬done ∧ ok`` is the mask that threads into
          the model as ``active``: the flash-decode kernel zeroes
          non-emitting rows' lengths and skips their KV blocks, so a
          finished/frozen row stops writing K/V mid-block (the composed
          fallback ignores it; garbage rows are masked below either
          way). Keys advance only on emit — a request's RNG stream is a
          function of (seed, emitted count), horizon-invariant.
        """
        caches, last_logits, positions, keys, done, ok, emitted = carry
        ok = ok & finite_rows(last_logits)
        # (emitted < budgets) is redundant with the done flip below for
        # every block the scheduler dispatches (live rows always carry
        # budget >= 1) — it guards the degenerate budget-0 row a direct
        # engine caller could create, which must emit nothing.
        emit = active & ~done & ok & (emitted < budgets)
        next_keys, tok = split_and_sample(keys, last_logits, temps,
                                          top_ks, top_ps, k_max)
        tok = jnp.where(emit, tok, pad_id)
        logits, states = model.apply(variables, tok[:, None],
                                     training=False, cache=caches,
                                     pos=positions, active=emit)
        new_caches = _caches_from_states(model, states, caches)
        row_logits = logits[:, -1, :]
        ok = jnp.where(emit, ok & finite_rows(row_logits), ok)
        counted = emit & ok
        emitted = emitted + counted.astype(jnp.int32)
        done = done | (counted & (eos_ids >= 0) & (tok == eos_ids)) \
                    | (counted & (emitted >= budgets))
        act = emit[:, None]
        return (new_caches,
                jnp.where(act, row_logits, last_logits),
                jnp.where(emit, positions + 1, positions),
                jnp.where(act, next_keys, keys),
                done, ok, emitted), tok

    def step(variables, caches, last_logits, positions, active, keys,
             temps, top_ks, top_ps, eos_ids, budgets):
        b = positions.shape[0]
        init = (caches, last_logits, positions, keys,
                jnp.zeros((b,), bool),        # done (within this block)
                jnp.ones((b,), bool),         # ok   (health, carried)
                jnp.zeros((b,), jnp.int32))   # emitted (within block)

        def scan_body(carry, _):
            return body(active, temps, top_ks, top_ps, eos_ids, budgets,
                        variables, carry)

        if horizon == 1:
            # Inline, not a length-1 scan: the default must stay
            # bit-identical to the classic single-token step program.
            carry, tok = scan_body(init, None)
            tok_block = tok[:, None]
        else:
            carry, toks = lax.scan(scan_body, init, None, length=horizon)
            tok_block = jnp.transpose(toks, (1, 0))        # [H,B]->[B,H]
        caches, last_logits, positions, keys, done, ok, emitted = carry
        return (tok_block, emitted, ok, caches, last_logits, positions,
                keys, jnp.maximum(budgets - emitted, 0))

    return step
