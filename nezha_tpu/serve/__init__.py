"""Continuous-batching inference: the serving layer on top of
``models/generate.py``'s compiled decode.

One-shot ``generate()`` decodes a whole batch in lockstep: every request
shares sampling params, and nothing can join or leave mid-flight. The
serve stack replaces the batch lifecycle with a slot lifecycle:

- ``slots``: the KV pools. Default is the BLOCK-PAGED layout
  (``PagedSlotPool``): per-layer ``[num_blocks, H, block_size, D]``
  buffers, a host-side free list of ref-counted blocks, and per-slot
  block tables threaded into the compiled programs — admission binds
  only what the prompt needs, decode binds lazily as positions
  advance, and a prefix-reuse trie lets a request whose prompt prefix
  matches cached blocks take REFERENCES instead of re-prefilling
  (copy-on-write protects shared blocks; exhaustion is typed
  backpressure, never a crash). ``ServeConfig.kv_dtype="int8"`` stores
  blocks as int8 with per-(block, head) fp32 absmax scales (the shared
  ``ops/quant.py`` core): ~2x resident requests at the same device
  budget, with the dequant fused into the flash-decode kernel's block
  loop. ``SlotPool`` is the classic dense ``[B_max, H, L_max, D]``
  worst-case-reservation layout (``ServeConfig.kv_layout="dense"``).
- ``sampling``: per-row temperature / top-k / top-p as traced arrays, so
  one compiled program serves every mix of requests (top-k masks by
  per-row k under a static ``k_max`` cap — ``lax.top_k``'s k is static).
- ``engine``: exactly ``1 + len(prefill_buckets)`` jitted programs,
  reused forever — one prefill program per static prompt-pad bucket
  (prompts pick the smallest bucket that fits; prompts longer than
  ``max_prefill_len`` prefill in successive chunks through the same
  programs at traced offsets) and the batched decode step over all
  ``B_max`` rows. The step is a DEVICE-RESIDENT sampling loop: a
  ``lax.scan`` of ``ServeConfig.decode_horizon`` single-token steps in
  one compiled program (sampled tokens feed the next step's embedding
  without visiting the host; per-row EOS ids and new-token budgets are
  engine state, so completion flips a carried ``done`` mask mid-block
  and the row stops sampling and writing K/V), returning a ``[B, H]``
  token block + per-row emitted counts — the per-token host dispatch +
  sync cost shrinks by the horizon. On TPU the attention per scan step
  is the Pallas flash-decode kernel — per-row lengths skip KV blocks
  instead of masking them, and the emit mask zeroes finished rows'
  lengths. All programs route through the runtime ``CompileCache``, so
  the frozen-program steady state is provable from the
  ``compile_cache.*`` obs counters. ``ServeConfig.speculative`` grows
  the step into a fused draft→verify→accept loop: a cheap draft model
  (an early-exit slice of the target, or a separate checkpoint)
  proposes ``draft_k`` tokens per window, ONE batched target forward
  scores them all, and the longest agreeing prefix is emitted — up to
  ``decode_horizon * (draft_k + 1)`` tokens per dispatch at unchanged
  outputs (greedy bit-identical; sampled via lossless rejection
  sampling), the draft's KV mirroring the target pool's slot
  lifecycle.
- ``scheduler``: bounded FIFO admission with backpressure, per-request
  deadlines, and the iteration loop (admit -> decode one block for all
  active rows -> retire on EOS / max-new-tokens / deadline, freeing
  slots for waiters; retire/admit and deadline checks run once per
  horizon). Failure is request-scoped: a prefill exception or
  NaN/inf logit burst retires only the affected request
  (``FinishReason.ERROR``) while the batch keeps decoding, and a step
  crash gets one bounded retry — provable on demand through the
  ``nezha_tpu.faults`` injection layer. Fully instrumented through
  ``nezha_tpu.obs`` (serve.ttft_s / serve.tpot_s histograms,
  queue-depth and batch-occupancy gauges,
  admitted/rejected/retired/errors counters).

Scale-out rides on top of the single-replica stack rather than inside
it:

- ``supervisor``: spawn N replicas (each the whole stack above on its
  own port — subprocess or in-process-thread backed), restart crashed
  ones with capped seeded backoff and a circuit breaker, and perform
  the rolling drain (replicas stop one at a time, so capacity never
  hits zero mid-drain).
- ``router``: the HTTP front end over those replicas — /healthz
  probing with K-miss ejection and readmission, least-loaded routing,
  queue-full 503 only when EVERY live replica is full, and bounded
  seeded-backoff failover for replicas that die before their response
  begins (a committed stream is never retried — typed error instead).

- ``sharded`` (imported on demand, not at package import): the SECOND
  scale axis — ``ShardedEngine`` spreads one replica over an M-device
  1xM ``tp`` mesh (params Megatron-sharded, paged K/V head-sharded,
  pool bookkeeping host-side and unchanged, the frozen program set
  traced under the GSPMD auto-partitioner with pinned output
  shardings), and ``reshard`` streams a training-topology checkpoint
  into the serve layout with per-leaf CRC verification
  (``nezha-reshard``; typed ``ReshardError`` = refuse to start).
  ``--replicas N --mesh M`` composes: N routed replicas x M-device
  meshes.

``nezha-serve`` (cli/serve.py) fronts the scheduler with stdio-JSONL and
stdlib-http modes (``--replicas N`` puts the router/supervisor pair in
front of N worker processes, ``--mesh M`` makes each worker an M-device
tensor-parallel engine); ``benchmarks/serving.py`` load-tests it
into the same run-dir telemetry artifacts training writes
(``--replicas/--kill-rate`` chaos-loads the router, ``--mesh`` runs the
single-replica loops sharded).
"""

from nezha_tpu.serve.engine import (Engine, ServeConfig,
                                    SpeculativeConfig, self_draft)
from nezha_tpu.serve.migrate import MigrationError
from nezha_tpu.serve.router import Router, register_router_instruments
from nezha_tpu.serve.sampling import sample_tokens
from nezha_tpu.serve.scheduler import (
    PRIORITIES,
    FinishReason,
    QueueFull,
    Request,
    RequestResult,
    Scheduler,
    TenantOverLimit,
)
from nezha_tpu.serve.slots import (KVBlocksExhausted, PagedSlotPool,
                                   PrefixTrie, SlotPool)
from nezha_tpu.serve.supervisor import (
    ProcessBackend,
    RouterConfig,
    Supervisor,
    ThreadBackend,
)

__all__ = [
    "Engine", "ServeConfig", "SpeculativeConfig", "self_draft",
    "SlotPool", "PagedSlotPool", "PrefixTrie",
    "KVBlocksExhausted", "sample_tokens",
    "Scheduler", "Request", "RequestResult", "QueueFull",
    "TenantOverLimit", "PRIORITIES", "FinishReason",
    "Router", "RouterConfig", "Supervisor", "ProcessBackend",
    "ThreadBackend", "register_router_instruments", "MigrationError",
]
