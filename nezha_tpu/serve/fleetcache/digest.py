"""Trie digests: bounded prefix-hash summaries of a replica's KV
cache, advertised to the Router over ``/healthz`` (PR 17).

A digest maps ``hash_prefix(tokens[:k*block_size])`` -> tier tag
(``"device"`` or ``"host"``) for a bounded number of cached prefixes.
The hash is ``blake2b`` (8-byte digest) over each token encoded as a
little-endian signed 64-bit integer — a canonical byte encoding, so
the replica building the digest and the Router hashing an incoming
prompt agree without ever shipping tokens.  Python's builtin ``hash``
is per-process salted and must never be used here.

Digests are *advisory*: a stale entry costs one wasted peer probe (the
export side returns an empty wire for zero coverage), never
correctness.  That is what lets the rebuild be lazy and the bound be
small.
"""

from __future__ import annotations

import hashlib
import time
from typing import Dict, List, Optional, Sequence, Tuple

# Wire-format version of the ``fleet_digest`` healthz payload.  Bump
# on any change to the hash encoding or entry shape; the Router
# ignores digests whose version it does not recognise.
DIGEST_VERSION = 1

_TIERS = ("device", "host")


def _token_bytes(tok: int) -> bytes:
    return int(tok).to_bytes(8, "little", signed=True)


def hash_prefix(tokens: Sequence[int]) -> str:
    """Canonical hash of one token prefix (hex, 16 chars)."""
    h = hashlib.blake2b(digest_size=8)
    for t in tokens:
        h.update(_token_bytes(t))
    return h.hexdigest()


def prefix_hashes(tokens: Sequence[int], block_size: int) -> List[str]:
    """Hashes of every block-aligned prefix of ``tokens``, one pass.

    Element ``k`` is ``hash_prefix(tokens[:(k+1)*block_size])``; the
    incremental update makes hashing an L-token prompt O(L) rather
    than O(L^2 / block_size).
    """
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    h = hashlib.blake2b(digest_size=8)
    out: List[str] = []
    nblocks = len(tokens) // block_size
    for k in range(nblocks):
        for t in tokens[k * block_size:(k + 1) * block_size]:
            h.update(_token_bytes(t))
        out.append(h.hexdigest())
    return out


def build_digest(pool, max_entries: int) -> Dict[str, str]:
    """-> ``{prefix_hash: tier}`` for up to ``max_entries`` cached
    prefixes of ``pool`` (a PagedSlotPool), recency-first.

    ``pool.digest_entries()`` yields ``(path_tokens, tier)`` with
    device-trie paths first (hottest first), then host-tier keys (MRU
    first).  Device wins on a hash collision between tiers — a device
    hit is strictly cheaper than a host promote, and the Router only
    uses the tag for telemetry-grade expectations, not correctness.
    """
    if max_entries < 1:
        raise ValueError(f"max_entries must be >= 1, got {max_entries}")
    out: Dict[str, str] = {}
    for path_tokens, tier in pool.digest_entries():
        if tier not in _TIERS:
            raise ValueError(f"unknown digest tier {tier!r}")
        key = hash_prefix(path_tokens)
        prev = out.get(key)
        if prev is None:
            if len(out) >= max_entries:
                # Entries arrive recency-first, so truncation drops
                # the coldest prefixes — keep scanning only to let a
                # device tag upgrade an already-admitted host tag.
                continue
            out[key] = tier
        elif prev == "host" and tier == "device":
            out[key] = tier
    return out


class DigestCache:
    """Lazily rebuilt digest + the healthz payload that carries it.

    The scheduler owns one of these (under its lock); every
    ``/healthz`` hit calls :meth:`payload`, which rebuilds at most
    once per ``interval_s`` — a bounded trie walk, never a device op.
    """

    def __init__(self, interval_s: float = 2.0,
                 max_entries: int = 256) -> None:
        if interval_s <= 0:
            raise ValueError(
                f"interval_s must be > 0, got {interval_s}")
        if max_entries < 1:
            raise ValueError(
                f"max_entries must be >= 1, got {max_entries}")
        self.interval_s = float(interval_s)
        self.max_entries = int(max_entries)
        self._entries: Dict[str, str] = {}
        self._built_t: float = 0.0

    def payload(self, pool) -> Dict[str, object]:
        """-> healthz fields: ``fleet_digest`` (versioned entry map),
        ``digest_size`` and ``digest_age_s``."""
        now = time.monotonic()
        if self._built_t <= 0.0 or now - self._built_t >= self.interval_s:
            self._entries = build_digest(pool, self.max_entries)
            self._built_t = now
        return {
            "fleet_digest": {
                "v": DIGEST_VERSION,
                "block_size": int(pool.block_size),
                "entries": dict(self._entries),
            },
            "digest_size": len(self._entries),
            "digest_age_s": max(0.0, now - self._built_t),
        }


def digest_entries_of(
        payload: Optional[dict],
) -> Optional[Tuple[int, Dict[str, str]]]:
    """-> ``(block_size, entries)`` from one replica's healthz
    payload, or ``None`` if absent / malformed / wrong version."""
    if not isinstance(payload, dict):
        return None
    dig = payload.get("fleet_digest")
    if not isinstance(dig, dict) or dig.get("v") != DIGEST_VERSION:
        return None
    bs = dig.get("block_size")
    entries = dig.get("entries")
    if not isinstance(bs, int) or bs < 1 or not isinstance(entries, dict):
        return None
    return bs, entries


__all__ = [
    "DIGEST_VERSION",
    "DigestCache",
    "build_digest",
    "digest_entries_of",
    "hash_prefix",
    "prefix_hashes",
]
