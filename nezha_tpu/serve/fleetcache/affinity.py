"""Affinity scoring: turn cached digests into a routing decision
(PR 17).

Pure functions — the Router owns all state (digest snapshots live in
``Replica.last_health``, staleness is judged against the prober
clock).  Keeping this transport- and lock-free is what makes the
scorer unit-testable without a cluster.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from nezha_tpu.serve.fleetcache.digest import hash_prefix


def coverage(entries: Dict[str, str],
             hashes: Sequence[str]) -> Tuple[int, Optional[str]]:
    """-> ``(blocks, tier)``: how many leading block-aligned prefixes
    of the prompt (pre-hashed into ``hashes``) this digest covers, and
    the tier tag of the longest covering entry.

    Scans longest-first: the longest covered prefix determines both
    the score and the tier a hit is expected to land in, and prompts
    shorter than the digest's reach exit after one lookup.
    """
    for k in range(len(hashes) - 1, -1, -1):
        tier = entries.get(hashes[k])
        if tier is not None:
            return k + 1, tier
    return 0, None


def score(cover_blocks: int, block_size: int,
          in_flight: int, queued: int) -> float:
    """Expected-prefix-hit tokens discounted by candidate load.

    ``cover_blocks * block_size`` tokens of prefill are avoided on a
    hit; each in-flight or queued request on the candidate delays the
    new arrival by roughly one decode round, hence the harmonic
    discount.  A zero-coverage candidate scores 0.0 regardless of
    load — cold placement is :func:`place_cold`'s job, not a
    tie-break inside the scorer.
    """
    if cover_blocks <= 0:
        return 0.0
    return (cover_blocks * block_size) / (1.0 + in_flight + queued)


def place_cold(tokens: Sequence[int], block_size: int,
               rids: Sequence[int]) -> Optional[int]:
    """Consistent-hash placement when no candidate covers anything.

    Hashes the first block of the prompt (the whole prompt when
    shorter) and picks among ``rids`` — the caller passes only the
    candidates tied at minimal load, so this never overrides the
    least-loaded invariant, it only breaks its ties deterministically
    per prefix.  Without this, zero-load ties always resolve to the
    lowest rid and repeat users never grow an owner replica.
    """
    if not rids:
        return None
    head = list(tokens[:max(1, block_size)])
    if not head:
        return None
    ordered: List[int] = sorted(rids)
    return ordered[int(hash_prefix(head), 16) % len(ordered)]


__all__ = ["coverage", "place_cold", "score"]
