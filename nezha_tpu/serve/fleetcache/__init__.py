"""Fleet cache coordination: turn N replicas' private KV caches into
one logical three-tier cache (PR 17).

Two halves, each deliberately tiny and transport-free:

``digest``
    Replica side.  A bounded prefix-hash summary of what a replica's
    paged pool currently holds — device trie nodes *and* PR 15
    host-tier entries, tagged per tier — rebuilt lazily at a pinned
    interval and piggybacked on the ``/healthz`` payload the PR 9
    prober already collects.  Hashes are ``blake2b`` over a canonical
    little-endian token encoding, so two processes that cached the
    same prefix advertise the same hash without exchanging tokens.

``affinity``
    Router side.  Pure functions that turn cached digests into a
    routing decision: ``coverage`` (how many leading blocks of this
    prompt does a candidate's digest cover, and from which tier),
    ``score`` (expected hit length discounted by load) and
    ``place_cold`` (consistent-hash placement among load-tied
    candidates when nobody covers anything, so repeat users grow an
    owner instead of piling onto the lowest rid).

The Router combines the two into a three-tier lookup per prefill:
own device trie -> own host tier -> peer replica (``pull_from``
pointer resolved over the PR 11 ``/kv_export`` int8 wire) -> cold
prefill.  See ``docs/RUNBOOK.md`` section 10 ("Fleet-wide KV reuse").
"""

from nezha_tpu.serve.fleetcache.digest import (
    DIGEST_VERSION,
    DigestCache,
    build_digest,
    digest_entries_of,
    hash_prefix,
    prefix_hashes,
)
from nezha_tpu.serve.fleetcache.affinity import (
    coverage,
    place_cold,
    score,
)

__all__ = [
    "DIGEST_VERSION",
    "DigestCache",
    "build_digest",
    "digest_entries_of",
    "hash_prefix",
    "prefix_hashes",
    "coverage",
    "place_cold",
    "score",
]
