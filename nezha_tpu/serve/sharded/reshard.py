"""Train-topology checkpoint -> serve-mesh parameters, one leaf at a time.

A training run lays parameters out for THROUGHPUT (zero1/dp replicas,
gspmd meshes, or a plain single-host npz); the sharded serve engine
needs them laid out for LATENCY — Megatron head/feature-sharded over a
1xM ``tp`` mesh. This module is the bridge (the portable-redistribution
problem of arXiv:2112.01075, serving edition):

- :func:`serve_tp_rules` / :func:`serve_shardings` — the serve-side
  partition specs, derived from the SAME ``GPT2_TP_RULES`` table
  training uses (no second table to drift), with one serving-specific
  relaxation: the vocab-sharded embedding falls back to replication
  when ``vocab_size % M != 0`` (GPT-2's 50257 divides none of 2/4/8 —
  jit sharding requires exact divisibility, and the decode path is not
  embedding-bound).
- :func:`place_variables` — commit an in-memory variables tree onto the
  mesh per those specs (the ``--random-init`` / already-loaded path).
- :func:`reshard_checkpoint` — the STREAMING path behind
  ``nezha-reshard`` and ``nezha-serve --mesh M --ckpt-dir ...``: walk
  the serve template one leaf at a time, read that leaf from the
  training checkpoint (dense npz: lazy per-entry decompress, CRC32-
  verified against the PR 4 embedded manifest; sharded dirs: assembled
  per-device-slice from the overlapping stored shards via
  ``make_array_from_callback``, so no host ever materializes more than
  the slices it feeds), and ``device_put`` it straight into its
  head-sharded ``NamedSharding``. Host memory stays bounded by the
  largest single leaf, never the model.
- :func:`save_serve_checkpoint` / :func:`verify_roundtrip` — write the
  re-laid parameters as a serve-topology sharded checkpoint (per-shard
  npz, COMPLETE-marker committed) and prove the round trip bitwise.

Failure is typed end to end: a missing leaf, a CRC32 mismatch, a torn
npz, or an injected ``serve.reshard`` fault all surface as
:class:`ReshardError` — the engine REFUSES TO START rather than serving
garbage weights (the drill RUNBOOK §9 documents). The whole load runs
under the schema-pinned ``serve.reshard_s`` span.
"""

from __future__ import annotations

import json
import os
import zlib
from pathlib import Path
from typing import Any, List, Optional, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nezha_tpu import faults, obs
from nezha_tpu.parallel.gspmd import GPT2_TP_RULES, param_specs_from_rules


class ReshardError(RuntimeError):
    """Typed reshard failure: checkpoint missing/corrupt (torn npz, CRC
    mismatch, absent leaf), geometry mismatch, or an injected
    ``serve.reshard`` fault. The sharded engine refuses to start on it
    — a half-loaded model must never reach the decode loop."""


def serve_tp_rules(model_cfg, mesh_devices: int):
    """The serving partition-rule table: ``GPT2_TP_RULES`` verbatim,
    except the vocab-sharded embedding replicates when the vocab does
    not divide the mesh (jit shardings require exact divisibility;
    attention/MLP weights — the bulk of the bytes — still shard)."""
    rules = []
    for pat, spec in GPT2_TP_RULES:
        if (pat == r"^wte/embedding$"
                and model_cfg.vocab_size % max(int(mesh_devices), 1)):
            rules.append((pat, P()))
        else:
            rules.append((pat, spec))
    return rules


def serve_shardings(params: Any, mesh: Mesh, rules) -> Any:
    """Pytree of ``NamedSharding``s matching ``params`` (array leaves
    or ShapeDtypeStructs) under the serve rules."""
    specs = param_specs_from_rules(params, rules)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))


def place_variables(variables: Any, mesh: Mesh, rules) -> Any:
    """Commit a variables tree onto the serve mesh: params per the rule
    table, model state replicated. Idempotent — re-placing an
    already-committed tree is a no-op device_put."""
    shardings = serve_shardings(variables["params"], mesh, rules)
    params = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), variables["params"], shardings)
    state = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())),
        variables.get("state", {}))
    return {"params": params, "state": state}


# ------------------------------------------------------- leaf plumbing
def _leaf_key(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _nest(flat: dict) -> dict:
    """``{"a/b/c": leaf}`` -> nested dicts (host-side; the scan-trunk
    fallback's unstack input)."""
    out: dict = {}
    for key, val in flat.items():
        node = out
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = val
    return out


def _template_variables(model):
    """Shape/dtype-only serve template (no weights materialized):
    ``jax.eval_shape`` over the model's own init."""
    return jax.eval_shape(model.init, jax.random.PRNGKey(0))


# --------------------------------------------------- the streaming load
def reshard_checkpoint(ckpt_dir: str, model, mesh: Mesh, *,
                       step: Optional[int] = None,
                       rules=None) -> Tuple[Any, int]:
    """Load a training-topology checkpoint and re-lay it onto the serve
    mesh, one leaf at a time -> ``(variables, step)`` with every param
    committed to its head-sharded ``NamedSharding``.

    Sources, in order: the dense npz format (``train/checkpoint.py`` —
    CRC32-verified per leaf against the embedded PR 4 manifest as it
    streams) and the per-shard format (``train/sharded_checkpoint.py``
    — zero1/dp/gspmd saves, each serve-side device slice assembled from
    exactly the stored shards that overlap it). Scan-layers trunks
    (``h_scan``) take a verified non-streaming fallback: restore, then
    unstack to the unrolled decode layout the serve engine runs.

    Raises :class:`ReshardError` on ANY integrity or geometry problem —
    the engine must refuse to start, not serve garbage."""
    from nezha_tpu.train import checkpoint as ckpt
    from nezha_tpu.train import sharded_checkpoint as sckpt

    try:
        faults.point("serve.reshard")
    except faults.InjectedFault as e:
        raise ReshardError(f"injected reshard fault: {e}") from e
    if rules is None:
        rules = serve_tp_rules(model.cfg, int(mesh.shape.get("tp", 1)))
    template = _template_variables(model)
    shardings = serve_shardings(template["params"], mesh, rules)
    with obs.span("serve.reshard_s", ckpt_dir=str(ckpt_dir),
                  mesh=int(mesh.shape.get("tp", 1))) as sp:
        dense_step = (step if step is not None
                      else ckpt.latest_step(ckpt_dir))
        npz = (os.path.join(ckpt_dir, f"step_{dense_step:08d}.npz")
               if dense_step is not None else None)
        if npz is not None and os.path.exists(npz):
            out = _reshard_npz(npz, template, shardings, mesh, rules,
                               model)
            sp.set(source="npz", step=int(dense_step))
            return out, int(dense_step)
        sstep = step if step is not None else sckpt.latest_step(ckpt_dir)
        sdir = (os.path.join(ckpt_dir, f"step_{sstep:08d}.sharded")
                if sstep is not None else None)
        if sdir is not None and os.path.isdir(sdir):
            out = _reshard_sharded_dir(sdir, template, shardings, mesh)
            sp.set(source="sharded", step=int(sstep))
            return out, int(sstep)
        raise ReshardError(
            f"no training checkpoint (npz or sharded) in {ckpt_dir!r}")


def _reshard_npz(path: str, template, shardings, mesh, rules, model):
    """Stream one dense-npz checkpoint onto the mesh. ``np.load`` is a
    lazy zip reader — each leaf decompresses on access, so host memory
    is bounded by the largest leaf. Every leaf's bytes are CRC32-
    checked against the embedded manifest BEFORE they are committed to
    a device (manifest-less pre-PR-4 saves load with a stderr-free
    pass — nothing to verify against)."""
    from nezha_tpu.train.checkpoint import MANIFEST_KEY

    try:
        z = np.load(path)
    except Exception as e:
        raise ReshardError(
            f"{os.path.basename(path)}: unreadable "
            f"({type(e).__name__}: {e})") from e
    try:
        files = set(z.files)
        manifest = None
        if MANIFEST_KEY in files:
            try:
                manifest = json.loads(str(z[MANIFEST_KEY]))["leaves"]
            except Exception as e:
                raise ReshardError(
                    f"{os.path.basename(path)}: unreadable embedded "
                    f"manifest ({type(e).__name__}: {e})") from e
        if any("h_scan" in k for k in files):
            return _reshard_scan_npz(z, manifest, files, mesh, rules,
                                     model)

        def read_leaf(key: str) -> np.ndarray:
            # TrainState layout ("variables/params/...") or the
            # graph-engine layout ("params/...").
            for cand in (f"variables/{key}", key):
                if cand in files:
                    arr = z[cand]
                    if manifest is not None:
                        meta = manifest.get(cand)
                        if meta is None:
                            raise ReshardError(
                                f"leaf {cand!r} missing from the "
                                f"checkpoint manifest")
                        crc = zlib.crc32(np.ascontiguousarray(
                            arr).tobytes()) & 0xFFFFFFFF
                        if crc != meta["crc32"]:
                            raise ReshardError(
                                f"CRC32 mismatch for leaf {cand!r} — "
                                f"checkpoint corrupt, refusing to "
                                f"serve it")
                    return arr
            raise ReshardError(f"checkpoint missing leaf {key!r}")

        return _stream_leaves(template, shardings, mesh, read_leaf)
    finally:
        z.close()


def _reshard_scan_npz(z, manifest, files, mesh, rules, model):
    """Scan-layers fallback: the checkpoint's trunk is stacked under
    ``h_scan`` while the serve template is unrolled (``h0..hN``), so
    leaf-by-leaf streaming cannot key-match. Verify + load the params
    (CRC per leaf), unstack ONCE on host, then place — host memory
    briefly holds the trunk, the documented cost of this layout."""
    from nezha_tpu.models.gpt2 import unstack_layer_params

    flat = {}
    for key in files:
        if not (key.startswith("variables/params/")
                or key.startswith("params/")):
            continue
        arr = z[key]
        if manifest is not None:
            meta = manifest.get(key)
            crc = zlib.crc32(
                np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF
            if meta is None or crc != meta["crc32"]:
                raise ReshardError(
                    f"CRC32 mismatch for leaf {key!r} — checkpoint "
                    f"corrupt, refusing to serve it")
        flat[key.split("params/", 1)[1]] = arr
    params = _nest(flat)
    params = unstack_layer_params(params, model.cfg.num_layers)
    return place_variables({"params": params, "state": {}}, mesh, rules)


def _reshard_sharded_dir(sdir: str, template, shardings, mesh):
    """Per-shard training save -> serve mesh: each serve device's slice
    is assembled from exactly the stored shards overlapping it
    (``_ShardStore.read``), then committed via
    ``make_array_from_callback`` — the memory-bounded redistribution
    move of arXiv:2112.01075. The format carries COMPLETE markers, not
    CRCs; a missing/incomplete process file surfaces typed."""
    from nezha_tpu.train.sharded_checkpoint import _ShardStore

    try:
        store = _ShardStore(Path(sdir))
    except Exception as e:
        raise ReshardError(
            f"{os.path.basename(sdir)}: unreadable shard store "
            f"({type(e).__name__}: {e})") from e
    try:
        def read_leaf(key: str):
            for cand in (f"variables/{key}", key):
                if cand in store.leaves:
                    return cand
            raise ReshardError(f"checkpoint missing leaf {key!r}")

        leaves_t, treedef = jax.tree_util.tree_flatten_with_path(
            template)
        shard_leaves = jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
        # Template order: params leaves first, then state — shardings
        # only covers params; state leaves replicate.
        placed = []
        state_sh = NamedSharding(mesh, P())
        n_params = len(shard_leaves)
        for i, (path, leaf) in enumerate(leaves_t):
            key = _leaf_key(path)
            cand = read_leaf(key)
            entry = store.leaves[cand]
            if tuple(entry["shape"]) != tuple(leaf.shape):
                raise ReshardError(
                    f"shape mismatch for {key!r}: serve template "
                    f"{tuple(leaf.shape)} vs saved "
                    f"{tuple(entry['shape'])}")
            sh = shard_leaves[i] if i < n_params else state_sh
            try:
                arr = jax.make_array_from_callback(
                    tuple(leaf.shape), sh,
                    lambda idx, k=cand, dt=leaf.dtype:
                        store.read(k, idx).astype(dt))
            except ValueError as e:
                raise ReshardError(
                    f"stored shards do not cover {key!r}: {e}") from e
            # Own the bytes (see restore_sharded): a zero-copy alias of
            # the callback's host buffer must never meet a donating
            # program.
            placed.append(arr.copy())
        return jax.tree_util.tree_unflatten(treedef, placed)
    finally:
        store.close()


def _stream_leaves(template, shardings, mesh, read_leaf):
    """Walk the serve template leaf-by-leaf: read (verified) host
    bytes, cast to the template dtype, commit to the leaf's serve
    sharding, drop the host copy — bounded by one leaf."""
    leaves_t, treedef = jax.tree_util.tree_flatten_with_path(template)
    shard_leaves = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
    state_sh = NamedSharding(mesh, P())
    n_params = len(shard_leaves)
    placed = []
    for i, (path, leaf) in enumerate(leaves_t):
        key = _leaf_key(path)
        arr = read_leaf(key)
        if tuple(arr.shape) != tuple(leaf.shape):
            raise ReshardError(
                f"shape mismatch for {key!r}: serve template "
                f"{tuple(leaf.shape)} vs saved {tuple(arr.shape)}")
        sh = shard_leaves[i] if i < n_params else state_sh
        placed.append(jax.device_put(
            np.asarray(arr).astype(leaf.dtype), sh))
    return jax.tree_util.tree_unflatten(treedef, placed)


# ----------------------------------------------------- serve-side save
def save_serve_checkpoint(out_dir: str, variables: Any,
                          step: int) -> str:
    """Write the re-laid parameters as a serve-topology sharded
    checkpoint (per-shard npz + COMPLETE markers — the
    ``train/sharded_checkpoint.py`` format, readable by
    :func:`reshard_checkpoint` on ANY later mesh size, including 1)."""
    from nezha_tpu.train import sharded_checkpoint as sckpt
    return sckpt.save_sharded(out_dir, {"variables": variables}, step)


def verify_roundtrip(out_dir: str, variables: Any,
                     step: int) -> List[str]:
    """Bitwise round-trip proof for ``nezha-reshard --verify``: read the
    serve-topology save back and compare every leaf against the live
    re-laid parameters. -> list of mismatched leaf keys (empty =
    round trip exact)."""
    from nezha_tpu.train.sharded_checkpoint import _ShardStore

    store = _ShardStore(Path(out_dir) / f"step_{step:08d}.sharded")
    bad: List[str] = []
    try:
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                variables)[0]:
            key = f"variables/{_leaf_key(path)}"
            if key not in store.leaves:
                bad.append(key)
                continue
            full = tuple(slice(0, n) for n in leaf.shape)
            stored = store.read(key, full)
            live = np.asarray(jax.device_get(leaf))
            if stored.tobytes() != live.astype(stored.dtype).tobytes():
                bad.append(key)
    finally:
        store.close()
    return bad
