"""The tensor-sharded serve engine: one replica, M devices, one mesh.

:class:`ShardedEngine` turns the single-device continuous-batching
engine (serve/engine.py) into an M-device tensor-parallel engine under
a 1xM device mesh (axis name ``tp`` — the same axis the training-side
GSPMD stack and the nested-``shard_map`` flash idiom already key on):

- **parameters** are committed Megatron-style per the serve rule table
  (:func:`~nezha_tpu.serve.sharded.reshard.serve_tp_rules` — column-
  parallel qkv/fc, row-parallel proj, the training table verbatim);
- **paged K/V pools and per-block scales** are committed HEAD-sharded
  (:class:`~nezha_tpu.serve.sharded.pool.ShardedPagedSlotPool`) — one
  logical pool, M physical shards, while block tables, the free list,
  ref counts, and the prefix trie stay host-side and layout-identical
  to PR 7;
- **programs** are EXACTLY the engine's frozen set — the same
  ``_build_prefill`` / ``_build_step`` / ``_build_spec_step`` closures,
  untouched — reached through the two subsystem hooks: pools built
  sharded, and every program trace wrapped in
  ``auto_partitioner_scope(mesh)`` so XLA's SPMD partitioner lays the
  collectives (attention stays embarrassingly head-parallel; one
  reduce per row-parallel proj) onto the mesh, model code skips Mosaic
  kernels the partitioner cannot split, and — on TPU — the decode
  attention drops into ``flash_decode_attention`` per-shard via a
  nested ``shard_map`` over the head axis with the scalar-prefetched
  block tables replicated (ops/pallas/decode_attention.py).

The frozen program contract is preserved PER MESH: steady state is
still ``1 step + len(all_prefill_buckets)`` executor entries with misses
frozen after warmup — the executor keys on function identity + shapes,
and the wrapped closures are built once per engine. Greedy outputs are
bit-identical to the single-device engine on a fitting config
(attention partitions per head; the per-proj reduces are the only
cross-device math), which the ``sharded_serve`` bench suite and
tests/test_sharded.py pin.

Composition: ``nezha-serve --replicas N --mesh M`` gives N routed
replicas x M-device meshes — the router/supervisor never sees the mesh
(a sharded replica answers the same HTTP surface), so the two scale
axes multiply without new protocol. Migration composes too:
``export_block_payload`` gathers the head shards into the full-head
int8+scales wire payload (gather-on-export), and installs scatter back
into whatever mesh the destination runs.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

import jax

from jax.sharding import NamedSharding, PartitionSpec as P

from nezha_tpu import faults, obs
from nezha_tpu.parallel.gspmd import auto_partitioner_scope
from nezha_tpu.parallel.mesh import make_mesh
from nezha_tpu.serve.engine import Engine, ServeConfig
from nezha_tpu.serve.sharded.pool import ShardedPagedSlotPool
from nezha_tpu.serve.sharded.reshard import (place_variables,
                                             serve_tp_rules)


class ShardedEngine(Engine):
    """The M-device tensor-parallel serve engine. Drop-in for
    :class:`~nezha_tpu.serve.engine.Engine` everywhere the scheduler,
    migration, and front ends are concerned — the mesh is an internal
    axis, not a protocol change. ``mesh_devices=1`` is a valid
    degenerate mesh (useful for A/B parity runs on one device)."""

    _seq_prefill_capable = True

    def __init__(self, model, variables, cfg: ServeConfig = ServeConfig(),
                 *, mesh_devices: int, devices: Optional[Sequence] = None,
                 rules=None, draft_model=None, draft_variables=None):
        m = int(mesh_devices)
        if m < 1:
            raise ValueError(f"mesh_devices must be >= 1, got {m}")
        if cfg.kv_layout != "paged":
            raise ValueError(
                "the sharded engine requires kv_layout='paged' — the "
                "dense layout has no head-sharded pool")
        # Sequence-sharded prefill (PR 20). NEZHA_NO_SEQ_PREFILL=1 is
        # the runtime escape hatch: fall back to the replicated prefill
        # path — the long buckets keep serving the same prompts, only
        # the chunk attention stops sharding over the sequence axis.
        import os
        if (cfg.prefill_mode == "sequence"
                and os.environ.get("NEZHA_NO_SEQ_PREFILL")):
            cfg = dataclasses.replace(cfg, prefill_mode="replicated")
        self._seq_active = cfg.prefill_mode == "sequence"
        self._seq_variant = None
        if self._seq_active:
            if m < 2:
                raise ValueError(
                    "prefill_mode='sequence' requires mesh_devices > 1 "
                    "— there is no sequence axis to shard over on a "
                    "degenerate 1-device mesh")
            bad = [w for w in cfg.all_prefill_buckets if w % m]
            if bad:
                raise ValueError(
                    f"prefill_mode='sequence' needs every prefill "
                    f"bucket width divisible by mesh_devices={m}; "
                    f"offending buckets: {bad} (size prefill_buckets/"
                    f"long_prefill_buckets accordingly)")
            # "auto" resolves to ulysses: the engine's head-
            # divisibility requirement above guarantees H % M == 0,
            # and ulysses is the bitwise-parity layout (RUNBOOK §8).
            self._seq_variant = ("ulysses"
                                 if cfg.seq_prefill_variant == "auto"
                                 else cfg.seq_prefill_variant)
        avail = list(devices) if devices is not None else jax.devices()
        if m > len(avail):
            raise ValueError(
                f"mesh_devices={m} but only {len(avail)} device(s) "
                f"visible (force host devices with "
                f"--xla_force_host_platform_device_count on CPU)")
        if model.cfg.num_heads % m:
            raise ValueError(
                f"num_heads={model.cfg.num_heads} not divisible by "
                f"mesh_devices={m} — K/V pools shard on the head axis")
        # The 1xM serve mesh: one replica, M tensor shards. Axis name
        # 'tp' on purpose — the training rule table and the nested
        # shard_map kernel paths key on it.
        self.mesh = make_mesh({"tp": m}, devices=avail[:m])
        self.mesh_devices = m
        self._rules = (rules if rules is not None
                       else serve_tp_rules(model.cfg, m))
        variables = place_variables(variables, self.mesh, self._rules)
        if draft_variables is not None and draft_model is not None:
            draft_variables = place_variables(
                draft_variables, self.mesh,
                serve_tp_rules(draft_model.cfg, m))
        # Output-sharding pins for the program wrapper (created BEFORE
        # super().__init__, which builds the programs through the
        # hooks): cache pytrees stay head-sharded, everything else
        # replicates. P(None, "tp") partitions axis 1 for both leaf
        # ranks in play — [N, H, bs, D] K/V blocks and [N, H] scales.
        self._kv_out = NamedSharding(self.mesh, P(None, "tp"))
        self._rep_out = NamedSharding(self.mesh, P())
        # super().__init__ builds pools and programs through the two
        # subsystem hooks below; a self-draft built inside it SHARES
        # the placed target leaves, so its params arrive sharded free.
        super().__init__(model, variables, cfg, draft_model=draft_model,
                         draft_variables=draft_variables)
        # Commit the per-row engine state (logits, positions, keys,
        # sampling params, completion state) to the mesh REPLICATED at
        # construction: combined with the wrapper's output constraints
        # below, every dispatch of a program sees one stable sharding
        # signature — without this, the first trace keys on
        # uncommitted zeros and the second dispatch pays a hidden
        # whole-program recompile (measured: ~100x one prefill's cost).
        for name in ("last_logits", "positions", "keys", "temps",
                     "top_ks", "top_ps", "eos_ids", "budgets"):
            setattr(self, name,
                    jax.device_put(getattr(self, name), self._rep_out))
        if self.spec is not None:
            self.residual = jax.device_put(self.residual, self._rep_out)
        obs.gauge("serve.mesh.devices").set(m)
        # How many sequence shards each prefill chunk spreads over
        # (0 = replicated prefill). Re-pinned per prefill call, like
        # kernel_active — bench harnesses reset the registry after
        # warmup.
        obs.gauge("serve.prefill.seq_shards").set(
            float(m) if self._seq_active else 0.0)
        # The base engine resolved prefill-kernel activeness for the
        # raw-Mosaic path; under the partitioner the kernel runs as a
        # nested shard_map instead, so the nested-kernel escape hatch
        # ALSO kills it here — re-pin the gauge when it does.
        import os
        if self.prefill_kernel_active \
                and os.environ.get("NEZHA_NO_NESTED_KERNELS"):
            self.prefill_kernel_active = False
            obs.gauge("serve.prefill.kernel_active").set(0.0)
        # Trace-shape estimate of the cross-shard collective payload
        # per TOKEN through the target model: the SPMD partitioner
        # inserts one activation reduce after each row-parallel proj
        # (attention out + MLP out -> 2 per layer), fp32-width — the
        # same trace-time accounting idiom PR 1 uses for the training
        # collectives. 0 on a degenerate 1-device mesh.
        c = model.cfg
        self._coll_bytes_per_token = (
            0 if m == 1 else 2 * c.num_layers * c.hidden_size * 4)

    # ----------------------------------------------- subsystem hooks
    def _make_paged_pool(self, model, *, num_blocks, prefix_cache,
                         eviction, quantized, host_blocks=0):
        cfg = self.cfg
        return ShardedPagedSlotPool(
            model, cfg.max_batch_size, cfg.max_len, cfg.cache_dtype,
            mesh=self.mesh, block_size=cfg.kv_block_size,
            num_blocks=num_blocks, prefix_cache=prefix_cache,
            eviction=eviction, quantized=quantized,
            host_blocks=host_blocks)

    def _make_dense_pool(self, model):
        raise ValueError("the sharded engine has no dense pool")

    def _wrap_program(self, fn):
        """Every frozen program traces under the auto-partitioner scope
        carrying the serve mesh: model code sees the mesh (TPU decode
        attention drops to the per-shard nested-shard_map kernel;
        Mosaic is never handed to the partitioner raw) and XLA inserts
        the collectives. One wrapper per built program, created once in
        ``__init__`` — the executor keys on the wrapper's identity, so
        the frozen-program contract counts exactly as before.

        Outputs are sharding-PINNED: cache pytrees (the list-shaped
        elements — target and draft caches alike) stay head-sharded,
        every other output replicates. Pinning is what makes each
        program's input signature a FIXED POINT — its state outputs
        feed the next dispatch with the same shardings the first trace
        committed, so no dispatch after the first ever recompiles
        (the per-mesh frozen-program contract, at the jit level as
        well as the executor level)."""
        mesh = self.mesh
        kv_out, rep_out = self._kv_out, self._rep_out

        def pin(out):
            if isinstance(out, list):       # a per-layer caches list
                return jax.tree_util.tree_map(
                    lambda x: jax.lax.with_sharding_constraint(
                        x, kv_out), out)
            if isinstance(out, tuple):
                return tuple(pin(o) for o in out)
            return jax.lax.with_sharding_constraint(out, rep_out)

        def sharded_program(*args):
            with auto_partitioner_scope(mesh):
                return pin(fn(*args))

        return sharded_program

    def _wrap_prefill_program(self, fn):
        """In sequence mode the bucket programs trace with the
        seq-prefill scope nested inside the partitioner scope, so the
        model's prefill-chunk branch builds the nested sequence-sharded
        shard_map (serve/sharded/seq_prefill.py — importing it here is
        also what arms the model's ``sys.modules`` probe). Step/decode
        programs never come through this hook and stay untouched;
        replicated mode is the plain :meth:`_wrap_program`, byte for
        byte."""
        if not self._seq_active:
            return self._wrap_program(fn)
        from nezha_tpu.serve.sharded import seq_prefill

        inner = self._wrap_program(fn)
        mesh, variant = self.mesh, self._seq_variant

        def seq_program(*args):
            with seq_prefill.seq_prefill_scope(mesh, variant):
                return inner(*args)

        return seq_program

    # ------------------------------------------------------- dispatch
    def prefill(self, slot: int, tokens, **kwargs) -> None:
        if self._seq_active:
            # Chunk-retirement drill point for sequence mode: a seeded
            # fault here must retire ONLY the victim request with zero
            # slot/block/scale leaks on every shard (tests/chaos).
            faults.point("serve.prefill.seq")
            obs.gauge("serve.prefill.seq_shards").set(
                float(self.mesh_devices))
            with obs.span("serve.prefill.seq_s"):
                super().prefill(slot, tokens, **kwargs)
            if self._seq_variant == "ring":
                # One ring rotation per chunk: every shard's block
                # travels the full ring, world hops per chunk program.
                obs.counter("serve.prefill.ring_hops_total").inc(
                    self.mesh_devices * self.last_prefill_chunks)
        else:
            super().prefill(slot, tokens, **kwargs)
        if self._coll_bytes_per_token:
            # The tokens the compiled chunks ACTUALLY pushed through
            # the target model (bucket pads included, a prefix hit's
            # cached span excluded — the base prefill records it).
            obs.counter("serve.mesh.collective_bytes").inc(
                self.last_prefill_tokens
                * self._coll_bytes_per_token)

    def step(self, active: np.ndarray):
        out = super().step(active)
        if self._coll_bytes_per_token:
            obs.counter("serve.mesh.collective_bytes").inc(
                self.cfg.max_batch_size * self.tokens_per_dispatch
                * self._coll_bytes_per_token)
        return out

    # ----------------------------------------------------- accounting
    def memory_report(self) -> dict:
        """Exact per-device vs logical byte accounting — the proof
        instrument for "``--mesh M`` serves a config whose KV + params
        exceed a single device's budget". Params are summed from the
        committed leaves' addressable shards on the mesh's first device
        (replicated leaves count full size there — honest: each device
        really holds them); KV is the pools' CAPACITY (all blocks), the
        number a budget must provision for, not the instantaneous
        ``bytes_resident``."""
        dev0 = self.mesh.devices.flat[0]

        def dev_bytes(tree):
            total = shard = 0
            for leaf in jax.tree_util.tree_leaves(tree):
                if not isinstance(leaf, jax.Array):
                    continue
                total += leaf.nbytes
                shard += sum(s.data.nbytes
                             for s in leaf.addressable_shards
                             if s.device == dev0)
            return total, shard

        p_total, p_shard = dev_bytes(self.variables)
        pools = [self.pool.caches]
        if self.draft_pool is not None:
            pools.append(self.draft_pool.caches)
        k_total = k_shard = 0
        for caches in pools:
            t, s = dev_bytes(caches)
            k_total += t
            k_shard += s
        return {
            "mesh_devices": self.mesh_devices,
            "params_bytes": p_total,
            "params_bytes_per_device": p_shard,
            "kv_capacity_bytes": k_total,
            "kv_capacity_bytes_per_device": k_shard,
            "bytes_total": p_total + k_total,
            "bytes_per_device": p_shard + k_shard,
        }
