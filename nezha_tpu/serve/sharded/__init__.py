"""Tensor-sharded serving: one replica spread over an M-device mesh.

The subsystem behind ``nezha-serve --mesh M`` and ``nezha-reshard``:

- :class:`~nezha_tpu.serve.sharded.engine.ShardedEngine` — the
  frozen-program engine with parameters Megatron-sharded and the paged
  K/V pools head-sharded across a 1xM ``tp`` mesh; block tables and
  every other piece of pool bookkeeping stay host-side and
  layout-identical to the single-device engine.
- :class:`~nezha_tpu.serve.sharded.pool.ShardedPagedSlotPool` — one
  logical block pool, M physical shards; ``bytes_resident_per_shard``
  is the per-device budget instrument.
- :mod:`~nezha_tpu.serve.sharded.reshard` — train-topology checkpoint
  -> serve-mesh parameters, streamed one leaf at a time with CRC
  verification; typed :class:`ReshardError` means the engine refuses
  to start rather than serving garbage.

Composes with the other scale axis: ``--replicas N --mesh M`` = N
routed replicas x M-device meshes (the router never sees the mesh).
"""

from nezha_tpu.serve.sharded.engine import ShardedEngine
from nezha_tpu.serve.sharded.pool import ShardedPagedSlotPool
from nezha_tpu.serve.sharded.reshard import (
    ReshardError,
    place_variables,
    reshard_checkpoint,
    save_serve_checkpoint,
    serve_shardings,
    serve_tp_rules,
    verify_roundtrip,
)

__all__ = [
    "ShardedEngine", "ShardedPagedSlotPool", "ReshardError",
    "place_variables", "reshard_checkpoint", "save_serve_checkpoint",
    "serve_shardings", "serve_tp_rules", "verify_roundtrip",
]
