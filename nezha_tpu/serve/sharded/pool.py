"""Head-sharded paged KV pool: one logical pool, M physical shards.

:class:`ShardedPagedSlotPool` is the PR 7 block-paged pool laid out
across a serve mesh: every per-layer K/V buffer
(``[num_blocks, H, block_size, D]``) and per-block scale row
(``[num_blocks, H]``, int8 pools) is committed to the mesh with the
HEAD axis partitioned over ``tp`` — block ``b`` exists on every device,
each device holding its ``H / M`` head slice of it. Everything
host-side is **deliberately unchanged and unsharded**: the free list,
ref counts, per-slot block tables, bound counts, and the prefix trie
are exactly PR 7's single bookkeeping state, because a block is a
LOGICAL unit — binding, COW, eviction, and the write-at-ref==1
invariant are decisions about block *identities*, which are mesh-
invariant. The ``mesh-host-side-tables`` lint rule pins the other
direction of that split: none of this host state may ever be mutated
from inside a ``shard_map``-lowered body.

What this buys:

- capacity scales with M — ``bytes_resident`` is the logical total,
  :attr:`bytes_resident_per_shard` what each device actually holds
  (the acceptance instrument for "a model whose KV exceeds one
  device's budget serves on ``--mesh M``");
- the COW / gather / scatter device ops (slots.py module jits) work
  verbatim: they are leading-axis (block-indexed) ops over the caches
  pytree, so XLA partitions them trivially along the untouched head
  axis, and a donated rewrite stays a per-shard rewrite;
- migration is GATHER-ON-EXPORT: ``export_block_payload`` already
  converts the gathered blocks to host arrays, which assembles the
  full-head wire payload from the shards — the int8+scales wire format
  (and the installer on any mesh size) is unchanged. A per-shard pull
  protocol is the noted follow-up.

``leak_check`` extends the PR 7 oracle per shard: besides the ref-count
books, every cache leaf must still be partitioned over ``tp`` (a
program or maintenance op that silently replicated the pool would
multiply resident bytes by M — exactly the regression the sharded
engine exists to prevent).
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import jax.numpy as jnp

from nezha_tpu.serve.slots import PagedSlotPool


class ShardedPagedSlotPool(PagedSlotPool):
    """PR 7's paged pool with device state committed head-sharded over
    a serve mesh (axis name ``tp``). Host bookkeeping is inherited
    UNCHANGED — one logical pool, M physical shards."""

    def __init__(self, model, capacity: int, max_len: int,
                 dtype=jnp.bfloat16, *, mesh: Mesh,
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 prefix_cache: bool = True, eviction: str = "lru",
                 quantized: bool = False, host_blocks: int = 0):
        if "tp" not in mesh.axis_names:
            raise ValueError(
                f"serve mesh must carry a 'tp' axis, got "
                f"{mesh.axis_names}")
        tp = int(mesh.shape["tp"])
        if model.cfg.num_heads % tp:
            raise ValueError(
                f"num_heads={model.cfg.num_heads} not divisible by the "
                f"mesh's tp={tp} — the KV pools shard on the head axis")
        # The host tier composes unchanged: demotion is the migration
        # export gather (gather-on-export assembles full heads from
        # the shards) and promotion the migration install scatter
        # (XLA partitions the leading-axis write along the untouched
        # head axis), so one host payload format serves every mesh.
        super().__init__(model, capacity, max_len, dtype,
                         block_size=block_size, num_blocks=num_blocks,
                         prefix_cache=prefix_cache, eviction=eviction,
                         quantized=quantized, host_blocks=host_blocks)
        self.mesh = mesh
        self._kv_sharding = NamedSharding(mesh, P(None, "tp"))
        self.caches = self._place(self.caches)

    def _place(self, caches):
        """Commit every block-indexed leaf to the head sharding. One
        spec serves both leaf ranks: ``P(None, "tp")`` partitions axis
        1 (heads) and replicates the rest, for ``[N, H, bs, D]`` data
        and ``[N, H]`` scale rows alike."""
        return [{k: jax.device_put(v, self._kv_sharding)
                 for k, v in layer.items()} for layer in caches]

    # ------------------------------------------------------ accounting
    @property
    def shard_devices(self) -> int:
        """Mesh size M — how many physical shards the logical pool has."""
        return int(self.mesh.shape["tp"])

    @property
    def bytes_resident_per_shard(self) -> int:
        """Device bytes ONE shard holds for the resident blocks: the
        head axis divides exactly (validated at construction), so each
        device carries ``bytes_resident / M``. This is the number the
        per-device memory budget is judged against — and the reason a
        config whose logical pool exceeds one device fits under
        ``--mesh M``."""
        return self.bytes_resident // self.shard_devices

    # -------------------------------------------------------- invariants
    def leak_check(self) -> None:
        """PR 7's ref-count oracle, extended per shard: every cache
        leaf must still be PARTITIONED over the mesh's tp axis. A
        maintenance path that rebuilt the caches tree without the
        sharding (or a program whose output XLA chose to replicate)
        would silently multiply resident device bytes by M — a leak in
        the capacity dimension this subsystem exists to scale."""
        super().leak_check()
        if self.shard_devices > 1:
            for li, layer in enumerate(self.caches):
                for key, leaf in layer.items():
                    sh = getattr(leaf, "sharding", None)
                    if sh is None or sh.is_fully_replicated:
                        raise AssertionError(
                            f"layer {li} {key!r} pool leaf lost its "
                            f"head sharding (fully replicated across "
                            f"the {self.shard_devices}-device mesh) — "
                            f"resident bytes silently multiplied")
