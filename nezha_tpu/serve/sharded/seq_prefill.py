"""Sequence-sharded prefill: one chunk's attention spread over the mesh.

``prefill_mode="sequence"`` (``nezha-serve --prefill-mode sequence``)
splits each prefill chunk's QUERY rows across the 1xM ``tp`` mesh so an
8k-32k document prompt stops monopolizing a replica for its whole
prefill — the long-context knob on top of the head-sharded pools PR 14
placed. Finished blocks land DIRECTLY in the head-sharded paged pool,
so decode proceeds completely unchanged.

Two layouts, selected by ``ServeConfig.seq_prefill_variant``:

- ``"ulysses"`` (the auto default whenever ``H % M == 0`` — always true
  under :class:`ShardedEngine`, which requires head-divisible pools):
  one ``lax.all_to_all`` reshards the chunk from the sequence domain to
  the head domain, each shard then runs the EXACT replicated prefill
  computation on its own ``H/M`` heads (the PR 18 flash-prefill kernel,
  fused int8 epilogue write included, or the composed masked mirror),
  and a reverse all-to-all restores the sequence layout. Per-head math
  is untouched and the all-to-alls only move data, so this variant is
  BIT-IDENTICAL to the replicated path — the parity gate the bench
  suite enforces.
- ``"ring"``: ``lax.ppermute`` neighbour hops, reusing
  ``parallel/ring.py``'s online-softmax hop fold. On float pools with
  the kernel available, the Q blocks circulate ("ring-q"): every hop
  runs ONE paged flash-prefill program on the traveling Q slice's own
  heads with a per-row global ``q_offsets`` operand — each (Q block,
  head group) pair is computed completely by exactly one shard, so no
  softmax merge is needed and the result is bitwise identical to the
  replicated kernel per row. The composed fallback ("ring-KV")
  circulates the gathered own-head paged PREFIX blocks instead and
  merges prefix and chunk attention by log-sum-exp
  (:func:`~nezha_tpu.parallel.ring.ring_attention_lse`); its reduction
  ORDER differs from the replicated composed path, so it carries a
  greedy-token parity guarantee rather than a bitwise one. Int8 pools
  under ring fall back to the composed per-shard
  ``_quant_prefill_write`` chain (the fused epilogue needs the full
  chunk's queries resident — prefer ulysses for int8, see RUNBOOK §8).

The module is a TRACE-TIME switch, not a runtime one:
:func:`seq_prefill_scope` is a contextvar scope (the
``auto_partitioner_scope`` idiom) that :class:`ShardedEngine` enters
while tracing its bucket programs; ``models/gpt2`` checks it through
``sys.modules`` (zero cost unless serving sequence mode ever imported
this module) and routes its paged prefill-chunk branch here. One
nested ``shard_map`` per bucket program — the frozen
``1 + len(buckets)`` program contract per (mesh, bucket) is untouched.
"""

from __future__ import annotations

import dataclasses
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

VARIANTS = ("auto", "ulysses", "ring")

_SEQ_PREFILL: ContextVar[Optional["SeqPrefillParams"]] = ContextVar(
    "nezha_seq_prefill", default=None)


@dataclasses.dataclass(frozen=True)
class SeqPrefillParams:
    """What the model needs to know to build the nested shard_map."""
    mesh: object          # the serve mesh (has a "tp" axis)
    variant: str          # "ulysses" | "ring" (resolved, never "auto")


@contextmanager
def seq_prefill_scope(mesh, variant: str):
    """Mark the dynamic extent of a prefill-program trace as
    sequence-sharded (``auto_partitioner_scope``'s contextvar idiom —
    composes with it; the sharded engine nests this inside). ``variant``
    must already be resolved (not ``"auto"``)."""
    if variant not in ("ulysses", "ring"):
        raise ValueError(
            f"seq_prefill_scope needs a resolved variant, got {variant!r}")
    token = _SEQ_PREFILL.set(SeqPrefillParams(mesh=mesh, variant=variant))
    try:
        yield
    finally:
        _SEQ_PREFILL.reset(token)


def seq_prefill_params() -> Optional[SeqPrefillParams]:
    """The active scope's params, or None outside any scope."""
    return _SEQ_PREFILL.get()


def _check_divisible(s: int, h: int, world: int):
    if s % world:
        raise ValueError(
            f"sequence-sharded prefill needs the chunk width ({s}) "
            f"divisible by the mesh size ({world}) — size prefill "
            f"buckets accordingly (ServeConfig validates this)")
    if h % world:
        raise ValueError(
            f"sequence-sharded prefill needs num_heads ({h}) divisible "
            f"by the mesh size ({world})")


def _composed_shard_attention(qh, k_pool, v_pool, tab, pos, scales,
                              *, L, d):
    """The replicated composed masked-attention expression, restricted
    to one shard's head slice — kept in lockstep with
    ``models/gpt2._apply_paged``'s composed branch so the ulysses
    mirror stays bit-identical to the single-device path."""
    from nezha_tpu import ops

    b, hh, s, _ = qh.shape
    if scales is not None:
        from nezha_tpu.ops.quant import dequantize_kv_block
        ks, vs = scales
        k_all = dequantize_kv_block(k_pool[tab], ks[tab], qh.dtype)
        v_all = dequantize_kv_block(v_pool[tab], vs[tab], qh.dtype)
    else:
        k_all, v_all = k_pool[tab], v_pool[tab]
    k_all = k_all.transpose(0, 2, 1, 3, 4).reshape(b, hh, L, d)
    v_all = v_all.transpose(0, 2, 1, 3, 4).reshape(b, hh, L, d)
    abs_q = pos + jnp.arange(s)[:, None]
    attendable = jnp.arange(L)[None, :] <= abs_q
    mask = jnp.where(attendable, 0.0, -jnp.inf).astype(jnp.float32)
    return ops.dot_product_attention(qh, k_all.astype(qh.dtype),
                                     v_all.astype(qh.dtype), mask=mask)


def _float_scatter_write(kp, vp, tab, pos, kh, vh, *, L, bs_kv, m):
    """The replicated float chunk write (one XLA scatter through the
    table), per shard on its own heads — same expression as
    ``_apply_paged``."""
    s = kh.shape[2]
    ppos = jnp.minimum(pos + jnp.arange(s), L - 1)
    bi = jnp.clip(ppos // bs_kv, 0, m - 1)
    blk = tab[:, bi]                                        # [b, s]
    off = (ppos % bs_kv)[None, :]                           # [1, s]
    kp = kp.at[blk, :, off, :].set(kh.transpose(0, 2, 1, 3).astype(kp.dtype))
    vp = vp.at[blk, :, off, :].set(vh.transpose(0, 2, 1, 3).astype(vp.dtype))
    return kp, vp


def seq_prefill_attention(q, k_chunk, v_chunk, k_pool, v_pool,
                          block_tables, starts, *, mesh,
                          variant: str = "ulysses",
                          use_kernel: bool = False,
                          block_scales=None,
                          scale: Optional[float] = None,
                          interpret: Optional[bool] = None):
    """Sequence-sharded paged prefill-chunk attention + pool write.

    Same operand contract as
    :func:`~nezha_tpu.ops.pallas.flash_prefill_attention`:
    ``q/k_chunk/v_chunk [B, H, S, D]`` fresh chunk projections (global
    values under the engine's auto-partitioner trace), pools
    ``[N, H, bs, D]`` head-sharded ``P(None, "tp")`` on ``mesh``,
    ``block_tables [B, M]`` / ``starts [B]`` replicated host
    bookkeeping. ``starts`` must be a per-row broadcast of the chunk's
    scalar offset (the engine's chunk programs guarantee it — the
    composed mirrors index with ``starts[0]``).

    Returns the UNIFORM 6-tuple
    ``(out, k_pool', v_pool', k_scales', v_scales', qerr)`` — float
    pools pass scales through as ``None`` with ``qerr=None``; int8
    pools return fresh scales and the max-abs requant error (already
    ``pmax``-reduced over the mesh).
    """
    from nezha_tpu.parallel._compat import shard_map

    axis = "tp"
    world = int(mesh.shape[axis])
    b, H, s, d = q.shape
    _check_divisible(s, H, world)
    hh = H // world
    s_loc = s // world
    bs_kv = k_pool.shape[2]
    m = block_tables.shape[1]
    L = m * bs_kv
    quant = block_scales is not None
    if variant not in ("ulysses", "ring"):
        raise ValueError(f"unknown seq-prefill variant {variant!r}")
    if variant == "ulysses" and H % world:
        raise ValueError(
            f"ulysses needs num_heads ({H}) divisible by mesh ({world})")

    sspec = P(None, None, axis, None)   # activations: sequence axis
    hspec = P(None, axis)               # pools/scales: head axis
    rep = P()

    def seq_to_heads(x):
        # [b, H, s/M, d] local -> [b, H/M, s, d]: the ulysses move.
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    tab32 = jnp.asarray(block_tables, jnp.int32)
    starts32 = jnp.asarray(starts, jnp.int32)

    if variant == "ulysses":
        return _ulysses(q, k_chunk, v_chunk, k_pool, v_pool, tab32,
                        starts32, block_scales, shard_map, mesh, axis,
                        sspec, hspec, rep, seq_to_heads, heads_to_seq,
                        use_kernel=use_kernel, scale=scale,
                        interpret=interpret, L=L, bs_kv=bs_kv, m=m, d=d)
    return _ring(q, k_chunk, v_chunk, k_pool, v_pool, tab32, starts32,
                 block_scales, shard_map, mesh, axis, sspec, hspec,
                 rep, world=world, hh=hh, s_loc=s_loc,
                 use_kernel=use_kernel, scale=scale,
                 interpret=interpret, L=L, bs_kv=bs_kv, m=m, d=d)


def _ulysses(q, k, v, kp, vp, tab, starts, block_scales, shard_map,
             mesh, axis, sspec, hspec, rep, seq_to_heads, heads_to_seq,
             *, use_kernel, scale, interpret, L, bs_kv, m, d):
    """All-to-all variant: per shard, the EXACT replicated computation
    on its own head group — bitwise parity by construction."""
    from nezha_tpu.ops.pallas import flash_prefill_attention

    if block_scales is not None:
        ks, vs = block_scales

        def body(q_, k_, v_, kp_, vp_, tab_, st_, ks_, vs_):
            qh, kh, vh = (seq_to_heads(q_), seq_to_heads(k_),
                          seq_to_heads(v_))
            if use_kernel:
                out, kp_n, vp_n, ks_n, vs_n, qerr = \
                    flash_prefill_attention(
                        qh, kh, vh, kp_, vp_, tab_, st_, scale=scale,
                        interpret=interpret, block_scales=(ks_, vs_))
            else:
                from nezha_tpu.models.gpt2 import _quant_prefill_write
                pos = st_[0]
                sc = kh.shape[2]
                kp_n, ks_n, ek = _quant_prefill_write(kp_, ks_, tab_,
                                                      pos, kh, sc)
                vp_n, vs_n, ev = _quant_prefill_write(vp_, vs_, tab_,
                                                      pos, vh, sc)
                qerr = jnp.maximum(ek, ev)
                out = _composed_shard_attention(
                    qh, kp_n, vp_n, tab_, pos, (ks_n, vs_n), L=L, d=d)
            return (heads_to_seq(out), kp_n, vp_n, ks_n, vs_n,
                    lax.pmax(qerr, axis))

        f = shard_map(body, mesh=mesh,
                      in_specs=(sspec, sspec, sspec, hspec, hspec, rep,
                                rep, hspec, hspec),
                      out_specs=(sspec, hspec, hspec, hspec, hspec,
                                 rep))
        out, kp_n, vp_n, ks_n, vs_n, qerr = f(q, k, v, kp, vp, tab,
                                              starts, ks, vs)
        return out, kp_n, vp_n, ks_n, vs_n, qerr

    def body(q_, k_, v_, kp_, vp_, tab_, st_):
        qh, kh, vh = (seq_to_heads(q_), seq_to_heads(k_),
                      seq_to_heads(v_))
        pos = st_[0]
        kp_n, vp_n = _float_scatter_write(kp_, vp_, tab_, pos, kh, vh,
                                          L=L, bs_kv=bs_kv, m=m)
        if use_kernel:
            out = flash_prefill_attention(qh, kh, vh, kp_n, vp_n, tab_,
                                          st_, scale=scale,
                                          interpret=interpret)
        else:
            out = _composed_shard_attention(qh, kp_n, vp_n, tab_, pos,
                                            None, L=L, d=d)
        return heads_to_seq(out), kp_n, vp_n

    f = shard_map(body, mesh=mesh,
                  in_specs=(sspec, sspec, sspec, hspec, hspec, rep,
                            rep),
                  out_specs=(sspec, hspec, hspec))
    out, kp_n, vp_n = f(q, k, v, kp, vp, tab, starts)
    return out, kp_n, vp_n, None, None, None


def _ring(q, k, v, kp, vp, tab, starts, block_scales, shard_map, mesh,
          axis, sspec, hspec, rep, *, world, hh, s_loc, use_kernel,
          scale, interpret, L, bs_kv, m, d):
    """Neighbour-hop variant. Float + kernel circulates Q blocks
    ("ring-q", bitwise); otherwise the gathered own-head paged prefix
    circulates and merges with the chunk's ring attention by
    log-sum-exp ("ring-KV", greedy parity)."""
    from nezha_tpu.ops.pallas import flash_prefill_attention
    from nezha_tpu.parallel.ring import _NEG_BIG, ring_attention_lse

    perm = [(i, (i + 1) % world) for i in range(world)]
    quant = block_scales is not None
    b = q.shape[0]
    s = q.shape[2]

    def head_domain(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    if not quant and use_kernel:
        # ring-q: the traveling Q block meets each shard's resident
        # head group exactly once; q_offsets puts the kernel's causal
        # diagonal at the block's GLOBAL offset, so every (Q block,
        # head group) result is complete — no merge, bitwise parity.
        def body(q_, k_, v_, kp_, vp_, tab_, st_):
            idx = lax.axis_index(axis)
            kh, vh = head_domain(k_), head_domain(v_)
            pos = st_[0]
            kp_n, vp_n = _float_scatter_write(kp_, vp_, tab_, pos, kh,
                                              vh, L=L, bs_kv=bs_kv,
                                              m=m)

            def hop(i, carry):
                q_cur, o_cur = carry
                src = (idx - i) % world
                q_sl = lax.dynamic_slice(
                    q_cur, (0, idx * hh, 0, 0), (b, hh, s_loc, d))
                o_i = flash_prefill_attention(
                    q_sl, kh, vh, kp_n, vp_n, tab_, st_, scale=scale,
                    interpret=interpret,
                    q_offsets=st_ + src * s_loc)
                o_cur = lax.dynamic_update_slice(
                    o_cur, o_i.astype(o_cur.dtype), (0, idx * hh, 0, 0))
                # The collective stays OUTSIDE any conditional — every
                # rank participates every hop (ring.py's rule).
                return (lax.ppermute(q_cur, axis, perm),
                        lax.ppermute(o_cur, axis, perm))

            _, out = lax.fori_loop(0, world, hop,
                                   (q_, jnp.zeros_like(q_)))
            return out, kp_n, vp_n

        f = shard_map(body, mesh=mesh,
                      in_specs=(sspec, sspec, sspec, hspec, hspec, rep,
                                rep),
                      out_specs=(sspec, hspec, hspec))
        out, kp_n, vp_n = f(q, k, v, kp, vp, tab, starts)
        return out, kp_n, vp_n, None, None, None

    # ring-KV composed: write first (the replicated composed ordering),
    # ring the chunk's self-attention over fresh operands, ring the
    # gathered own-head prefix, merge by log-sum-exp.
    def body(q_, k_, v_, kp_, vp_, tab_, st_, *scargs):
        idx = lax.axis_index(axis)
        kh, vh = head_domain(k_), head_domain(v_)
        pos = st_[0]
        if quant:
            from nezha_tpu.models.gpt2 import _quant_prefill_write
            ks_, vs_ = scargs
            kp_n, ks_n, ek = _quant_prefill_write(kp_, ks_, tab_, pos,
                                                  kh, s)
            vp_n, vs_n, ev = _quant_prefill_write(vp_, vs_, tab_, pos,
                                                  vh, s)
            qerr = lax.pmax(jnp.maximum(ek, ev), axis)
            from nezha_tpu.ops.quant import dequantize_kv_block
            kd = dequantize_kv_block(kp_n[tab_], ks_n[tab_], q_.dtype)
            vd = dequantize_kv_block(vp_n[tab_], vs_n[tab_], q_.dtype)
        else:
            kp_n, vp_n = _float_scatter_write(kp_, vp_, tab_, pos, kh,
                                              vh, L=L, bs_kv=bs_kv,
                                              m=m)
            kd = kp_n[tab_].astype(q_.dtype)
            vd = vp_n[tab_].astype(q_.dtype)
        # Own-head dense prefix view [b, hh, L, d] — this is the block
        # that circulates ("ring-passed paged K/V").
        kd = kd.transpose(0, 2, 1, 3, 4).reshape(b, hh, L, d)
        vd = vd.transpose(0, 2, 1, 3, 4).reshape(b, hh, L, d)

        # Chunk part: parallel/ring.py's online-softmax hop fold over
        # the fresh seq-sharded operands (all heads, local queries).
        out_c, lse_c = ring_attention_lse(q_, k_, v_, axis,
                                          causal=True, scale=scale,
                                          use_flash=False)

        sc = scale if scale is not None else 1.0 / (d ** 0.5)
        prefix_len = st_[:, None, None, None]                # [b,1,1,1]
        kpos = jnp.arange(L)[None, None, None, :]

        def hop(i, carry):
            mx, l, acc, kd_cur, vd_cur = carry
            # After i hops the resident block covers head group src.
            src = (idx - i) % world
            q_h = lax.dynamic_slice(q_, (0, src * hh, 0, 0),
                                    (b, hh, s_loc, d))
            scores = jnp.einsum(
                "bhqd,bhkd->bhqk", q_h, kd_cur,
                preferred_element_type=jnp.float32) * sc
            attendable = kpos < prefix_len
            scores = jnp.where(attendable, scores, _NEG_BIG)
            m_src = jnp.max(scores, axis=-1, keepdims=True)
            # Masked lanes zero EXPLICITLY: an empty prefix would
            # otherwise see exp(_NEG_BIG - _NEG_BIG) = 1 per lane.
            p = jnp.where(attendable, jnp.exp(scores - m_src), 0.0)
            l_src = jnp.sum(p, axis=-1, keepdims=True)
            acc_src = jnp.einsum("bhqk,bhkd->bhqd",
                                 p.astype(vd_cur.dtype), vd_cur,
                                 preferred_element_type=jnp.float32)
            at = (0, src * hh, 0, 0)
            mx = lax.dynamic_update_slice(mx, m_src, at)
            l = lax.dynamic_update_slice(l, l_src, at)
            acc = lax.dynamic_update_slice(acc, acc_src, at)
            return (mx, l, acc, lax.ppermute(kd_cur, axis, perm),
                    lax.ppermute(vd_cur, axis, perm))

        H_all = q_.shape[1]
        m0 = jnp.full((b, H_all, s_loc, 1), _NEG_BIG, jnp.float32)
        l0 = jnp.zeros((b, H_all, s_loc, 1), jnp.float32)
        a0 = jnp.zeros((b, H_all, s_loc, d), jnp.float32)
        mx, l, acc, _, _ = lax.fori_loop(0, world, hop,
                                         (m0, l0, a0, kd, vd))
        out_p = acc / jnp.maximum(l, 1e-30)
        lse_p = (mx + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]

        # Log-sum-exp merge: an empty prefix carries lse_p ~ -1e30, so
        # its weight underflows to exactly zero.
        lse_t = jnp.logaddexp(lse_p, lse_c)
        w_p = jnp.exp(lse_p - lse_t)[..., None]
        w_c = jnp.exp(lse_c - lse_t)[..., None]
        out = (out_p * w_p
               + out_c.astype(jnp.float32) * w_c).astype(q_.dtype)
        if quant:
            return out, kp_n, vp_n, ks_n, vs_n, qerr
        return out, kp_n, vp_n

    if quant:
        ks, vs = block_scales
        f = shard_map(body, mesh=mesh,
                      in_specs=(sspec, sspec, sspec, hspec, hspec, rep,
                                rep, hspec, hspec),
                      out_specs=(sspec, hspec, hspec, hspec, hspec,
                                 rep))
        out, kp_n, vp_n, ks_n, vs_n, qerr = f(q, k, v, kp, vp, tab,
                                              starts, ks, vs)
        return out, kp_n, vp_n, ks_n, vs_n, qerr
    f = shard_map(body, mesh=mesh,
                  in_specs=(sspec, sspec, sspec, hspec, hspec, rep,
                            rep),
                  out_specs=(sspec, hspec, hspec))
    out, kp_n, vp_n = f(q, k, v, kp, vp, tab, starts)
    return out, kp_n, vp_n, None, None, None
