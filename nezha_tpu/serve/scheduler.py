"""Admission, retirement, and the serving iteration loop.

The scheduler owns everything request-shaped: a bounded admission
queue served by WEIGHTED FAIR QUEUEING across tenants within priority
lanes (submit past capacity fails fast — backpressure, not unbounded
memory; with every request in one lane and one tenant, the default,
WFQ degenerates to the classic bounded FIFO bit for bit), per-request
deadlines, and the continuous-batching iteration:

    admit waiters into free slots -> decode one BLOCK (up to
    ``decode_horizon`` tokens per row, one compiled dispatch) for all
    active rows -> retire rows on EOS / max-new-tokens / deadline ->
    admit again (a slot freed by retirement is refilled in the SAME
    iteration, so capacity never idles while work is queued).

The decode consumes the engine's ``[B, H]`` token block: each live
row's tokens are sliced at its device-computed emitted count (overshoot
past EOS/budget never reaches here — it was dropped on device), events
stream per token, and retire/admit runs once per horizon, so the host
cost between dispatches is paid once per H tokens. Deadlines are
checked once per block — granularity coarsens to one horizon.

Telemetry flows through ``nezha_tpu.obs`` at the serving layer's
metrics of record: ``serve.ttft_s`` (submit -> first token, placed at
the row's position WITHIN its first block) and ``serve.tpot_s``
(``block_dt / tokens_emitted`` observed once per emitted token, so
percentiles stay comparable across horizon settings) histograms,
``serve.host_gap_s`` (host time between consecutive step dispatches —
the gap the decode horizon amortizes) and ``serve.decode.horizon``
(tokens-per-dispatch ceiling in effect) histograms,
``serve.prefill.bucket_len`` (static pad width per prefill chunk — the
bucket-occupancy view), ``serve.queue_depth`` and
``serve.batch_occupancy`` gauges,
``serve.{admitted,rejected,expired,retired,tokens}_total``,
``serve.{errors,step_retries}_total`` and ``serve.prefill.chunks_total``
counters, ``faults.injected_total`` (the chaos ledger), and a
``serve.decode_attention`` span around every batched decode block —
the names tools/check_telemetry_schema.py pins. With no run active
every call site is the registry's branch-only no-op.

Distributed tracing rides the same lifecycle: a request carrying a
``trace_id`` (router-minted and forwarded on the wire, or minted at
``submit`` when this scheduler is the admission edge) emits one
per-request span fragment per lifecycle stage — ``serve.queue_wait``
(submit -> admit), ``serve.prefill`` (+ the engine's per-chunk
``serve.prefill.chunk``), ``serve.park`` / ``serve.kv_export`` (the
migration handoff), ``serve.decode_window`` (each dispatch the request
rode) and ``serve.decode`` (residency + the first-token milestone) —
all stamped with the trace id so ``nezha-telemetry RUN_DIR --trace``
can stitch the fleet's fragments into one per-request timeline.
Untraced (or sampled-out) requests emit ZERO extra spans, and with
telemetry disabled the whole layer stays branch-only no-op.

Failure isolation is request-scoped by design: a prefill exception or a
non-finite logit row retires ONLY the affected request
(``FinishReason.ERROR``, slot freed the same iteration) while the loop
keeps decoding everyone else, and a crashed ``engine.step`` gets one
bounded backoff retry before the failure surfaces. The fault-injection
layer (``nezha_tpu.faults``) manufactures all three on demand;
tests/test_faults.py proves zero slot leaks under a seeded chaos plan.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Sequence

import numpy as np

from nezha_tpu import faults, obs
from nezha_tpu.serve.engine import Engine
from nezha_tpu.serve.slots import KVBlocksExhausted


class QueueFull(Exception):
    """Admission queue at capacity — the backpressure signal. Callers
    should shed load or retry later (HTTP mode maps this to 503)."""


class TenantOverLimit(QueueFull):
    """One TENANT's queued share hit ``tenant_queue_cap`` — the typed
    per-tenant backpressure signal (PR 19). A subclass of
    :class:`QueueFull` so every existing handler still maps it to 503;
    callers that care about the distinction catch it first. Counted
    into ``serve.tenant_over_limit_total`` (and, like every shed,
    ``serve.rejected_total``)."""


# Priority classes, highest first — rank 0 outranks rank 2 when the
# preemption trigger and the WFQ tie-break compare lanes.
PRIORITIES = ("interactive", "batch", "background")
_PRIORITY_RANK = {p: i for i, p in enumerate(PRIORITIES)}

# Default WFQ admission-grant split (ServeConfig.priority_weights
# None): per 7 grants under full backlog, 4 interactive, 2 batch,
# 1 background — lower lanes are slowed, never starved.
_DEFAULT_WEIGHTS = (("interactive", 4), ("batch", 2), ("background", 1))


class FinishReason:
    EOS = "eos"
    LENGTH = "length"          # max_new_tokens reached
    DEADLINE = "deadline"      # expired (queued, mid-decode, or at the
                               # drain cutoff)
    ERROR = "error"            # prefill failure or non-finite logits —
                               # the request is retired, its slot freed,
                               # and the batch keeps decoding
    PREFILLED = "prefilled"    # prefill_only request: prompt KV computed
                               # and PARKED for migration — not an end
                               # state for the request, which decodes on
                               # whichever replica pulls (or resumes) it


@dataclasses.dataclass
class Request:
    """One generation request. ``deadline_s`` is a wall-clock budget in
    seconds from submit; expired requests are retired with whatever
    tokens they have (possibly none, if still queued)."""

    prompt: Sequence[int]
    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_id: Optional[int] = None
    seed: int = 0
    deadline_s: Optional[float] = None
    request_id: Optional[str] = None
    # Disaggregated serving (serve/migrate.py): prefill the prompt and
    # PARK the slot (blocks held under a TTL) instead of decoding — the
    # admission half of the two-phase KV handoff. The request finishes
    # with FinishReason.PREFILLED; decoding happens wherever the parked
    # KV is pulled to (or locally via resume_parked).
    prefill_only: bool = False
    # Distributed tracing: the fleet-wide trace id this request carries
    # (minted by the router at admission and forwarded on the wire, or
    # minted at submit when the field is ABSENT and a telemetry run is
    # active — subject to obs.set_trace_sample). "" = the router
    # already sampled this request OUT: honored as untraced, never
    # re-minted. Untraced requests' lifecycles emit ZERO extra spans.
    trace_id: Optional[str] = None
    # Multi-tenant scheduling (PR 19): the WFQ lane this request queues
    # in (one of PRIORITIES; the default keeps every pre-PR-19 caller
    # in one lane — exact FIFO) and the tenant whose fair share and
    # queue cap it counts against.
    priority: str = "interactive"
    tenant_id: str = "default"


@dataclasses.dataclass
class RequestResult:
    request_id: str
    tokens: List[int]
    finish_reason: str
    ttft_s: Optional[float]    # None when expired before the first token
    latency_s: float
    error: Optional[str] = None   # set for FinishReason.ERROR: what broke


@dataclasses.dataclass
class _Live:
    """Host bookkeeping for one occupied slot."""

    req: Request
    request_id: str
    submit_t: float
    deadline_t: Optional[float]
    tokens: List[int] = dataclasses.field(default_factory=list)
    ttft_s: Optional[float] = None
    # Distributed-tracing state (None everywhere for untraced requests):
    # the trace id plus the epoch-clock milestones the per-request
    # lifecycle spans are emitted from. Wall (epoch) time, not monotonic
    # — fragments from different processes must stitch on one clock.
    trace_id: Optional[str] = None
    submit_wall: Optional[float] = None     # submit()
    decode_t0_wall: Optional[float] = None  # prefill done / resume
    first_token_wall: Optional[float] = None
    park_wall: Optional[float] = None       # prefill_only park
    # Preemption ledger (PR 19): how many times this request has been
    # suspended mid-decode — capped by ServeConfig.preemption_budget so
    # one request cannot thrash between slot and host tier forever.
    preempt_count: int = 0


def register_serve_instruments() -> None:
    """Pre-register (get-or-create) the full serving instrument set so
    every serving run's summary carries it — a run with zero rejections
    still reports ``rejected_total = 0`` (the stable schema
    tools/check_telemetry_schema.py pins). Called at scheduler
    construction; call again after a registry reset (e.g. a benchmark
    that starts its run AFTER warmup)."""
    for c in ("admitted", "rejected", "expired", "retired", "tokens",
              "errors", "step_retries"):
        obs.counter(f"serve.{c}_total")
    obs.counter("serve.prefill.chunks_total")
    # Flash-prefill kernel (PR 18): whether paged prefill chunks go
    # through the Pallas kernel (gauge re-set by the engine at init)
    # and the per-layer int8 K/V block writes its epilogue fused in
    # place of the gather/requant round-trip. Impl-invariant: the XLA
    # path and bf16 pools report 0s, never omit the names.
    obs.gauge("serve.prefill.kernel_active")
    obs.counter("serve.prefill.fused_writes_total")
    # Sequence-sharded prefill (PR 20): the mesh shards each prefill
    # chunk spans (0 = replicated mode, M = sequence mode on a 1xM
    # mesh; gauge re-set by the engine) and the ppermute hops ring-
    # variant chunks paid. Mode-invariant: replicated and ulysses runs
    # report 0s, never omit the names.
    obs.gauge("serve.prefill.seq_shards")
    obs.counter("serve.prefill.ring_hops_total")
    # The fault layer's injection count rides in every serving summary
    # (0 when no plan is active) so chaos runs and clean runs share one
    # schema — dashboards can divide errors by injections.
    obs.counter("faults.injected_total")
    # Paged-KV instruments (schema-pinned for every serving run so the
    # summary shape is layout-invariant — a dense run reports 0s):
    # blocks resident, requests that took cached prefix references
    # instead of re-prefilling, and copy-on-write block copies.
    obs.counter("serve.kv.prefix_hits_total")
    obs.counter("serve.kv.cow_copies_total")
    # Cross-replica migration (disaggregated prefill/decode tiers,
    # serve/migrate.py): committed installs and their wire bytes —
    # migration GB/s is bytes / the router.migrate span durations.
    # Layout-invariant 0s on runs that never migrate.
    obs.counter("serve.kv.migrations_total")
    obs.counter("serve.kv.migration_bytes")
    obs.gauge("serve.kv.blocks_used")
    # Tiered KV host spill (PR 15): trie blocks demoted to host RAM on
    # eviction instead of discarded, and blocks promoted back on a
    # returning prefix hit; occupancy gauges for the host-side LRU.
    # Layout/knob-invariant 0s on runs without a host tier.
    obs.counter("serve.kv.demotions_total")
    obs.counter("serve.kv.promotions_total")
    obs.gauge("serve.kv.host_blocks_used")
    obs.gauge("serve.kv.host_bytes_resident")
    # Fleet-wide KV reuse (PR 17, serve/fleetcache): requests that
    # reused cached prefix blocks, split by the tier the blocks came
    # from — own device trie, own host tier, or a sibling replica's
    # peer pull — plus the wire bytes peer pulls installed. Knob-
    # invariant 0s on single-replica / affinity-off runs, so every
    # serving summary renders the same "fleet kv:" line.
    obs.counter("serve.kv.fleet_hits_total")
    obs.counter("serve.kv.fleet_hits_device_total")
    obs.counter("serve.kv.fleet_hits_host_total")
    obs.counter("serve.kv.fleet_hits_peer_total")
    obs.counter("serve.kv.pull_bytes")
    # KV quantization instruments (schema-pinned, layout/dtype
    # invariant): device bytes the resident KV actually holds (the
    # capacity lever int8 moves), the storage width in bits (8 = int8,
    # 16 = bf16, 32 = f32 — lets the report label the dtype), and the
    # per-block max-abs dequant error sampled at each prefill-chunk
    # write (empty on bf16 runs — nothing is quantized).
    obs.gauge("serve.kv.bytes_resident")
    obs.gauge("serve.kv.quant_bits")
    obs.histogram("serve.kv.quant_error")
    # Speculative decoding instruments (schema-pinned, 0/empty when the
    # knob is off so every serving summary shares one shape): draft
    # tokens proposed, draft tokens accepted, and the per-verify
    # accepted-prefix length histogram (tokens-per-verify = p50 + 1).
    obs.counter("serve.spec.draft_tokens_total")
    obs.counter("serve.spec.accepted_total")
    obs.histogram("serve.spec.accepted_len")
    # Tensor-sharded serving (serve/sharded, PR 14): the mesh size this
    # engine spans (1 = classic single-device) and the trace-shape
    # estimate of cross-shard collective payload (0 off-mesh) — every
    # serving summary carries both, so dashboards can split fleets by
    # topology without schema forks.
    obs.gauge("serve.mesh.devices")
    obs.counter("serve.mesh.collective_bytes")
    obs.gauge("serve.queue_depth")
    obs.gauge("serve.batch_occupancy")
    obs.histogram("serve.ttft_s")
    obs.histogram("serve.tpot_s")
    # Multi-tenant scheduling (PR 19): preempt/resume lifecycle
    # counters, the per-tenant cap's typed sheds, the live count of
    # suspended requests, and the per-priority-class TTFT split (the
    # registry has no labels, so the split is three pinned names the
    # report and /metrics render alongside the aggregate). Knob-
    # invariant: runs with preemption off and one lane report 0s /
    # empty splits, never omit the names.
    obs.counter("serve.preemptions_total")
    obs.counter("serve.resumes_total")
    obs.counter("serve.tenant_over_limit_total")
    obs.gauge("serve.preempted_live")
    for p in PRIORITIES:
        obs.histogram(f"serve.ttft_s.{p}")
    obs.histogram("serve.prefill.bucket_len")
    # Decode-horizon instruments: the host gap between consecutive step
    # dispatches (what a horizon > 1 amortizes over H tokens) and the
    # horizon each dispatch ran at (count = dispatches, so
    # tokens_total / count is the realized tokens-per-dispatch).
    obs.histogram("serve.host_gap_s")
    obs.histogram("serve.decode.horizon")


class Scheduler:
    """Bounded-FIFO continuous-batching scheduler over an :class:`Engine`.

    ``on_token(request_id, token)`` streams each decoded token;
    ``on_finish(result)`` fires at retirement. Both run on the thread
    driving :meth:`step`. ``submit`` is thread-safe (HTTP handlers call
    it concurrently with the decode loop).

    ``step_retry_backoff_s`` is the pause before the single
    ``engine.step`` retry — long enough for a transient to clear, short
    enough that in-flight TPOT survives one hiccup.
    """

    step_retry_backoff_s = 0.05

    # How long a parked (prefill_only) slot waits for its migration
    # pull / ACK / resume before the scheduler reclaims it — the
    # leak-proofing backstop of the two-phase handoff: a decode replica
    # that pulled and died, or an ACK lost on the wire, costs the
    # source at most this window of held blocks.
    parked_ttl_s = 60.0

    # Cross-thread state and the lock that guards it — the declaration
    # nezha-lint's lock-discipline rule enforces: every write to these
    # outside `with self._lock` (or a method marked `[holds: _lock]`,
    # meaning the caller already holds it) fails the build. submit()
    # runs on HTTP handler threads against the decode loop's step(),
    # and the migration endpoints (export/ack/resume) run on handler
    # threads too.
    _LOCK_GUARDED = {"_lanes": "_lock", "_lane_vt": "_lock",
                     "_lane_rr": "_lock", "_queued_n": "_lock",
                     "_vt_now": "_lock", "_preempted": "_lock",
                     "preemptions": "_lock", "resumes": "_lock",
                     "_live": "_lock",
                     "results": "_lock", "_host_gap_t": "_lock",
                     "_parked": "_lock", "_digest_cache": "_lock"}

    def __init__(self, engine: Engine,
                 on_token: Optional[Callable[[str, int], None]] = None,
                 on_finish: Optional[Callable[[RequestResult], None]] = None):
        self.engine = engine
        self.on_token = on_token
        self.on_finish = on_finish
        self.queue_capacity = engine.cfg.queue_capacity
        # WFQ admission state (PR 19): priority lane -> tenant -> FIFO
        # deque, the per-lane virtual-time clock the weighted pick
        # compares, the per-lane tenant round-robin ring (a tenant is
        # in its lane dict and ring exactly while its deque is
        # non-empty), the total queued count, and the virtual time of
        # the last grant (an idling lane re-enters at this clock so it
        # can never burst a backlog of unearned credit).
        self._lanes: Dict[str, Dict[str, Deque[_Live]]] = {}
        self._lane_vt: Dict[str, float] = {}
        self._lane_rr: Dict[str, Deque[str]] = {}
        self._queued_n = 0
        self._vt_now = 0.0
        self._weights = dict(engine.cfg.priority_weights
                             or _DEFAULT_WEIGHTS)
        # Requests suspended mid-decode by the preemption trigger:
        # request_id -> _Live (no slot held — their KV sits in the
        # prefix trie / host tier until resume re-admits them).
        self._preempted: Dict[str, _Live] = {}
        # Optional fast-path SLO signal (PR 16's tracker): when set
        # (cli/serve wires the first interactive serve.ttft_s --slo
        # spec), _decode feeds it per interactive first token and a
        # burn rate > 1 lifts the one-preemption-per-admission-pass
        # quota — assigned once at startup, like on_token/on_finish.
        self.slo_tracker = None
        # Plain preemption ledgers (obs counters only count inside a
        # run; these always do — benchmarks read them directly).
        self.preemptions = 0
        self.resumes = 0
        self._live: Dict[int, _Live] = {}          # slot -> request state
        # Parked prefill_only requests awaiting their migration pull
        # (or a local-decode resume): request_id -> (slot, live,
        # expires_t). Slots here hold their prompt blocks but never
        # decode; step() reclaims entries past their TTL.
        self._parked: Dict[str, tuple] = {}
        # Lazily built fleet digest (PR 17) — created on the first
        # /healthz hit that asks for one, recreated when the knobs
        # change (the CLI passes them per call).
        self._digest_cache = None
        self._lock = threading.RLock()
        self._ids = itertools.count()
        self.results: Dict[str, RequestResult] = {}
        # End timestamp of the previous decode dispatch, None when the
        # loop was idle in between — serve.host_gap_s only measures the
        # host gap WITHIN continuous decoding, never idle waits.
        self._host_gap_t: Optional[float] = None
        register_serve_instruments()
        pool = engine.pool
        obs.gauge("serve.kv.quant_bits").set(
            8 if pool.quantized
            else 8 * int(np.dtype(pool.dtype).itemsize))
        # 1 for the classic engine; the sharded engine set M already at
        # its own construction — re-set here so the gauge is correct
        # whichever was built first.
        obs.gauge("serve.mesh.devices").set(
            getattr(engine, "mesh_devices", 1))

    # ------------------------------------------------------- admission
    def submit(self, req: Request) -> str:
        """Enqueue; returns the request id. Raises :class:`QueueFull`
        past capacity and ``ValueError`` for requests that can never be
        served (prompt too long for the static prefill width, or
        prompt + max_new_tokens past the slot's KV capacity)."""
        cfg = self.engine.cfg
        n = len(req.prompt)
        # Admission limit is the slot's KV capacity, not the prefill
        # width: prompts past max_prefill_len prefill in chunks
        # (engine.py), so only max_len bounds what can be served.
        if n < 1:
            raise ValueError("prompt must be non-empty")
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if n + req.max_new_tokens > cfg.max_len:
            raise ValueError(
                f"prompt ({n}) + max_new_tokens ({req.max_new_tokens}) "
                f"exceeds max_len {cfg.max_len}")
        if self.engine.paged:
            # A request whose prefill span (or full resident footprint)
            # needs more blocks than the pool could EVER free can never
            # be served — bounce it here, before it wedges the queue
            # head forever waiting for blocks that cannot exist.
            pool = self.engine.pool
            need = max(self.engine.prefill_blocks_needed(n),
                       pool.blocks_for_span(n + req.max_new_tokens))
            if need > pool.max_request_blocks:
                raise ValueError(
                    f"request needs {need} KV blocks "
                    f"(block_size {pool.block_size}) but the pool can "
                    f"bind at most {pool.max_request_blocks} per "
                    f"request — raise kv_num_blocks or lower the "
                    f"request's footprint")
        vocab = self.engine.vocab
        if not all(0 <= t < vocab for t in req.prompt):
            # Admission IS the validation boundary (the engine trusts its
            # caller): a bad id surfacing inside prefill/step would kill
            # the decode loop with other requests in flight — and would
            # have allocated a slot first — instead of bouncing this
            # submit before any resource is held.
            raise ValueError(f"prompt ids must be in [0, {vocab})")
        if req.priority not in _PRIORITY_RANK:
            raise ValueError(
                f"priority must be one of {PRIORITIES}, got "
                f"{req.priority!r}")
        if not isinstance(req.tenant_id, str) or not req.tenant_id:
            raise ValueError(
                f"tenant_id must be a non-empty string, got "
                f"{req.tenant_id!r}")
        # Trace adoption: a request arriving with a router-minted trace
        # id keeps it; the empty string means "routed, and the ROUTER's
        # sample knob rolled it out" — the minting edge already
        # decided, so re-minting here would double the effective
        # sample rate and leave root-less traces. Only a request with
        # NO verdict at all (trace_id None: direct submit, stdio, a
        # pre-tracing client) makes this scheduler the admission edge
        # that mints — None again when no run is active or the local
        # sample knob rolls it out, in which case the whole lifecycle
        # emits zero extra spans.
        if req.trace_id == "":
            trace_id = None
        elif req.trace_id is not None:
            trace_id = req.trace_id
        else:
            trace_id = obs.mint_trace_id()
        with self._lock:
            if self._queued_n >= self.queue_capacity:
                obs.counter("serve.rejected_total").inc()
                raise QueueFull(
                    f"admission queue at capacity {self.queue_capacity}")
            cap = cfg.tenant_queue_cap
            if cap is not None and self._tenant_depth(
                    req.tenant_id) >= cap:
                # The per-tenant bound fails typed — one tenant's burst
                # never reads as a full fleet to everyone else. Still a
                # shed, so rejected_total keeps meaning ALL sheds.
                obs.counter("serve.tenant_over_limit_total").inc()
                obs.counter("serve.rejected_total").inc()
                raise TenantOverLimit(
                    f"tenant {req.tenant_id!r} at queue cap {cap}")
            rid = req.request_id or f"req-{next(self._ids)}"
            now = time.monotonic()
            self._queue_push(_Live(
                req=req, request_id=rid, submit_t=now,
                deadline_t=None if req.deadline_s is None
                else now + req.deadline_s,
                trace_id=trace_id,
                submit_wall=time.time() if trace_id else None))
            obs.gauge("serve.queue_depth").set(self._queued_n)
        return rid

    # ------------------------------------------------------- iteration
    def step(self) -> int:
        """One serving iteration. Returns the number of tokens decoded
        (0 when fully idle)."""
        with self._lock:
            self._expire_queued()
            self._expire_parked()
            self._expire_preempted()
            self._admit()
            if self._live:
                emitted = self._decode()
            else:
                emitted = 0
                self._host_gap_t = None     # idle: no gap to measure
            self._admit()          # refill slots freed by retirement
            obs.gauge("serve.queue_depth").set(self._queued_n)
            obs.gauge("serve.batch_occupancy").set(
                self.engine.pool.occupancy)
            obs.gauge("serve.kv.blocks_used").set(
                self.engine.pool.blocks_used)
            obs.gauge("serve.kv.bytes_resident").set(
                self.engine.pool.bytes_resident)
            obs.gauge("serve.kv.host_blocks_used").set(
                self.engine.pool.host_blocks_used)
            obs.gauge("serve.kv.host_bytes_resident").set(
                self.engine.pool.host_bytes_resident)
            return emitted

    def run_until_idle(self, max_iters: Optional[int] = None) -> int:
        """Drive :meth:`step` until queue and slots are empty; returns
        the iteration count."""
        iters = 0
        while self.has_work():
            self.step()
            iters += 1
            if max_iters is not None and iters >= max_iters:
                break
        return iters

    def has_work(self) -> bool:
        with self._lock:
            return bool(self._queued_n or self._live or self._preempted)

    @property
    def parked_count(self) -> int:
        with self._lock:
            return len(self._parked)

    @property
    def preempted_count(self) -> int:
        with self._lock:
            return len(self._preempted)

    @property
    def queue_depth(self) -> int:
        """Current admission-queue length (all lanes, all tenants).
        Pacing clients (the stdio reader, closed-loop benchmarks)
        should wait for room here instead of hammering submit() —
        every QueueFull counts into ``serve.rejected_total``, which
        must mean SHED REQUESTS, not retry polls."""
        with self._lock:
            return self._queued_n

    def tenant_queue_depths(self) -> Dict[str, int]:
        """Per-tenant queued counts across every lane — the
        ``/healthz`` / ``/stats`` view operators size tenant_queue_cap
        against. Empty when nothing is queued."""
        with self._lock:
            out: Dict[str, int] = {}
            for lane in self._lanes.values():
                for tenant, dq in lane.items():
                    out[tenant] = out.get(tenant, 0) + len(dq)
            return out

    # ----------------------------------------------- WFQ queue plumbing
    # Invariant: a tenant appears in its lane's dict and round-robin
    # ring exactly while its deque is non-empty, and a priority key
    # appears in _lanes/_lane_rr exactly while the lane holds work —
    # so ring[0] always names a servable tenant. _lane_vt persists
    # across idleness (clamped forward by _queue_push).

    def _tenant_depth(self, tenant: str) -> int:
        """[holds: _lock]"""
        return sum(len(lane[tenant]) for lane in self._lanes.values()
                   if tenant in lane)

    def _queue_push(self, live: _Live) -> None:
        """[holds: _lock]"""
        pri, tenant = live.req.priority, live.req.tenant_id
        lane = self._lanes.setdefault(pri, {})
        if not lane:
            # The lane was idle: re-enter at the current virtual time,
            # never behind it — an empty lane earns no credit.
            self._lane_vt[pri] = max(self._lane_vt.get(pri, 0.0),
                                     self._vt_now)
        dq = lane.get(tenant)
        if dq is None:
            lane[tenant] = dq = collections.deque()
            self._lane_rr.setdefault(
                pri, collections.deque()).append(tenant)
        dq.append(live)
        self._queued_n += 1

    def _pick_lane(self) -> Optional[str]:
        """[holds: _lock] The non-empty lane with the smallest virtual
        time — the weighted-fair pick; PRIORITIES order breaks ties,
        so interactive wins an exact draw."""
        best = None
        for pri in PRIORITIES:
            if pri not in self._lanes:
                continue
            vt = self._lane_vt.get(pri, 0.0)
            if best is None or vt < best[0]:
                best = (vt, pri)
        return None if best is None else best[1]

    def _peek_next(self) -> Optional[_Live]:
        """[holds: _lock] The request _pop_next would grant next,
        without granting it (the admission loop's block-budget peek)."""
        pri = self._pick_lane()
        if pri is None:
            return None
        return self._lanes[pri][self._lane_rr[pri][0]][0]

    def _pop_next(self) -> Optional[_Live]:
        """[holds: _lock] Grant one admission: pop the WFQ pick,
        advance its lane's virtual clock by 1/weight, and rotate the
        lane's tenant ring (equal-share round robin within a lane)."""
        pri = self._pick_lane()
        if pri is None:
            return None
        ring = self._lane_rr[pri]
        tenant = ring[0]
        dq = self._lanes[pri][tenant]
        live = dq.popleft()
        self._queued_n -= 1
        ring.rotate(-1)
        if not dq:
            del self._lanes[pri][tenant]
            ring.remove(tenant)
            if not self._lanes[pri]:
                del self._lanes[pri]
                del self._lane_rr[pri]
        vt = self._lane_vt.get(pri, 0.0)
        self._vt_now = max(self._vt_now, vt)
        self._lane_vt[pri] = vt + 1.0 / self._weights[pri]
        return live

    # -------------------------------------------------------- internals
    def _expire_queued(self) -> None:
        """[holds: _lock] — step() calls this inside the lock."""
        now = time.monotonic()
        for pri in list(self._lanes):
            lane = self._lanes[pri]
            ring = self._lane_rr[pri]
            for tenant in list(lane):
                kept: Deque[_Live] = collections.deque()
                for live in lane[tenant]:
                    if (live.deadline_t is not None
                            and now >= live.deadline_t):
                        obs.counter("serve.expired_total").inc()
                        self._finish(live, FinishReason.DEADLINE)
                        self._queued_n -= 1
                    else:
                        kept.append(live)
                if kept:
                    lane[tenant] = kept
                else:
                    del lane[tenant]
                    ring.remove(tenant)
            if not lane:
                del self._lanes[pri]
                del self._lane_rr[pri]

    def _expire_parked(self) -> None:
        """[holds: _lock] — step() calls this inside the lock. The park
        TTL is what makes the two-phase handoff leak-proof against a
        decode replica that pulled and died before ACKing (or an ACK
        lost on the wire): the source reclaims the slot and its blocks
        itself. The request's "prefilled" answer was already delivered;
        this is resource reclamation, counted like any other deadline
        miss."""
        now = time.monotonic()
        for rid in [r for r, (_, _, exp) in self._parked.items()
                    if now >= exp]:
            slot, live, _ = self._parked.pop(rid)
            self.engine.pool.free(slot)
            self._emit_park_span(live, "expired")
            obs.counter("serve.expired_total").inc()
            obs.counter("serve.retired_total").inc()

    def _expire_preempted(self) -> None:
        """[holds: _lock] — step() calls this inside the lock. A
        deadline keeps ticking while a request is suspended: it
        retires here with whatever tokens it already has, counted like
        any other deadline miss (and into ``retired_total`` — it WAS
        admitted once)."""
        now = time.monotonic()
        expired = [r for r, l in self._preempted.items()
                   if l.deadline_t is not None and now >= l.deadline_t]
        for rid in expired:
            live = self._preempted.pop(rid)
            obs.counter("serve.expired_total").inc()
            obs.counter("serve.retired_total").inc()
            self._finish(live, FinishReason.DEADLINE)
        if expired:
            obs.gauge("serve.preempted_live").set(len(self._preempted))

    # ------------------------------------------------------- preemption
    def _peek_preempted(self) -> Optional[_Live]:
        """[holds: _lock] The suspended request resume would re-admit
        next: highest priority first, oldest submit within it."""
        if not self._preempted:
            return None
        return min(self._preempted.values(),
                   key=lambda l: (_PRIORITY_RANK[l.req.priority],
                                  l.submit_t, l.request_id))

    def _pop_preempted(self, request_id: str) -> _Live:
        """[holds: _lock]"""
        live = self._preempted.pop(request_id)
        obs.gauge("serve.preempted_live").set(len(self._preempted))
        return live

    def _slo_burning(self) -> bool:
        """[holds: _lock] True when the wired interactive-TTFT SLO
        tracker is burning its error budget faster than it earns it —
        the PR 16 control signal that lifts the gentle one-preemption-
        per-pass quota."""
        return (self.slo_tracker is not None
                and self.slo_tracker.burn_rate() > 1.0)

    def _maybe_preempt(self, target: _Live, already: int) -> bool:
        """[holds: _lock] Try to free capacity for ``target`` by
        preempting one live decode of STRICTLY lower priority (lowest
        class first, least-progressed row within it) whose
        ``preemption_budget`` is not exhausted. Gentle by default —
        one preemption per admission pass — unless the interactive SLO
        is burning, when the quota opens to the whole batch. False
        when the knob is off, no victim qualifies, or the
        ``scheduler.preempt`` drill vetoed the suspend (the victim
        just keeps decoding)."""
        cfg = self.engine.cfg
        if not cfg.preemption:
            return False
        if already >= (len(self._live) if self._slo_burning() else 1):
            return False
        rank = _PRIORITY_RANK[target.req.priority]
        victim = None
        for slot, live in self._live.items():
            if _PRIORITY_RANK[live.req.priority] <= rank:
                continue
            if live.preempt_count >= cfg.preemption_budget:
                continue
            key = (-_PRIORITY_RANK[live.req.priority],
                   len(live.tokens), slot)
            if victim is None or key < victim[0]:
                victim = (key, slot, live)
        if victim is None:
            return False
        return self._preempt(victim[1], victim[2])

    def _preempt(self, slot: int, live: _Live) -> bool:
        """[holds: _lock] Suspend one live decode: index its bound
        blocks (prompt + every emitted token) into the prefix trie —
        where admission pressure can LRU-evict them and, with a host
        tier, demote them through the serve.kv.demotions_total path —
        free the slot, and park the request in ``_preempted`` for
        resume. On the dense layout (or with the cache off /
        kv_eviction="none", where trie refs would pin blocks forever)
        nothing is indexed: resume pays a cold re-prefill, trading
        compute instead of leaking capacity. The ``scheduler.preempt``
        fault point fires FIRST: an injected error is the typed
        degradation drill — the victim simply keeps decoding."""
        try:
            faults.point("scheduler.preempt")
        except Exception:
            return False
        pool = self.engine.pool
        with obs.span("serve.preempt_s", request_id=live.request_id,
                      priority=live.req.priority,
                      tokens=len(live.tokens)):
            if (self.engine.paged and pool.prefix_cache_enabled
                    and pool.eviction == "lru"):
                pool.register_prefix(
                    slot, list(live.req.prompt) + live.tokens)
            del self._live[slot]
            pool.free(slot)
        live.preempt_count += 1
        self._preempted[live.request_id] = live
        self.preemptions += 1
        obs.counter("serve.preemptions_total").inc()
        obs.gauge("serve.preempted_live").set(len(self._preempted))
        return True

    def _resume_one(self, live: _Live) -> None:
        """[holds: _lock] Re-admit one preempted request: prefill its
        full context (prompt + emitted tokens) into a fresh slot with
        the REMAINING token budget and rejoin the batch. Greedy decode
        is deterministic given the context, so the resumed stream is
        bit-identical to an uninterrupted run; full blocks indexed at
        preemption prefix-hit the trie (or promote back from the host
        tier) instead of recomputing. A prefill failure retires the
        request typed, exactly like admission."""
        pool = self.engine.pool
        self._pop_preempted(live.request_id)
        slot = pool.alloc()
        req = live.req
        context = list(req.prompt) + live.tokens
        try:
            with obs.trace_context(live.trace_id):
                with obs.span("serve.prefill",
                              request_id=live.request_id,
                              prompt_len=len(context), resumed=True):
                    self.engine.prefill(
                        slot, context, seed=req.seed,
                        temperature=req.temperature, top_k=req.top_k,
                        top_p=req.top_p, eos_id=req.eos_id,
                        max_new_tokens=(req.max_new_tokens
                                        - len(live.tokens)))
        except Exception as e:
            pool.free(slot)
            obs.counter("serve.errors_total").inc()
            # Admitted once at first grant — balance with a retirement.
            obs.counter("serve.retired_total").inc()
            self._finish(live, FinishReason.ERROR,
                         error=f"resume prefill failed: "
                               f"{type(e).__name__}: {e}")
            return
        self.resumes += 1
        obs.counter("serve.resumes_total").inc()
        if live.trace_id is not None:
            live.decode_t0_wall = time.time()
        self._live[slot] = live

    def _admit(self) -> None:
        """[holds: _lock] — step() calls this inside the lock. One
        admission pass: grant free slots to the WFQ pick among queued
        requests and resumable preempted ones (a preempted request
        outranks a queued pick of equal or lower priority — it is
        older, already-admitted work whose KV may still be cached),
        preempting a strictly-lower-priority live decode when the pick
        cannot get a slot or its blocks any other way."""
        pool = self.engine.pool
        preempts = 0
        while True:
            cand = self._peek_next()
            pre = self._peek_preempted()
            use_pre = pre is not None and (
                cand is None or _PRIORITY_RANK[pre.req.priority]
                <= _PRIORITY_RANK[cand.req.priority])
            target = pre if use_pre else cand
            if target is None:
                break
            if not pool.num_free:
                # Slot pressure: make room by suspending a lower-
                # priority live decode — or wait for retirement.
                if not self._maybe_preempt(target, preempts):
                    break
                preempts += 1
                continue
            if self.engine.paged:
                # Admission budget is FREE BLOCKS, not free slots: only
                # admit the pick if its worst-case (no prefix hit)
                # prefill binding fits the free list plus what cache
                # eviction could reclaim. The worst case also COVERS a
                # host-tier promotion: a promoted span allocates
                # exactly the device blocks a cold prefill of that
                # span would have bound (promotion substitutes a
                # host->device copy for recompute, never extra
                # footprint), so promotable requests need no separate
                # budget line. A resumed request budgets its full
                # context (prompt + emitted tokens). Otherwise wait —
                # live rows retire and release blocks, and lane order
                # holds (skipping ahead would starve long prompts).
                ctx = len(target.req.prompt) + (len(target.tokens)
                                                if use_pre else 0)
                need = self.engine.prefill_blocks_needed(ctx)
                if pool.available_blocks() < need:
                    if self._maybe_preempt(target, preempts):
                        # The victim's blocks moved to the trie (or
                        # the free list): re-check the budget.
                        preempts += 1
                        continue
                    if not self._live:
                        # Nothing in flight will EVER free more blocks
                        # (with kv_eviction="none" the prefix cache
                        # pins its blocks permanently): waiting would
                        # livelock, so retire the pick with a typed
                        # error instead — later, smaller requests may
                        # still be servable.
                        if use_pre:
                            # Already counted admitted once — balance
                            # the books with a retirement.
                            self._pop_preempted(target.request_id)
                            obs.counter("serve.retired_total").inc()
                        else:
                            self._pop_next()
                        obs.counter("serve.errors_total").inc()
                        self._finish(
                            target, FinishReason.ERROR,
                            error=f"kv blocks exhausted: need {need}, "
                                  f"{pool.available_blocks()} "
                                  f"reclaimable, {pool.blocks_used} "
                                  f"in use (kv_eviction="
                                  f"{pool.eviction!r})")
                        continue
                    break
            if use_pre:
                self._resume_one(target)
            else:
                self._admit_one()

    def _admit_one(self) -> None:
        """[holds: _lock] Grant the WFQ pick its slot and prefill it —
        the per-request tail of the admission pass (_admit checked the
        slot and block budgets first)."""
        pool = self.engine.pool
        live = self._pop_next()
        slot = pool.alloc()
        req = live.req
        if live.trace_id is not None:
            # Queue wait is only measurable retroactively (submit ->
            # this admission) — the first stitched-timeline segment
            # after the router hop.
            obs.emit_span("serve.queue_wait", live.submit_wall,
                          time.time(), trace_id=live.trace_id,
                          request_id=live.request_id)
        try:
            # The ambient trace context makes serve.prefill (and the
            # engine's per-chunk serve.prefill.chunk spans beneath
            # it) carry the request's trace id; a no-op for
            # untraced requests.
            with obs.trace_context(live.trace_id):
                with obs.span("serve.prefill",
                              request_id=live.request_id,
                              prompt_len=len(req.prompt)):
                    self.engine.prefill(
                        slot, req.prompt, seed=req.seed,
                        temperature=req.temperature, top_k=req.top_k,
                        top_p=req.top_p, eos_id=req.eos_id,
                        max_new_tokens=req.max_new_tokens)
        except Exception as e:
            # submit() pre-validates the request SHAPE, but runtime/
            # XLA errors (OOM-ish transients, injected faults) can
            # still surface here — and one bad request must never
            # kill the decode loop with neighbors in flight. Free
            # the slot, retire the request as an ERROR, keep
            # admitting. (The span recorded the exception type.)
            pool.free(slot)
            obs.counter("serve.errors_total").inc()
            self._finish(live, FinishReason.ERROR,
                         error=f"prefill failed: "
                               f"{type(e).__name__}: {e}")
            return
        obs.counter("serve.admitted_total").inc()
        if req.prefill_only:
            # Disaggregation: park the prefilled slot for the
            # migration pull instead of decoding. The request
            # finishes PREFILLED (its waiter gets the handle); the
            # slot holds its prompt blocks until kv_ack / resume /
            # TTL. A duplicate id would orphan the first park's
            # slot, so it is a typed error.
            if live.request_id in self._parked:
                pool.free(slot)
                obs.counter("serve.errors_total").inc()
                self._finish(live, FinishReason.ERROR,
                             error=f"request {live.request_id!r} "
                                   f"already parked")
                return
            if live.trace_id is not None:
                live.park_wall = time.time()
            self._parked[live.request_id] = (
                slot, live, time.monotonic() + self.parked_ttl_s)
            self._finish(live, FinishReason.PREFILLED)
            return
        if live.trace_id is not None:
            live.decode_t0_wall = time.time()
        self._live[slot] = live

    def _decode(self) -> int:
        """[holds: _lock] — step() calls this inside the lock."""
        horizon = self.engine.cfg.decode_horizon
        active = np.zeros((self.engine.cfg.max_batch_size,), bool)
        for slot in self._live:
            active[slot] = True
        # Occupancy OF THIS DECODE, folded into the metric.* histogram
        # the report renders percentiles from (the same name a
        # record_metrics stream would fold into) — the gauge alone only
        # keeps the final value, which is 0 for any drained server.
        obs.histogram("metric.batch_occupancy").observe(
            len(self._live) / self.engine.cfg.max_batch_size)
        # Wall-clock twin of the monotonic dispatch window, taken only
        # when a traced request is in the batch: per-request
        # serve.decode_window spans and the first-token milestone are
        # stitched on the epoch clock across processes.
        traced_batch = obs.enabled() and any(
            l.trace_id is not None for l in self._live.values())
        t0_wall = time.time() if traced_batch else None
        t0 = time.monotonic()
        if self._host_gap_t is not None:
            # Host time since the previous block came back: the
            # retire/admit/stream pass plus any interleaved prefill —
            # the per-dispatch cost a horizon > 1 spreads over H tokens.
            obs.histogram("serve.host_gap_s").observe(
                t0 - self._host_gap_t)
        def _dispatch():
            # KV block exhaustion (genuine, or an injected serve.kv.bind
            # fault) is TYPED BACKPRESSURE, not an engine failure: retire
            # only the victim row — freeing its blocks — and redial with
            # the survivors. Convergence is guaranteed (every retirement
            # releases blocks); None means the block retired everyone.
            while True:
                try:
                    return self.engine.step(active)
                except KVBlocksExhausted as e:
                    slot = e.slot
                    if slot is None or slot not in self._live:
                        raise
                    victim = self._live.pop(slot)
                    self.engine.pool.free(slot)
                    active[slot] = False
                    obs.counter("serve.errors_total").inc()
                    obs.counter("serve.retired_total").inc()
                    self._finish(victim, FinishReason.ERROR,
                                 error=f"kv blocks exhausted: {e}")
                    if not self._live:
                        return None

        with obs.span("serve.decode_attention", rows=len(self._live)):
            try:
                out = _dispatch()
            except Exception:
                # One bounded retry with backoff: a transient step crash
                # (preempted device, injected fault) must not retire
                # every in-flight request. A second consecutive failure
                # surfaces to the caller — that is a dead engine, not a
                # hiccup. (If the first dispatch died AFTER consuming
                # its donated cache buffers the retry fails fast on the
                # donation error and surfaces the same way.)
                obs.counter("serve.step_retries_total").inc()
                time.sleep(self.step_retry_backoff_s)
                out = _dispatch()
            if out is None:
                self._host_gap_t = None
                return 0
            tokens, block_emitted = out
        now = time.monotonic()
        dt = now - t0
        now_wall = time.time() if traced_batch else None
        self._host_gap_t = now
        obs.histogram("serve.decode.horizon").observe(
            self.engine.tokens_per_dispatch)
        speculative = self.engine.spec is not None
        ok = self.engine.step_ok
        emitted = 0
        for slot in list(self._live):
            live = self._live[slot]
            e = int(block_emitted[slot])
            if live.trace_id is not None and t0_wall is not None and e:
                # One fragment per traced request per dispatch window:
                # where a slow request's decode time actually went.
                obs.emit_span("serve.decode_window", t0_wall, now_wall,
                              trace_id=live.trace_id,
                              request_id=live.request_id, tokens=e)
            retired = False
            for i in range(e):
                tok = int(tokens[slot, i])
                live.tokens.append(tok)
                emitted += 1
                if live.ttft_s is None:
                    # The first token landed at its position WITHIN the
                    # block, not at the block end — a fresh row emits
                    # from scan step 0, so crediting the whole block
                    # would overstate TTFT by (H-1)/H of a block. In
                    # speculative mode the block's width varies with
                    # acceptance, so the first ACCEPTED token is
                    # credited at its position among the row's e
                    # actually-emitted tokens (PR 5's move, denominator
                    # adjusted); classic keeps the exact /horizon form.
                    denom = e if speculative else horizon
                    live.ttft_s = ((t0 - live.submit_t)
                                   + dt * (i + 1) / denom)
                    if live.trace_id is not None and t0_wall is not None:
                        live.first_token_wall = (t0_wall
                                                 + dt * (i + 1) / denom)
                    obs.histogram("serve.ttft_s").observe(live.ttft_s)
                    # Per-priority-class split (pinned): one histogram
                    # per lane so the report/exposition can show
                    # interactive latency separately from the batch
                    # traffic it preempts.
                    obs.histogram(
                        f"serve.ttft_s.{live.req.priority}").observe(
                            live.ttft_s)
                    if (self.slo_tracker is not None
                            and live.req.priority == "interactive"):
                        # Feed the wired interactive-TTFT SLO tracker
                        # per first token: its burn rate is the PR 16
                        # control signal that widens the preemption
                        # quota in _maybe_preempt.
                        cfg = self.slo_tracker.cfg
                        ok = {"<": live.ttft_s < cfg.threshold,
                              "<=": live.ttft_s <= cfg.threshold,
                              ">": live.ttft_s > cfg.threshold,
                              ">=": live.ttft_s >= cfg.threshold,
                              }[cfg.op]
                        self.slo_tracker.observe(ok)
                # Per-token decode latency: the block cost split over
                # the tokens it produced, observed once per token —
                # horizon=1 degenerates to the classic one-dt-per-token
                # and percentiles stay comparable across horizons.
                obs.histogram("serve.tpot_s").observe(dt / e)
                if self.on_token is not None:
                    self.on_token(live.request_id, tok)
                reason = None
                if (live.req.eos_id is not None
                        and tok == live.req.eos_id):
                    reason = FinishReason.EOS
                elif len(live.tokens) >= live.req.max_new_tokens:
                    reason = FinishReason.LENGTH
                elif (live.deadline_t is not None
                        and now >= live.deadline_t):
                    # Deadlines are block-granular now: the whole block
                    # shares one `now`, and tokens decoded past a
                    # mid-block deadline are dropped with the
                    # retirement (RUNBOOK §8 documents the coarsening).
                    reason = FinishReason.DEADLINE
                if reason is not None:
                    del self._live[slot]
                    self.engine.pool.free(slot)
                    obs.counter("serve.retired_total").inc()
                    if reason == FinishReason.DEADLINE:
                        # expired_total counts EVERY deadline miss,
                        # queued or mid-decode (FinishReason's
                        # documented contract).
                        obs.counter("serve.expired_total").inc()
                    self._finish(live, reason)
                    retired = True
                    break
            if retired:
                continue
            if ok is not None and not ok[slot]:
                # Non-finite logits (NaN/inf burst) at some scan step:
                # the device froze the row there and excluded the
                # garbage from its emitted count, so everything
                # delivered above is pre-burst. Retire ONLY this
                # request; the rest of the batch keeps decoding.
                del self._live[slot]
                self.engine.pool.free(slot)
                obs.counter("serve.errors_total").inc()
                obs.counter("serve.retired_total").inc()
                self._finish(live, FinishReason.ERROR,
                             error="non-finite logits")
        obs.counter("serve.tokens_total").inc(emitted)
        if not self._live:
            # The block retired the whole batch: the next decode only
            # happens after new admissions, which may be arbitrarily
            # later (open-loop callers gate step() on has_work(), so
            # the idle reset in step() never runs for them) — a gap
            # measured across that wait would be idle time, not host
            # overhead.
            self._host_gap_t = None
        return emitted

    def _finish(self, live: _Live, reason: str,
                error: Optional[str] = None) -> None:
        """[holds: _lock] — every caller (admission, decode, drain)
        already holds the lock; ``results`` is read by waiter threads."""
        if live.trace_id is not None and live.decode_t0_wall is not None:
            # The retire fragment: one span covering this request's
            # whole decode residency, carrying the first-token epoch
            # milestone the stitcher ends the TTFT decomposition at.
            attrs = {"request_id": live.request_id,
                     "finish_reason": reason,
                     "tokens": len(live.tokens)}
            if live.ttft_s is not None:
                attrs["ttft_s"] = live.ttft_s
            if live.first_token_wall is not None:
                attrs["first_token"] = live.first_token_wall
            obs.emit_span("serve.decode", live.decode_t0_wall,
                          time.time(), trace_id=live.trace_id, **attrs)
        result = RequestResult(
            request_id=live.request_id, tokens=live.tokens,
            finish_reason=reason, ttft_s=live.ttft_s,
            latency_s=time.monotonic() - live.submit_t, error=error)
        self.results[live.request_id] = result
        if self.on_finish is not None:
            self.on_finish(result)

    def _emit_park_span(self, live: _Live, outcome: str) -> None:
        """[holds: _lock] One ``serve.park`` fragment per traced park,
        emitted at its release (ACK / resume / TTL / drain) — the
        stitched timeline's view of how long the source held the blocks
        and which way the two-phase handoff resolved."""
        if live.trace_id is None or live.park_wall is None:
            return
        obs.emit_span("serve.park", live.park_wall, time.time(),
                      trace_id=live.trace_id,
                      request_id=live.request_id, outcome=outcome)

    # ------------------------------------------------------- migration
    def export_parked(self, request_id: str) -> dict:
        """The source half of the migration pull (``/kv_export``):
        export the parked request's full-block prompt prefix as the
        int8+scales wire object (serve/migrate.py). Read-only — the
        parked refs survive until :meth:`ack_parked` (the two-phase
        commit) or the TTL. Raises ``KeyError`` for an unknown/expired
        park and :class:`~nezha_tpu.serve.migrate.MigrationError` when
        this engine's layout cannot export. Runs under the scheduler
        lock: the gather must not race a decode dispatch that donates
        the cache buffers."""
        from nezha_tpu.serve import migrate
        faults.point("replica.kv_export")
        with self._lock:
            if request_id not in self._parked:
                raise KeyError(request_id)
            slot, live, _ = self._parked[request_id]
            pool = self.engine.pool
            # The export fragment adopts the PARKED request's trace (the
            # authoritative id — it arrived with the prefill_only
            # admission); untraced parks record nothing.
            with obs.trace_context(live.trace_id):
                with obs.traced_span("serve.kv_export",
                                     request_id=request_id) as sp:
                    if not self.engine.paged:
                        raise migrate.MigrationError(
                            "kv_layout 'dense' has no blocks to export "
                            "— migration requires the paged pool")
                    tokens = [int(t) for t in live.req.prompt]
                    nfull = min(len(tokens) // pool.block_size,
                                int(pool._bound[slot]))
                    if nfull == 0:
                        # Sub-block prompt: nothing reusable to ship —
                        # a legal, empty payload (the decode side just
                        # prefills cold).
                        return migrate.encode_wire([], [],
                                                   pool.block_size)
                    layers, _ = pool.export_block_payload(slot, nfull)
                    wire = migrate.encode_wire(
                        tokens[:nfull * pool.block_size], layers,
                        pool.block_size)
                    sp.set(blocks=nfull, bytes=wire["nbytes"])
                    return wire

    def ack_parked(self, request_id: str) -> bool:
        """Commit of the two-phase handoff (``/kv_ack``): the decode
        side holds its own copy, so release the parked slot and its
        block refs. -> False (idempotently) when the park is unknown —
        already acked, TTL-reclaimed, or drained."""
        with self._lock:
            parked = self._parked.pop(request_id, None)
            if parked is None:
                return False
            slot, live, _ = parked
            self.engine.pool.free(slot)
            self._emit_park_span(live, "acked")
            obs.counter("serve.retired_total").inc()
            return True

    def resume_parked(self, request_id: str) -> bool:
        """Local-decode fallback (``role=both`` degradation): move a
        parked request into the live set and decode it HERE — the path
        the router takes when no decode-tier replica is live or every
        migration attempt failed. The parked prompt KV is already in
        this pool, so decoding starts immediately. -> False when the
        park is unknown (expired / acked away)."""
        with self._lock:
            parked = self._parked.pop(request_id, None)
            if parked is None:
                return False
            slot, live, _ = parked
            self._emit_park_span(live, "resumed")
            if live.trace_id is not None:
                live.decode_t0_wall = time.time()
            # The "prefilled" result was this request's park receipt,
            # not its answer — drop it so the real retirement's result
            # is the one waiters read.
            self.results.pop(request_id, None)
            self._live[slot] = live
            return True

    def install_migrated(self, tokens: Sequence[int], layers: list,
                         nbytes: int) -> int:
        """The destination half of the pull: install a decoded wire
        payload into this replica's pool + prefix trie (fresh blocks at
        ref == 1 — the write invariant by construction). The request
        submitted afterwards takes prefix-cache references through the
        ordinary admission path. Counts committed installs into the
        schema-pinned ``serve.kv.migrations_total`` /
        ``serve.kv.migration_bytes``."""
        from nezha_tpu.serve import migrate
        faults.point("replica.kv_install")
        with self._lock:
            if not self.engine.paged:
                raise migrate.MigrationError(
                    "kv_layout 'dense' cannot install migrated blocks")
            installed = self.engine.pool.install_block_payload(tokens,
                                                               layers)
            if installed > 0:
                # Committed installs only: an empty sub-block payload,
                # a disabled prefix cache, or an already-cached prefix
                # installs nothing and must not inflate the ledger
                # ("N pulls, 0 bytes moved" would misread as cache
                # wins). The router's plain ledgers count every
                # successful PULL separately.
                obs.counter("serve.kv.migrations_total").inc()
                obs.counter("serve.kv.migration_bytes").inc(nbytes)
            return installed

    # ----------------------------------------------------- fleet cache
    def fleet_digest(self, interval_s: float = 2.0,
                     max_entries: int = 256) -> dict:
        """The ``/healthz`` digest payload (PR 17): a bounded
        prefix-hash summary of what this replica's pool holds, rebuilt
        at most once per ``interval_s``. Dense pools (nothing
        block-indexed to advertise) report ``digest_size = 0`` and no
        ``fleet_digest`` key — the Router simply never scores this
        replica above zero coverage."""
        from nezha_tpu.serve import fleetcache
        with self._lock:
            if not self.engine.paged:
                return {"digest_size": 0, "digest_age_s": 0.0}
            dc = self._digest_cache
            if (dc is None or dc.interval_s != float(interval_s)
                    or dc.max_entries != int(max_entries)):
                dc = fleetcache.DigestCache(interval_s, max_entries)
                self._digest_cache = dc
            return dc.payload(self.engine.pool)

    def export_prefix(self, tokens: Sequence[int]) -> dict:
        """The source half of a PEER pull (``/kv_export`` tokens
        mode, PR 17): the longest cached full-block prefix of
        ``tokens`` this pool holds, as the int8+scales wire object.
        Unlike :meth:`export_parked` there is no park, no request and
        no ACK — the export is a read-only cache probe; the source
        gives up nothing and zero coverage is a legal empty wire.
        Runs under the scheduler lock (the gather must not race a
        cache-donating decode dispatch). The peer path's chaos knob is
        ``replica.kv_pull`` on the DESTINATION client (one registered
        site per point) — source-side failure is exercised by killing
        the owner outright."""
        from nezha_tpu.serve import migrate
        with self._lock:
            pool = self.engine.pool
            if not self.engine.paged:
                raise migrate.MigrationError(
                    "kv_layout 'dense' has no blocks to export — "
                    "peer pull requires the paged pool",
                    kind="kv_pull_failed")
            covered, layers, _ = pool.export_prefix_payload(tokens)
            return migrate.encode_wire(covered, layers, pool.block_size)

    def install_pulled(self, tokens: Sequence[int], layers: list,
                       nbytes: int) -> int:
        """The destination half of a peer pull: install the wire
        payload into this pool's prefix cache with the blocks tagged
        ``origin="peer"`` so their first reuse counts as a fleet peer
        hit, and account the wire bytes into the schema-pinned
        ``serve.kv.pull_bytes`` (NOT the migration ledgers — a peer
        pull is a cache transfer, not a request handoff)."""
        from nezha_tpu.serve import migrate
        with self._lock:
            if not self.engine.paged:
                raise migrate.MigrationError(
                    "kv_layout 'dense' cannot install pulled blocks",
                    kind="kv_pull_failed")
            installed = self.engine.pool.install_block_payload(
                tokens, layers, origin="peer")
            if installed > 0:
                obs.counter("serve.kv.pull_bytes").inc(nbytes)
            return installed

    # ----------------------------------------------------------- drain
    def cancel_remaining(self, reason: str = FinishReason.DEADLINE,
                         error: Optional[str] = None) -> int:
        """Retire EVERYTHING still queued or in flight — the drain
        cutoff. Each request finishes with ``reason`` and whatever
        tokens it already has, every slot returns to the pool, and the
        count of cancellations comes back (0 when already idle).
        Deadline-reason cancellations count into ``serve.expired_total``
        (the documented every-deadline-miss contract); error-reason ones
        (a dead engine at shutdown) into ``serve.errors_total`` with
        ``error`` as the detail."""
        def _count():
            if reason == FinishReason.DEADLINE:
                obs.counter("serve.expired_total").inc()
            elif reason == FinishReason.ERROR:
                obs.counter("serve.errors_total").inc()

        with self._lock:
            n = 0
            while self._queued_n:
                live = self._pop_next()
                _count()
                self._finish(live, reason, error=error)
                n += 1
            for slot in list(self._live):
                live = self._live.pop(slot)
                self.engine.pool.free(slot)
                obs.counter("serve.retired_total").inc()
                _count()
                self._finish(live, reason, error=error)
                n += 1
            # Preempted requests hold no slot or blocks (their KV, if
            # any survived, lives in the trie/host tier) — retire them
            # with whatever tokens they already emitted.
            for rid in list(self._preempted):
                live = self._preempted.pop(rid)
                obs.counter("serve.retired_total").inc()
                _count()
                self._finish(live, reason, error=error)
                n += 1
            obs.gauge("serve.preempted_live").set(0)
            # Parked migrations: their "prefilled" answers were already
            # delivered, so this is pure resource release — a drained
            # source simply stops being pullable (the router's next
            # /kv_export gets a typed 404 and retries elsewhere).
            for rid in list(self._parked):
                slot, parked_live, _ = self._parked.pop(rid)
                self.engine.pool.free(slot)
                self._emit_park_span(parked_live, "drained")
                obs.counter("serve.retired_total").inc()
            obs.gauge("serve.queue_depth").set(0)
            obs.gauge("serve.batch_occupancy").set(
                self.engine.pool.occupancy)
            obs.gauge("serve.kv.blocks_used").set(
                self.engine.pool.blocks_used)
            return n
