"""Replica supervision for multi-replica serving.

One engine process is one failure domain: a crash kills every in-flight
request it holds (and, pre-scale-out, the whole service). The supervisor
turns N copies of the existing single-replica stack into a crowd the
front end (`serve/router.py`) can survive losing members of:

- **spawn** — each replica is the SAME single-replica `nezha-serve`
  stack on its own port, launched through a pluggable backend:
  ``ProcessBackend`` runs ``python -m nezha_tpu.cli.serve ... --http
  PORT`` subprocesses (production: a real OS failure domain, SIGTERM
  drains it, SIGKILL is a genuine crash), ``ThreadBackend`` hosts the
  same engine/scheduler stack in-process behind a real HTTP socket
  (tests and benchmarks: killable without paying a process spawn +
  jax import per replica — a kill severs its sockets, so the router
  observes the same connection resets a SIGKILLed process produces).
- **restart** — a replica that dies while it should be serving is
  respawned with capped exponential backoff (the PR-4 retry-envelope
  idiom: base doubling to a cap, seeded ±50% jitter so a mass failure
  doesn't respawn in lockstep). Failures that never reach a healthy
  probe count consecutively; after ``max_restart_failures`` of them the
  replica's CIRCUIT BREAKER opens (state ``failed``) and the supervisor
  stops burning spawns on it — a replica that crashes at startup every
  time is a config problem, not a transient. Reaching healthy resets
  the count. Successful respawns count into
  ``router.replica_restarts_total`` (and :attr:`Supervisor.restarts`).
- **rolling drain** — SIGTERM at the front end drains replicas ONE AT A
  TIME: each gets a graceful drain (SIGTERM to a process — the worker's
  own PR-4 drain semantics; the in-process worker's drain method for
  threads) and its full drain budget while every later replica keeps
  serving its in-flight work, so live capacity steps down N, N-1, ...,
  1, 0 and never hits zero before the last replica.

Health (probe misses, ejection, readmission) is the router's verdict,
stored here per replica so routing and lifecycle share one record under
one lock. Chaos enters through :meth:`Supervisor.kill` (the seeded
replica-kill knob ``benchmarks/serving.py --kill-rate`` and the chaos
tests drive) and through the fault points ``supervisor.spawn`` (a spawn
attempt that fails before the backend runs) and ``replica.exec`` (the
worker crashes at startup — both the subprocess entry and the thread
worker route through :func:`replica_exec_point`, keeping one registered
site).
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import socket
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from nezha_tpu import faults, obs

# Replica lifecycle states. healthy (the router's probe verdict) is a
# separate axis: only a LIVE + healthy replica is routable.
STARTING = "starting"    # spawned, not yet probed healthy
LIVE = "live"            # probed healthy at least once since spawn
DRAINING = "draining"    # rolling drain in progress on this replica
STOPPED = "stopped"      # drained/shut down deliberately — never restarted
DEAD = "dead"            # died; restart scheduled (next_restart_t)
FAILED = "failed"        # circuit breaker open — restarts exhausted


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Shared knobs for the supervisor + router pair (the scale-out
    analogue of ``ServeConfig``): how many replicas, how health is
    judged, how failures are retried, and how restarts back off.

    ``probe_misses`` consecutive failed /healthz probes eject a replica
    from routing (one success readmits it). ``route_retries`` bounds
    how many times one request may be re-dispatched after its replica
    died before answering; retry sleeps follow the PR-4 envelope
    (``retry_backoff_base_s`` doubling to ``retry_backoff_max_s``,
    seeded ±50% jitter). ``forward_timeout_s`` bounds one replica
    answer — it must exceed the worst-case request latency, and a
    timeout is a typed error, never a retry (a slow replica is not a
    dead one, and re-dispatching its request would double-serve it).
    ``max_restart_failures`` consecutive spawn/startup failures open a
    replica's circuit breaker. ``drain_timeout_s`` is the per-replica
    budget of the rolling drain.

    ``roles`` assigns each replica a serving role for the disaggregated
    prefill/decode topology: ``()`` (default) makes every replica
    ``"both"`` (the classic homogeneous pool); otherwise it must name
    one of ``prefill`` / ``decode`` / ``both`` per replica. With at
    least one ``prefill`` member the router admits new requests onto
    the prefill tier and MIGRATES the finished prompt's KV to a decode
    replica (serve/migrate.py); ``both`` members belong to the decode
    tier and double as the local-decode degradation target."""

    replicas: int = 2
    roles: Tuple[str, ...] = ()
    probe_interval_s: float = 0.5
    probe_timeout_s: float = 5.0
    probe_misses: int = 3
    route_retries: int = 2
    retry_backoff_base_s: float = 0.05
    retry_backoff_max_s: float = 1.0
    forward_timeout_s: float = 120.0
    restart_backoff_base_s: float = 0.25
    restart_backoff_max_s: float = 5.0
    max_restart_failures: int = 5
    spawn_timeout_s: float = 300.0
    drain_timeout_s: float = 30.0
    seed: int = 0
    # Fleet-wide KV reuse (PR 17, serve/fleetcache): route by prefix
    # affinity instead of pure least-loaded, using the trie digests
    # replicas piggyback on /healthz. digest_interval_s is the
    # replica-side rebuild cadence (the CLI forwards it to workers);
    # digest_max_entries bounds each digest's size on the wire.
    affinity_routing: bool = False
    digest_interval_s: float = 2.0
    digest_max_entries: int = 256
    # Elastic autoscaling (PR 19): when BOTH bounds are set the monitor
    # scales the live replica count between them from fleet pressure —
    # average /healthz queue depth per live replica and the
    # router.prefill_wait_s p90 (the PR 12 trace segment). A signal
    # must hold for autoscale_sustain_ticks consecutive monitor ticks
    # before acting, and actions are spaced by autoscale_cooldown_s —
    # the two-sided hysteresis that keeps a bursty queue from flapping
    # the fleet. Scale-up spawns through the PR 6 machinery (a failed
    # spawn counts against that replica's circuit breaker); scale-down
    # rolling-drains ONE replica gracefully. None/None (default)
    # disables the loop entirely.
    autoscale_min: Optional[int] = None
    autoscale_max: Optional[int] = None
    autoscale_up_queue: float = 4.0      # queued per live replica
    autoscale_up_wait_s: float = 1.0     # prefill_wait p90 bound
    autoscale_sustain_ticks: int = 3
    autoscale_cooldown_s: float = 5.0

    def __post_init__(self):
        if self.replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {self.replicas}")
        if self.probe_misses < 1:
            raise ValueError("probe_misses must be >= 1")
        if self.route_retries < 0:
            raise ValueError("route_retries must be >= 0")
        if self.max_restart_failures < 1:
            raise ValueError("max_restart_failures must be >= 1")
        if self.digest_interval_s <= 0:
            raise ValueError("digest_interval_s must be > 0")
        if self.digest_max_entries < 1:
            raise ValueError("digest_max_entries must be >= 1")
        roles = tuple(self.roles)
        if roles:
            if len(roles) != self.replicas:
                raise ValueError(
                    f"roles names {len(roles)} replica(s), "
                    f"replicas={self.replicas}")
            bad = sorted(set(roles) - {"prefill", "decode", "both"})
            if bad:
                raise ValueError(
                    f"roles must be 'prefill'/'decode'/'both', got {bad}")
            if "prefill" in roles and not any(
                    r in ("decode", "both") for r in roles):
                raise ValueError(
                    "a prefill tier needs at least one decode-capable "
                    "replica (role 'decode' or 'both')")
        object.__setattr__(self, "roles", roles)
        if (self.autoscale_min is None) != (self.autoscale_max is None):
            raise ValueError(
                "autoscale needs BOTH bounds (autoscale_min and "
                "autoscale_max) or neither")
        if self.autoscale_max is not None:
            if self.autoscale_min < 1:
                raise ValueError("autoscale_min must be >= 1")
            if self.autoscale_max < self.autoscale_min:
                raise ValueError(
                    f"autoscale_max ({self.autoscale_max}) must be >= "
                    f"autoscale_min ({self.autoscale_min})")
            if not (self.autoscale_min <= self.replicas
                    <= self.autoscale_max):
                raise ValueError(
                    f"replicas={self.replicas} must start inside "
                    f"[autoscale_min, autoscale_max] = "
                    f"[{self.autoscale_min}, {self.autoscale_max}]")
            if roles:
                raise ValueError(
                    "autoscale requires a homogeneous pool — it cannot "
                    "grow/shrink a fixed roles topology")
            if self.autoscale_sustain_ticks < 1:
                raise ValueError("autoscale_sustain_ticks must be >= 1")
            if self.autoscale_cooldown_s < 0:
                raise ValueError("autoscale_cooldown_s must be >= 0")
            if self.autoscale_up_queue <= 0:
                raise ValueError("autoscale_up_queue must be > 0")
            if self.autoscale_up_wait_s <= 0:
                raise ValueError("autoscale_up_wait_s must be > 0")

    def role_of(self, rid: int) -> str:
        return self.roles[rid] if self.roles else "both"

    @property
    def autoscale_enabled(self) -> bool:
        """True when both elastic bounds are set — the monitor then
        runs the autoscale control loop every tick."""
        return self.autoscale_max is not None

    @property
    def disaggregated(self) -> bool:
        """True when the topology has a dedicated prefill tier — the
        router then admits onto it and migrates KV to the decode
        tier."""
        return "prefill" in self.roles

    @property
    def digest_stale_s(self) -> float:
        """How old a replica's digest may be (replica-reported age +
        time since its probe landed) before the affinity scorer
        ignores it — a few rebuild intervals, floored at a few probe
        rounds so slow probing doesn't blind the scorer entirely."""
        return max(3.0 * self.digest_interval_s,
                   4.0 * self.probe_interval_s)


def replica_exec_point() -> None:
    """The ``replica.exec`` fault point: hit once when a replica worker
    begins executing, BEFORE it builds its engine. Both worker hosts
    route through here — the subprocess entry (``cli/serve.run_worker``)
    and the in-process thread worker — keeping one registered call site
    (tools/check_fault_points.py requires names to be unique). An
    ``error`` rule makes the replica crash at startup: the drill behind
    the supervisor's restart backoff and circuit breaker."""
    faults.point("replica.exec")


def free_port() -> int:
    """An ephemeral localhost port. Bound-then-released, so a parallel
    process could steal it before the worker binds — the supervisor's
    restart path absorbs that exactly like any other startup failure."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class Replica:
    """One replica's record: lifecycle (supervisor's), health (router's
    probe verdict), and routing load — mutated only under the
    supervisor lock so the two layers can't disagree."""

    rid: int
    role: str = "both"          # prefill | decode | both (routing tier)
    state: str = STARTING
    handle: Optional[object] = None
    port: int = 0
    healthy: bool = False
    probe_misses: int = 0
    restart_failures: int = 0
    next_restart_t: float = 0.0
    spawned_t: float = 0.0
    in_flight: int = 0
    last_health: Dict[str, object] = dataclasses.field(default_factory=dict)
    # Monotonic timestamp of the last SUCCESSFUL probe (0.0 = never) —
    # the router's affinity scorer judges digest staleness against it.
    probed_t: float = 0.0
    error: Optional[str] = None


# ------------------------------------------------------------- backends
class ProcessHandle:
    """A replica hosted as an OS process."""

    def __init__(self, proc: subprocess.Popen, port: int):
        self.proc = proc
        self.port = port

    def alive(self) -> bool:
        return self.proc.poll() is None

    def terminate(self) -> None:
        """Graceful: SIGTERM — the worker's own PR-4 drain semantics
        (admission closes, in-flight finishes within its
        --drain-timeout, stragglers cancel as "deadline")."""
        if self.alive():
            try:
                self.proc.terminate()
            except OSError:
                pass

    def kill(self) -> None:
        """Abrupt: SIGKILL — the chaos/crash path. The OS closes the
        worker's sockets, so the router sees connection resets."""
        if self.alive():
            try:
                self.proc.kill()
            except OSError:
                pass

    def wait(self, timeout: float) -> bool:
        try:
            self.proc.wait(timeout=timeout)
            return True
        except subprocess.TimeoutExpired:
            return False


class ProcessBackend:
    """Spawn replicas as ``nezha-serve`` subprocesses (the production
    backend): each runs the full single-replica stack via
    ``cli/serve.run_worker`` — the SAME code path ``--replicas 1``
    uses. ``argv_for`` maps ``(rid, port)`` to the worker argv
    (cli/serve.py builds it from the front end's own flags); stderr
    goes to ``log_dir/replica<rid>.log`` when given (the listening
    banner and tracebacks land there), else is inherited."""

    kind = "process"

    def __init__(self, argv_for: Callable[[int, int], List[str]],
                 env: Optional[Dict[str, str]] = None,
                 log_dir: Optional[str] = None):
        self.argv_for = argv_for
        self.env = env
        self.log_dir = log_dir

    def spawn(self, rid: int, port: int) -> ProcessHandle:
        stderr = None
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            stderr = open(os.path.join(self.log_dir,
                                       f"replica{rid}.log"), "ab")
        try:
            proc = subprocess.Popen(
                self.argv_for(rid, port), stdin=subprocess.DEVNULL,
                stdout=subprocess.DEVNULL, stderr=stderr,
                env=self.env)
        finally:
            if stderr is not None:
                stderr.close()   # the child holds its own fd now
        return ProcessHandle(proc, port)


class ThreadHandle:
    """A replica hosted as an in-process worker thread."""

    def __init__(self, worker: "_ThreadWorker"):
        self.worker = worker
        self.port = worker.port

    def alive(self) -> bool:
        return not self.worker.dead.is_set()

    def terminate(self) -> None:
        self.worker.drain()

    def kill(self) -> None:
        self.worker.kill()

    def wait(self, timeout: float) -> bool:
        return self.worker.dead.wait(timeout)


class ThreadBackend:
    """Host replicas as in-process worker threads behind real HTTP
    sockets — the test/benchmark backend. Each replica still builds its
    OWN engine/scheduler from ``worker_args`` (a parsed ``nezha-serve``
    namespace) and is reached over 127.0.0.1 exactly like a process
    replica, so the router code has ONE transport; what thread hosting
    trades away is OS-level isolation (a worker that corrupts the
    interpreter takes the house down — production uses
    :class:`ProcessBackend`). ``kill()`` severs the worker's open
    sockets before stopping it, so the router observes the same
    connection resets a SIGKILL produces."""

    kind = "thread"

    def __init__(self, worker_args, drain_timeout_s: float = 30.0,
                 roles: Optional[Sequence[str]] = None):
        self.worker_args = worker_args
        self.drain_timeout_s = drain_timeout_s
        self.roles = tuple(roles) if roles else ()

    def spawn(self, rid: int, port: int) -> ThreadHandle:
        # port is ignored: the worker binds port 0 and reports the real
        # one via the handle — no bind race to absorb.
        args = self.worker_args
        if self.roles:
            import copy
            args = copy.copy(args)
            args.role = self.roles[rid]
        worker = _ThreadWorker(args, rid,
                               drain_timeout_s=self.drain_timeout_s)
        worker.start()
        return ThreadHandle(worker)


# Engine builds trace + compile; serializing them keeps concurrent
# replica spawns deterministic and off each other's compile locks.
_BUILD_LOCK = threading.Lock()


class _ThreadWorker:
    """One in-process replica: engine + scheduler + a /generate +
    /healthz HTTP server matching the ``cli/serve.run_http`` protocol,
    purpose-built to be KILLABLE (connection tracking, daemon handler
    threads, abrupt socket severing) — the properties an OS process
    gets for free and a thread has to engineer."""

    # Handler threads, the decode loop, and kill() all touch these —
    # declared for nezha-lint's lock-discipline rule.
    _LOCK_GUARDED = {"_events": "_events_lock", "_conns": "_conns_lock"}

    def __init__(self, worker_args, rid: int, drain_timeout_s: float):
        from http.server import ThreadingHTTPServer

        self.args = worker_args
        self.rid = rid
        self.drain_timeout_s = drain_timeout_s
        self.dead = threading.Event()     # worker finished, any cause
        self.crashed = False
        self._drain_evt = threading.Event()
        self._killed = threading.Event()
        self._ready = threading.Event()   # stack built, serving
        self._sched = None
        self._events: Dict[str, threading.Event] = {}
        self._events_lock = threading.Lock()
        self._conns = set()
        self._conns_lock = threading.Lock()

        worker = self

        from http.server import BaseHTTPRequestHandler

        class Handler(BaseHTTPRequestHandler):
            timeout = 60

            def log_message(self, *a):
                pass

            def setup(self):
                super().setup()
                with worker._conns_lock:
                    worker._conns.add(self.connection)

            def finish(self):
                with worker._conns_lock:
                    worker._conns.discard(self.connection)
                try:
                    super().finish()
                except OSError:
                    pass    # connection already severed by kill()

            def _send(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/stats":
                    # Live registry snapshot (stats schema v1) — the
                    # per-replica row of the router's fleet view.
                    # Thread workers share the process registry, so
                    # every member answers the same numbers (carrying
                    # the same registry_id, which the router's fleet
                    # roll-up dedupes on; the production process
                    # backend is per-process).
                    payload = obs.stats_snapshot()
                    payload["role"] = getattr(worker.args, "role",
                                              "both")
                    if worker._ready.is_set():
                        payload["tenants"] = (
                            worker._sched.tenant_queue_depths())
                    return self._send(200, payload)
                if self.path == "/windows":
                    # Mergeable window views (sketch bucket counts
                    # ride along) — what the router scrapes for the
                    # fleet /metrics roll-up.
                    return self._send(200, obs.windows_payload())
                if self.path == "/metrics":
                    body = obs.render_prometheus(
                        obs.stats_snapshot(),
                        obs.windows_payload()).encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path != "/healthz":
                    return self._send(404, {"error": "unknown path"})
                if not worker._ready.is_set():
                    return self._send(503, {"status": "starting"})
                if worker._drain_evt.is_set() or worker._killed.is_set():
                    return self._send(503, {"status": "draining"})
                sched = worker._sched
                pool = sched.engine.pool
                payload = {
                    "status": "ok", "active": pool.num_active,
                    "capacity": pool.capacity,
                    "queued": sched.queue_depth,
                    "occupancy": pool.occupancy,
                    "role": getattr(worker.args, "role", "both"),
                    "parked": sched.parked_count,
                    # Per-tenant queue depths + suspended count
                    # (PR 19): same surface run_http mounts, so the
                    # router sees one replica protocol.
                    "tenants": sched.tenant_queue_depths(),
                    "preempted": sched.preempted_count}
                # Fleet digest piggyback (PR 17): the prober is the
                # transport — no extra endpoint, no extra round trip.
                payload.update(sched.fleet_digest(
                    getattr(worker.args, "digest_interval", 2.0),
                    getattr(worker.args, "digest_max_entries", 256)))
                self._send(200, payload)

            def do_POST(self):
                worker._handle_post(self)

        class Server(ThreadingHTTPServer):
            # Handlers are daemons here, unlike run_http: a killed
            # replica abandons its parked handlers by design (their
            # sockets are already severed), and non-daemon threads
            # would wedge interpreter exit.
            daemon_threads = True

            def handle_error(self, request, client_address):
                pass     # severed sockets raise in handlers — expected

        self._server = Server(("127.0.0.1", 0), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"nezha-replica-{rid}")

    def start(self) -> None:
        self._thread.start()

    # ---------------------------------------------------- request path
    def _handle_post(self, h) -> None:
        """Route one POST: ``/generate`` (plain, ``prefill_only``,
        ``pull_from``, or ``resume``) plus the migration endpoints
        ``/kv_export`` / ``/kv_ack`` — the same surface
        ``cli/serve.run_http`` mounts, so the router sees ONE replica
        protocol regardless of backend."""
        if h.path in ("/kv_export", "/kv_ack"):
            if not self._ready.is_set():
                return h._send(503, {"error": "starting"})
            from nezha_tpu.serve import migrate
            n = int(h.headers.get("Content-Length", 0))
            return h._send(*migrate.dispatch_kv_endpoint(
                self._sched, h.path, h.rfile.read(n)))
        self._handle_generate(h)

    def _handle_generate(self, h) -> None:
        if h.path != "/generate":
            return h._send(404, {"error": "unknown path"})
        if not self._ready.is_set():
            return h._send(503, {"error": "starting"})
        if self._drain_evt.is_set() or self._killed.is_set():
            return h._send(503, {"error": "draining"})
        from nezha_tpu.cli.serve import _parse_request, _result_obj
        from nezha_tpu.serve import QueueFull, migrate
        sched = self._sched
        try:
            n = int(h.headers.get("Content-Length", 0))
            obj = json.loads(h.rfile.read(n))
        except (ValueError, json.JSONDecodeError) as e:
            return h._send(400, {"error": str(e)})
        obs.adopt_trace_header(h.headers, obj)
        if isinstance(obj, dict) and obj.get("resume"):
            return self._handle_resume(h, str(obj["resume"]))
        mig_meta = None
        fleet_meta = None
        pull = obj.get("pull_from") if isinstance(obj, dict) else None
        if isinstance(pull, dict) and "tokens" in pull \
                and "request_id" not in pull:
            # Fleet peer pull (PR 17): fetch covering prefix blocks
            # from the sibling the router named, then fall through to
            # ordinary admission so submit prefix-hits them. Failure
            # DEGRADES to a cold prefill — never an HTTP error; the
            # pull is an optimization, not a dependency.
            try:
                fleet_meta = migrate.pull_prefix_into(sched, pull)
            except migrate.MigrationError as e:
                fleet_meta = {"bytes": 0, "blocks": 0, "installed": 0,
                              "degraded": str(e), "error_type": e.kind}
        elif pull is not None:
            # Decode side of a migration: pull + install + ACK before
            # admission, so the submit below prefix-hits the installed
            # blocks. Failure is HTTP 424 — the router's retry signal.
            try:
                mig_meta = migrate.pull_into(sched, pull)
            except migrate.MigrationError as e:
                return h._send(424, {"error": str(e),
                                     "error_type": e.kind})
        try:
            req = _parse_request(obj, self.args,
                                 self._tokenizer, self._eos_id,
                                 sched.engine.vocab)
        except ValueError as e:
            return h._send(400, {"error": str(e)})
        import uuid
        rid = req.request_id or f"r{self.rid}-{uuid.uuid4().hex[:12]}"
        req.request_id = rid
        ev = threading.Event()
        with self._events_lock:
            if rid in self._events:
                return h._send(409, {"error": f"request id {rid!r} "
                                              f"already in flight"})
            self._events[rid] = ev
        try:
            sched.submit(req)
        except QueueFull as e:
            with self._events_lock:
                self._events.pop(rid, None)
            return h._send(503, {"error": str(e)})
        except ValueError as e:
            with self._events_lock:
                self._events.pop(rid, None)
            return h._send(400, {"error": str(e)})
        if self.dead.is_set():
            # TOCTOU guard (same race run_http closes): the worker
            # finished its final waiter sweep between the admission
            # check above — which ran before this request's body
            # finished uploading — and the submit. Nobody will ever
            # retire the request or set the event, so answer 503 now
            # instead of parking on ev.wait() forever.
            with self._events_lock:
                self._events.pop(rid, None)
            return h._send(503, {"error": "draining"})
        ev.wait()
        with self._events_lock:
            self._events.pop(rid, None)
        res = sched.results.pop(rid, None)
        if res is None:
            return h._send(503, {"error": "replica stopped"})
        out = _result_obj(res, self._tokenizer)
        out.pop("event")
        if mig_meta is not None:
            out["migration"] = mig_meta
        if fleet_meta is not None:
            out["fleet_pull"] = fleet_meta
        h._send(200, out)

    def _handle_resume(self, h, rid: str) -> None:
        """Local-decode fallback: move a parked request into this
        replica's live set and answer with its finished result — the
        ``role=both`` degradation the router takes when the decode
        tier is gone or every migration attempt failed."""
        from nezha_tpu.cli.serve import _result_obj
        if self._drain_evt.is_set() or self._killed.is_set():
            return h._send(503, {"error": "draining"})
        sched = self._sched
        ev = threading.Event()
        with self._events_lock:
            if rid in self._events:
                return h._send(409, {"error": f"request id {rid!r} "
                                              f"already in flight"})
            self._events[rid] = ev
        if not sched.resume_parked(rid):
            with self._events_lock:
                self._events.pop(rid, None)
            return h._send(404, {"error": f"request {rid!r} is not "
                                          f"parked here",
                                 "error_type": "migration_failed"})
        if self.dead.is_set():
            with self._events_lock:
                self._events.pop(rid, None)
            return h._send(503, {"error": "draining"})
        ev.wait()
        with self._events_lock:
            self._events.pop(rid, None)
        res = sched.results.pop(rid, None)
        if res is None:
            return h._send(503, {"error": "replica stopped"})
        out = _result_obj(res, self._tokenizer)
        out.pop("event")
        out["resumed"] = True
        h._send(200, out)

    # ------------------------------------------------------ worker body
    def _run(self) -> None:
        try:
            replica_exec_point()
            with _BUILD_LOCK:
                from nezha_tpu.cli.serve import _build_stack
                sched, tokenizer, eos_id = _build_stack(self.args)
            self._tokenizer, self._eos_id = tokenizer, eos_id

            def on_finish(res):
                with self._events_lock:
                    ev = self._events.get(res.request_id)
                if ev is not None:
                    ev.set()

            sched.on_finish = on_finish
            self._sched = sched
            self._ready.set()
            threading.Thread(target=self._server.serve_forever,
                             kwargs={"poll_interval": 0.05},
                             daemon=True).start()
            while not self._killed.is_set() and not self._drain_evt.is_set():
                if not sched.step():
                    time.sleep(0.002)
            if self._drain_evt.is_set() and not self._killed.is_set():
                # Graceful drain: admission already closed (the handler
                # checks the event); finish in-flight within the
                # budget, cancel stragglers as "deadline".
                t_end = time.monotonic() + self.drain_timeout_s
                while (sched.has_work() and time.monotonic() < t_end
                       and not self._killed.is_set()):
                    if not sched.step():
                        time.sleep(0.002)
                sched.cancel_remaining()
        except BaseException:
            self.crashed = True
        finally:
            self.dead.set()
            try:
                self._server.shutdown()
                self._server.server_close()
            except OSError:
                pass
            self._release_waiters()

    def _release_waiters(self) -> None:
        sched = self._sched
        if sched is not None:
            try:
                from nezha_tpu.serve import FinishReason
                sched.cancel_remaining(FinishReason.ERROR,
                                       error="replica stopped")
            except Exception:
                pass
        with self._events_lock:
            for ev in self._events.values():
                ev.set()

    # -------------------------------------------------------- lifecycle
    def drain(self) -> None:
        self._drain_evt.set()

    def kill(self) -> None:
        """Abrupt stop, modelled on SIGKILL: sever every open
        connection FIRST (the router observes resets, exactly like a
        killed process), then stop the decode loop and the server."""
        self._killed.set()
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        try:
            self._server.shutdown()
            self._server.server_close()
        except OSError:
            pass
        self._release_waiters()


# ------------------------------------------------------------ supervisor
class Supervisor:
    """Owns the replica set: spawns it, restarts crashed members with
    capped seeded backoff (circuit breaker after
    ``cfg.max_restart_failures`` consecutive startup failures), and
    performs the rolling drain. The router reads/writes health and load
    through the accessor methods — every mutation happens under one
    lock. ``tick()`` is the monitor step; ``start()`` runs it on a
    background thread, tests may drive it directly."""

    tick_interval_s = 0.05

    # Cross-thread state -> guarding lock (enforced by nezha-lint's
    # lock-discipline rule): the monitor tick, the router's prober, and
    # HTTP handler threads all touch the replica records and ledgers.
    _LOCK_GUARDED = {"_replicas": "_lock", "_draining": "_lock",
                     "restarts": "_lock", "_rng": "_lock",
                     "_as_up_ticks": "_lock", "_as_down_ticks": "_lock",
                     "_as_cooldown_t": "_lock", "_as_target": "_lock"}

    def __init__(self, backend, cfg: RouterConfig):
        self.backend = backend
        self.cfg = cfg
        self._replicas = [Replica(rid=i, role=cfg.role_of(i))
                          for i in range(cfg.replicas)]
        self._lock = threading.RLock()
        self._rng = random.Random(cfg.seed)
        self._draining = False
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self.restarts = 0     # obs counters only count inside a run;
        #                       this plain ledger always does
        # Elastic autoscale state (PR 19): consecutive-tick pressure
        # counters (the sustain side of the hysteresis), the monotonic
        # time before which no further action may fire (the cooldown
        # side), and the current target scale the gauge reports.
        self._as_up_ticks = 0
        self._as_down_ticks = 0
        self._as_cooldown_t = 0.0
        self._as_target = cfg.replicas
        from nezha_tpu.serve.router import register_router_instruments
        register_router_instruments()
        obs.gauge("router.autoscale_target").set(self._as_target)

    # ------------------------------------------------------- lifecycle
    def start(self) -> None:
        with self._lock:
            for r in self._replicas:
                try:
                    self._spawn(r)
                except Exception as e:
                    self._spawn_failed(r, e, time.monotonic())
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name="nezha-supervisor")
        self._monitor.start()

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.tick_interval_s):
            self.tick()

    def tick(self) -> None:
        """One monitor step: notice deaths, time out wedged startups,
        perform restarts that have reached their backoff time."""
        now = time.monotonic()
        with self._lock:
            if self._draining:
                return
            for r in self._replicas:
                if r.state in (STARTING, LIVE) and not r.handle.alive():
                    self._note_death(r, now, "replica died")
                elif (r.state == STARTING
                        and now - r.spawned_t > self.cfg.spawn_timeout_s):
                    r.handle.kill()
                    self._note_death(r, now, "startup timed out")
                elif r.state == DEAD and now >= r.next_restart_t:
                    self._restart(r, now)
        if self.cfg.autoscale_enabled:
            self.autoscale_tick(now)

    def shutdown(self) -> None:
        """Stop the monitor and kill whatever is still running (the
        abrupt teardown — :meth:`rolling_drain` is the graceful one)."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
        with self._lock:
            self._draining = True
            for r in self._replicas:
                if r.handle is not None and r.state not in (STOPPED,
                                                            FAILED):
                    r.handle.kill()
                    r.state = STOPPED
                r.healthy = False
            self._update_live_gauge()

    # ------------------------------------------------------- internals
    def _spawn(self, r: Replica) -> None:
        """[holds: _lock] Raises on spawn failure (callers route the
        exception into the backoff/breaker accounting)."""
        faults.point("supervisor.spawn")
        port = free_port()
        r.handle = self.backend.spawn(r.rid, port)
        r.port = getattr(r.handle, "port", port)
        r.state = STARTING
        r.healthy = False
        r.probe_misses = 0
        r.spawned_t = time.monotonic()
        r.error = None

    def _spawn_failed(self, r: Replica, e: Exception, now: float) -> None:
        """[holds: _lock]"""
        r.restart_failures += 1
        r.error = f"spawn failed: {type(e).__name__}: {e}"
        if r.restart_failures >= self.cfg.max_restart_failures:
            r.state = FAILED
            print(f"supervisor: replica {r.rid} circuit breaker OPEN "
                  f"after {r.restart_failures} consecutive failures "
                  f"({r.error})", file=sys.stderr)
        else:
            r.state = DEAD
            r.next_restart_t = now + self._restart_backoff(
                r.restart_failures)

    def _note_death(self, r: Replica, now: float, why: str) -> None:
        """[holds: _lock]"""
        # Only deaths that never reached a healthy probe count toward
        # the breaker: a replica that serves and then gets killed is
        # RECOVERING each time, not failing to start.
        if r.state == STARTING:
            r.restart_failures += 1
        r.healthy = False
        r.error = why
        if r.restart_failures >= self.cfg.max_restart_failures:
            r.state = FAILED
            print(f"supervisor: replica {r.rid} circuit breaker OPEN "
                  f"after {r.restart_failures} consecutive startup "
                  f"failures", file=sys.stderr)
        else:
            r.state = DEAD
            r.next_restart_t = now + self._restart_backoff(
                r.restart_failures)
        self._update_live_gauge()

    def _restart(self, r: Replica, now: float) -> None:
        """[holds: _lock] — tick() performs restarts inside the lock."""
        try:
            self._spawn(r)
        except Exception as e:
            self._spawn_failed(r, e, now)
            return
        self.restarts += 1
        obs.counter("router.replica_restarts_total").inc()

    def _restart_backoff(self, failures: int) -> float:
        """[holds: _lock] — the seeded RNG stream is shared state."""
        base = min(self.cfg.restart_backoff_base_s * (2 ** failures),
                   self.cfg.restart_backoff_max_s)
        return base * (0.5 + self._rng.random())   # ±50% seeded jitter

    def _update_live_gauge(self) -> None:
        obs.gauge("router.replicas_live").set(sum(
            1 for r in self._replicas if r.state == LIVE and r.healthy))

    # ------------------------------------------------- router interface
    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas)

    def live_replicas(self) -> List[Replica]:
        with self._lock:
            return [r for r in self._replicas
                    if r.state == LIVE and r.healthy]

    def live_count(self) -> int:
        return len(self.live_replicas())

    def describe(self) -> List[dict]:
        with self._lock:
            return [{"rid": r.rid, "role": r.role, "port": r.port,
                     "state": r.state, "healthy": r.healthy,
                     "in_flight": r.in_flight,
                     "restart_failures": r.restart_failures}
                    for r in self._replicas]

    def mark_probe(self, rid: int, ok: bool,
                   payload: Optional[dict] = None) -> None:
        """The prober's verdict for one replica: a success readmits it
        (and promotes STARTING -> LIVE, resetting the breaker count);
        ``cfg.probe_misses`` consecutive failures eject it."""
        with self._lock:
            r = self._replicas[rid]
            if r.state not in (STARTING, LIVE):
                return
            if ok:
                r.probe_misses = 0
                r.last_health = dict(payload or {})
                r.probed_t = time.monotonic()
                r.healthy = True
                if r.state == STARTING:
                    r.state = LIVE
                    r.restart_failures = 0
            else:
                r.probe_misses += 1
                if r.healthy and r.probe_misses >= self.cfg.probe_misses:
                    r.healthy = False    # ejected until a probe succeeds
            self._update_live_gauge()

    def note_forward_failure(self, rid: int) -> None:
        """A forward died on the wire — stronger evidence than a missed
        probe, so the replica is ejected immediately; the prober
        readmits it the moment it answers again."""
        with self._lock:
            r = self._replicas[rid]
            r.healthy = False
            r.probe_misses = max(r.probe_misses, self.cfg.probe_misses)
            self._update_live_gauge()

    def add_in_flight(self, rid: int, delta: int) -> None:
        with self._lock:
            self._replicas[rid].in_flight += delta

    # ------------------------------------------------------------ chaos
    def kill(self, rid: int) -> None:
        """Hard-kill one replica (chaos): its sockets sever, in-flight
        forwards fail, and the monitor restarts it with backoff."""
        with self._lock:
            handle = self._replicas[rid].handle
        if handle is not None:
            handle.kill()

    # -------------------------------------------------------- autoscale
    def autoscale_target(self) -> int:
        with self._lock:
            return self._as_target

    def autoscale_tick(self, now: Optional[float] = None
                       ) -> Optional[str]:
        """One elastic control step (PR 19): read fleet pressure —
        total /healthz-reported queue depth per live replica, plus the
        ``router.prefill_wait_s`` p90 trace segment — and scale the
        replica count within ``[autoscale_min, autoscale_max]``.
        Two-sided hysteresis: a signal must hold for
        ``autoscale_sustain_ticks`` CONSECUTIVE ticks (a mixed reading
        resets both counters — the deadband), actions are spaced by
        ``autoscale_cooldown_s``, and exactly one replica moves per
        action. The ``supervisor.scale`` fault point fires at the
        decision: an injected error is the typed degradation drill —
        the action is skipped, pressure re-evaluates next tick, and the
        fleet stays at its current size. Returns "up"/"down"/None so
        tests can assert the ladder without timing games."""
        cfg = self.cfg
        if not cfg.autoscale_enabled:
            return None
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._draining:
                return None
            active = [r for r in self._replicas
                      if r.state not in (STOPPED, FAILED)]
            live = [r for r in active
                    if r.state == LIVE and r.healthy]
            queued = sum(int((r.last_health or {}).get("queued", 0))
                         for r in live)
            per_live = queued / max(1, len(live))
            wait_p90 = obs.histogram(
                "router.prefill_wait_s").percentile(90)
            hot = (per_live >= cfg.autoscale_up_queue
                   or (wait_p90 is not None
                       and wait_p90 >= cfg.autoscale_up_wait_s))
            idle = (queued == 0 and live
                    and all(r.in_flight == 0 for r in live))
            if hot:
                self._as_up_ticks += 1
                self._as_down_ticks = 0
            elif idle:
                self._as_down_ticks += 1
                self._as_up_ticks = 0
            else:
                # Deadband: neither hot nor fully idle — hold scale and
                # make BOTH signals re-earn their sustain run.
                self._as_up_ticks = 0
                self._as_down_ticks = 0
            obs.gauge("router.autoscale_target").set(self._as_target)
            if now < self._as_cooldown_t:
                return None
            n = len(active)
            decision = None
            if (self._as_up_ticks >= cfg.autoscale_sustain_ticks
                    and n < cfg.autoscale_max):
                decision = "up"
            elif (self._as_down_ticks >= cfg.autoscale_sustain_ticks
                    and n > cfg.autoscale_min):
                decision = "down"
            if decision is None:
                return None
            try:
                faults.point("supervisor.scale")
            except Exception:
                return None
            self._as_up_ticks = 0
            self._as_down_ticks = 0
            self._as_cooldown_t = now + cfg.autoscale_cooldown_s
            self._as_target = n + 1 if decision == "up" else n - 1
            obs.gauge("router.autoscale_target").set(self._as_target)
            if decision == "up":
                self._scale_up(now)
                return "up"
            # Scale-down: gracefully drain the HIGHEST-rid active
            # replica (LIFO keeps the stable base of the fleet the
            # long-lived members) on its own thread — the per-replica
            # drain blocks up to the drain budget and must not stall
            # the monitor loop.
            victim = active[-1]
            victim.state = DRAINING
        threading.Thread(
            target=self._drain_one,
            args=(victim, cfg.drain_timeout_s),
            daemon=True, name=f"nezha-scale-down-{victim.rid}").start()
        return "down"

    def _scale_up(self, now: float) -> None:
        """[holds: _lock] Add one replica: re-arm a previously drained
        (STOPPED) record if one exists — keeping the rid==index
        invariant the router's ledgers rely on — else append a fresh
        one. Spawn failures route into the PR 6 backoff/breaker
        accounting exactly like a restart."""
        r = next((x for x in self._replicas if x.state == STOPPED), None)
        if r is None:
            r = Replica(rid=len(self._replicas), role="both")
            self._replicas.append(r)
        else:
            r.restart_failures = 0
        try:
            self._spawn(r)
        except Exception as e:
            self._spawn_failed(r, e, now)

    # ------------------------------------------------------------ drain
    def _drain_one(self, r: Replica,
                   timeout_s: float,
                   progress: Optional[Callable[[int], None]] = None
                   ) -> None:
        """Gracefully stop ONE replica: graceful terminate, up to
        ``timeout_s`` for its in-flight work, then the hard stop — the
        per-replica body both :meth:`rolling_drain` and the autoscale
        scale-down share. Safe to call with the replica already marked
        DRAINING (the scale-down path does, inside its decision
        lock)."""
        with self._lock:
            handle = r.handle
            if r.state in (STOPPED, FAILED) or handle is None:
                return
            r.state = DRAINING
            self._update_live_gauge()
        if handle.alive():
            handle.terminate()
            # The worker runs its own drain inside; +5s covers its
            # shutdown tail so a healthy drain never gets killed at
            # exactly the budget.
            if not handle.wait(timeout_s + 5.0):
                handle.kill()
                handle.wait(5.0)
        with self._lock:
            r.state = STOPPED
            r.healthy = False
            self._update_live_gauge()
        if progress is not None:
            progress(self.live_count())

    def rolling_drain(self, timeout_s: Optional[float] = None,
                      progress: Optional[Callable[[int], None]] = None
                      ) -> None:
        """Drain replicas ONE AT A TIME: each gets a graceful stop and
        up to ``timeout_s`` (default ``cfg.drain_timeout_s``) to finish
        its in-flight work while every later replica keeps serving — so
        live capacity steps down one replica per round and only reaches
        zero when the last one exits. Restarts are frozen for the
        duration. ``progress(live_count)`` fires after each replica
        stops (tests assert the never-zero-mid-drain ladder with it)."""
        timeout_s = (self.cfg.drain_timeout_s if timeout_s is None
                     else timeout_s)
        with self._lock:
            self._draining = True
        for r in self._replicas:
            self._drain_one(r, timeout_s, progress)
