"""Per-row sampling for the batched decode step.

``models/generate._sample`` keys its compiled program on PYTHON-level
sampling params — fine for one-shot batch decode, fatal for serving,
where every recompile stalls the whole batch. Here temperature / top-k /
top-p arrive as TRACED ``[B]`` arrays, so one compiled step serves every
mix of requests:

- temperature ``<= 0`` selects greedy argmax for that row (no RNG
  consumed — a greedy row's tokens are bit-identical whatever its batch
  neighbors sample);
- top-k cannot be traced through ``lax.top_k`` (its k is static), so the
  step always extracts the static ``k_max`` largest logits (config cap)
  and masks by PER-ROW k via rank comparison against the row's k-th
  value; ``top_k <= 0`` disables truncation for the row, and per-row k is
  clamped to ``[1, k_max]``;
- top-p is the same exclusive-cumsum nucleus as ``_sample`` with p
  broadcast per row (``p >= 1`` keeps everything, ``p <= 0`` degrades to
  argmax via the rank-0 term — never an empty nucleus);
- rows draw from their OWN PRNG key (vmapped categorical), so sampling
  rows are also isolated: a request's token sequence depends only on its
  seed and its step count, never on who shares the batch.

:func:`split_and_sample` packages one decode step's sampling move —
split every row's key, sample from the carried logits — for the
engine's block-decode scan body: the caller advances a row's key only
when the token is actually EMITTED, so a request's RNG stream depends
on its seed and emitted-token count alone, never on the decode horizon
or its batch neighbors (horizon=1 and horizon=8 sample identical
sequences).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def finite_rows(logits) -> jax.Array:
    """``[B, V]`` logits -> ``[B]`` bool: True where every entry in the
    row is finite. The decode step's NaN/inf tripwire: computed in-program
    (two cheap reductions against a forward pass) on both the carried-in
    logits and the fresh row, so the host learns which rows went bad
    without an extra device round-trip — the scheduler retires those
    requests with ``FinishReason.ERROR`` instead of decoding garbage
    forever or killing the batch."""
    return jnp.isfinite(logits).all(axis=-1)


def sample_tokens(logits, keys, temperature, top_k, top_p,
                  k_max: int) -> jax.Array:
    """logits ``[B, V]``, keys ``[B, 2]`` (one PRNG key per row),
    temperature/top_p ``[B]`` float, top_k ``[B]`` int (``<= 0`` = off),
    ``k_max`` static int (``1 <= k_max <= V``) -> token ids ``[B]``.
    """
    b, v = logits.shape
    if not 1 <= k_max <= v:
        raise ValueError(f"k_max must be in [1, {v}], got {k_max}")
    greedy = temperature <= 0.0
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]

    # Per-row top-k under the static cap: the k_max'th-largest values are
    # computed once; each row thresholds at its own (clamped) k-th value.
    kth_vals = lax.top_k(scaled, k_max)[0]                    # [B, k_max]
    k_eff = jnp.clip(top_k, 1, k_max)
    kth = jnp.take_along_axis(kth_vals, (k_eff - 1)[:, None], axis=1)
    apply_k = (top_k > 0)[:, None]
    scaled = jnp.where(apply_k & (scaled < kth), -jnp.inf, scaled)

    # Per-row nucleus (same construction as generate._sample, p per row).
    sorted_logits = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    exclusive_cum = jnp.cumsum(probs, axis=-1) - probs
    rank = lax.broadcasted_iota(jnp.int32, sorted_logits.shape, 1)
    keep = (exclusive_cum < top_p[:, None]) | (rank == 0)
    threshold = jnp.min(
        jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True)
    scaled = jnp.where(scaled < threshold, -jnp.inf, scaled)

    sampled = jax.vmap(jax.random.categorical)(keys, scaled)
    return jnp.where(greedy, jnp.argmax(logits, axis=-1),
                     sampled).astype(jnp.int32)


def split_and_sample(keys, logits, temperature, top_k, top_p,
                     k_max: int):
    """One decode step's sampling move: split every row's PRNG key and
    sample from the carried logits. ``keys`` ``[B, 2]`` -> ``(next_keys
    [B, 2], tokens [B])``. The caller commits ``next_keys`` only for
    rows whose token is actually emitted — that is what keeps a
    request's RNG stream a function of (seed, emitted count) alone, so
    the same request samples bit-identical tokens at any decode horizon
    and next to any batch mix."""
    splits = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
    tok = sample_tokens(logits, splits[:, 1], temperature, top_k, top_p,
                        k_max)
    return splits[:, 0], tok
