"""Per-row sampling for the batched decode step.

``models/generate._sample`` keys its compiled program on PYTHON-level
sampling params — fine for one-shot batch decode, fatal for serving,
where every recompile stalls the whole batch. Here temperature / top-k /
top-p arrive as TRACED ``[B]`` arrays, so one compiled step serves every
mix of requests:

- temperature ``<= 0`` selects greedy argmax for that row (no RNG
  consumed — a greedy row's tokens are bit-identical whatever its batch
  neighbors sample);
- top-k cannot be traced through ``lax.top_k`` (its k is static), so the
  step always extracts the static ``k_max`` largest logits (config cap)
  and masks by PER-ROW k via rank comparison against the row's k-th
  value; ``top_k <= 0`` disables truncation for the row, and per-row k is
  clamped to ``[1, k_max]``;
- top-p is the same exclusive-cumsum nucleus as ``_sample`` with p
  broadcast per row (``p >= 1`` keeps everything, ``p <= 0`` degrades to
  argmax via the rank-0 term — never an empty nucleus);
- rows draw from their OWN PRNG key (vmapped categorical), so sampling
  rows are also isolated: a request's token sequence depends only on its
  seed and its step count, never on who shares the batch.

:func:`split_and_sample` packages one decode step's sampling move —
split every row's key, sample from the carried logits — for the
engine's block-decode scan body: the caller advances a row's key only
when the token is actually EMITTED, so a request's RNG stream depends
on its seed and emitted-token count alone, never on the decode horizon
or its batch neighbors (horizon=1 and horizon=8 sample identical
sequences).

The speculative-decoding kernels live here too (the engine's
draft→verify→accept window composes them): :func:`filter_logits` is the
ONE per-row temperature/top-k/top-p truncation both the classic sampler
and the speculative accept test apply — the rejection test is lossless
for any proposal distribution, but a draft proposal outside the
target's truncated support has p = 0 and always rejects, so the draft
proposes from the same filtered support to keep accept rates at the
draft's actual fidelity; :func:`accept_mask` is the per-position accept
decision (greedy: exact match against the target argmax; sampled: the
standard ``u·q ≤ p`` rejection test); :func:`residual_logits` is the
rejection-resample distribution ``norm(max(p − q, 0))`` in log space —
the engine carries it as the row's next sampling distribution (flagged
``residual``), so the token emitted after a rejection is drawn from
exactly the residual the lossless-speculative-sampling theorem
requires, one window later.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def finite_rows(logits) -> jax.Array:
    """``[B, V]`` logits -> ``[B]`` bool: True where every entry in the
    row is finite. The decode step's NaN/inf tripwire: computed in-program
    (two cheap reductions against a forward pass) on both the carried-in
    logits and the fresh row, so the host learns which rows went bad
    without an extra device round-trip — the scheduler retires those
    requests with ``FinishReason.ERROR`` instead of decoding garbage
    forever or killing the batch."""
    return jnp.isfinite(logits).all(axis=-1)


def filter_logits(logits, temperature, top_k, top_p,
                  k_max: int) -> jax.Array:
    """The per-row temperature/top-k/top-p truncation, factored out of
    :func:`sample_tokens` so the speculative accept test can apply the
    IDENTICAL filtering to draft and target logits: ``[B, V]`` logits ->
    ``[B, V]`` scaled logits with truncated entries at ``-inf``.
    Sampling from the result (``categorical``) is exactly what
    :func:`sample_tokens` does for non-greedy rows."""
    b, v = logits.shape
    if not 1 <= k_max <= v:
        raise ValueError(f"k_max must be in [1, {v}], got {k_max}")
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]

    # Per-row top-k under the static cap: the k_max'th-largest values are
    # computed once; each row thresholds at its own (clamped) k-th value.
    kth_vals = lax.top_k(scaled, k_max)[0]                    # [B, k_max]
    k_eff = jnp.clip(top_k, 1, k_max)
    kth = jnp.take_along_axis(kth_vals, (k_eff - 1)[:, None], axis=1)
    apply_k = (top_k > 0)[:, None]
    scaled = jnp.where(apply_k & (scaled < kth), -jnp.inf, scaled)

    # Per-row nucleus (same construction as generate._sample, p per row).
    sorted_logits = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    exclusive_cum = jnp.cumsum(probs, axis=-1) - probs
    rank = lax.broadcasted_iota(jnp.int32, sorted_logits.shape, 1)
    keep = (exclusive_cum < top_p[:, None]) | (rank == 0)
    threshold = jnp.min(
        jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(scaled < threshold, -jnp.inf, scaled)


def filtered_probs(logits, temperature, top_k, top_p,
                   k_max: int) -> jax.Array:
    """``softmax(filter_logits(...))`` — the probability vector the
    speculative rejection test and residual are computed over."""
    return jax.nn.softmax(filter_logits(logits, temperature, top_k,
                                        top_p, k_max), axis=-1)


def sample_tokens(logits, keys, temperature, top_k, top_p,
                  k_max: int) -> jax.Array:
    """logits ``[B, V]``, keys ``[B, 2]`` (one PRNG key per row),
    temperature/top_p ``[B]`` float, top_k ``[B]`` int (``<= 0`` = off),
    ``k_max`` static int (``1 <= k_max <= V``) -> token ids ``[B]``.
    """
    greedy = temperature <= 0.0
    scaled = filter_logits(logits, temperature, top_k, top_p, k_max)
    sampled = jax.vmap(jax.random.categorical)(keys, scaled)
    return jnp.where(greedy, jnp.argmax(logits, axis=-1),
                     sampled).astype(jnp.int32)


def split_and_sample(keys, logits, temperature, top_k, top_p,
                     k_max: int):
    """One decode step's sampling move: split every row's PRNG key and
    sample from the carried logits. ``keys`` ``[B, 2]`` -> ``(next_keys
    [B, 2], tokens [B])``. The caller commits ``next_keys`` only for
    rows whose token is actually emitted — that is what keeps a
    request's RNG stream a function of (seed, emitted count) alone, so
    the same request samples bit-identical tokens at any decode horizon
    and next to any batch mix."""
    splits = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
    tok = sample_tokens(logits, splits[:, 1], temperature, top_k, top_p,
                        k_max)
    return splits[:, 0], tok


# ------------------------------------------------- speculative decoding
def categorical_rows(keys, logits) -> jax.Array:
    """Per-row categorical draw: ``keys [B, 2]``, ``logits [B, V]`` ->
    ``[B]`` int32. Used for the residual-distribution resample, whose
    logits are ALREADY filtered log-probabilities — re-applying the
    temperature/top-k/top-p filter there would distort the lossless
    rejection-sampling law."""
    return jax.vmap(jax.random.categorical)(keys, logits).astype(
        jnp.int32)


def accept_mask(draft_tokens, p_probs, q_probs, u, greedy,
                target_argmax) -> jax.Array:
    """Per-position speculative accept decision.

    ``draft_tokens [B, K]`` (the k proposed tokens), ``p_probs`` /
    ``q_probs [B, K, V]`` (target / draft distributions at each
    position, BOTH filtered by :func:`filter_logits` with the row's own
    sampling params), ``u [B, K]`` uniforms, ``greedy [B]`` bool,
    ``target_argmax [B, K]`` (per-position argmax of the UNfiltered
    target logits) -> ``[B, K]`` bool accepts.

    Greedy rows accept exactly the tokens classic greedy would have
    emitted (``draft == argmax(p)``) — the bit-identity half of the
    parity gate. Sampled rows run the standard rejection test
    ``u · q(d) < p(d)`` (accept with probability ``min(1, p/q)``; the
    STRICT inequality matters — ``jax.random.uniform`` can return
    exactly 0, and ``0 · q <= 0`` would accept a token the target's
    truncated distribution assigns ZERO probability, an output classic
    sampling could never emit). A draft distribution that went
    non-finite fails the test DETERMINISTICALLY (no ``u`` involved),
    which keeps the emitted stream unbiased: the position simply falls
    back to a fresh sample from the plain target distribution next
    window."""
    psel = jnp.take_along_axis(p_probs, draft_tokens[..., None],
                               axis=2)[..., 0]
    qsel = jnp.take_along_axis(q_probs, draft_tokens[..., None],
                               axis=2)[..., 0]
    q_ok = jnp.isfinite(q_probs).all(axis=-1)
    sampled_acc = q_ok & (u * qsel < psel)
    greedy_acc = draft_tokens == target_argmax
    return jnp.where(greedy[:, None], greedy_acc, sampled_acc)


def residual_logits(p_probs, q_probs) -> jax.Array:
    """The rejection-resample distribution in log space:
    ``log(max(p − q, 0))`` per row (``[B, V]`` each). Sampling
    ``categorical`` from this is the residual draw of standard
    speculative sampling — the engine defers it one window by carrying
    these logits as the row's next sampling distribution. The floor
    guards zero-mass entries from producing ``-inf``: the engine's
    NaN/inf health tripwire (:func:`finite_rows`) runs on the CARRIED
    logits, so a ``-inf`` here would retire the row as poisoned. The
    floor must be a NORMAL fp32 number — XLA's CPU backend flushes
    denormals to zero (``1e-38 -> 0 -> log = -inf``, a bug found by
    driving the real server); ``1e-30`` lands zero-mass entries at
    ~``-69`` in log space, finite yet still zero probability for
    categorical purposes next to any real residual mass."""
    return jnp.log(jnp.maximum(p_probs - q_probs, 0.0) + 1e-30)
