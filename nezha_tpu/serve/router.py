"""The multi-replica HTTP front end: health-checked routing + failover.

One process in front of N single-replica engines (spawned and restarted
by ``serve/supervisor.py``), answering the SAME protocol one replica
does (POST ``/generate``, GET ``/healthz``) so clients cannot tell one
engine from a crowd — except that losing any replica no longer loses
the service:

- **probing** — a background prober GETs each replica's ``/healthz``
  every ``probe_interval_s``; ``probe_misses`` consecutive failures
  EJECT the replica from routing, one success readmits it. A forward
  that dies on the wire ejects immediately (stronger evidence than a
  missed probe).
- **balancing** — least-loaded: the replica with the fewest
  router-tracked in-flight forwards (ties broken by its last-probed
  queue depth). A replica answering 503 (queue full / draining) is
  skipped for that request; the client sees 503 only when EVERY live
  replica refused. With ``affinity_routing`` on (PR 17,
  serve/fleetcache) token-id requests are instead scored by
  prefix-AFFINITY — expected cached-prefix hit length from each
  replica's /healthz trie digest, discounted by load — and a
  near-miss hands the chosen replica a peer ``pull_from`` hint so it
  fetches the covering blocks from the sibling that has them (the
  ``router.kv_pull_s`` span brackets the hop; failure degrades to a
  cold prefill, never an error).
- **failover** — a replica that dies BEFORE its response begins
  provably delivered nothing, so the request is re-dispatched to a
  different replica: bounded retries (``route_retries``) with the PR-4
  seeded-backoff envelope (base doubling to a cap, ±50% jitter).
  Once a response has BEGUN, the stream is committed — a death
  mid-response returns the typed ``replica_lost`` error instead of a
  retry (the replica may have observably acted; re-running it could
  double-serve). Timeouts are typed errors too, never retries: a slow
  replica is not a dead one.
- **typed errors** — every failure mode the client can see carries an
  ``error_type``: ``no_live_replicas`` / ``queue_full`` (503, nothing
  could take the request), ``replica_lost`` (502), ``replica_timeout``
  (504), ``draining`` (503), ``bad_request`` (400, passthrough),
  ``injected_fault`` (500, chaos drills), ``migration_failed`` (502,
  disaggregated topologies only — every migration avenue AND the
  local-decode fallback failed). A request is NEVER silently dropped —
  the replica-kill chaos acceptance pins that.
- **disaggregation** — with ``RouterConfig.roles`` naming a prefill
  tier (``role=prefill`` members), admission lands on the least-loaded
  prefill replica with ``prefill_only`` (the replica prefills and
  PARKS the slot), then the router hands a decode-tier replica
  (``role=decode``/``both``) a ``pull_from`` reference: the decode
  side pulls the prompt's KV blocks over the int8+scales wire
  (serve/migrate.py), installs them into its own pool, ACKs the
  source (which only then releases its refs — two-phase handoff), and
  decodes. A prefill replica lost mid-migration restarts the whole
  pipeline on another prefill replica (nothing was delivered); a
  decode replica lost before answering retries the migration against
  another decode member; a dead/full decode tier degrades to LOCAL
  decode on the source (``resume`` — the ``role=both`` degradation),
  all under the same bounded seeded-backoff envelope. The
  ``router.migrate`` span brackets each orchestration;
  ``router.prefill_wait_s`` / ``router.decode_wait_s`` split the
  queueing delay per tier (schema-pinned).

Telemetry (schema-pinned by tools/check_telemetry_schema.py, rendered
as the report's "replicas" section): ``router.replicas_live`` gauge,
``router.retries_total`` / ``router.failovers_total`` /
``router.replica_restarts_total`` counters, ``router.route_s``
histogram, and the ``router.drain`` span around the rolling drain.
Plain attribute ledgers (:attr:`Router.retries`,
:attr:`Router.failovers`, ``Supervisor.restarts``) mirror the counters
for callers outside a telemetry run (obs counters are branch-only
no-ops while disabled). Fault points ``router.route`` and
``router.probe`` make both paths chaos-drillable.

Fleet observability (PR 12): the router MINTS a distributed trace id
per admission (sampled by ``obs.set_trace_sample`` / ``nezha-serve
--trace-sample``) and forwards it on every hop — the ``trace_id``
payload field + ``X-Nezha-Trace`` header on ``/generate``, the pull
reference on ``/kv_export``/``/kv_ack`` — so each replica's lifecycle
spans become fragments of one per-request timeline
(``nezha-telemetry RUN_DIR --trace`` stitches them; the
``router.request`` span is the root fragment). ``GET /stats`` answers
the LIVE fleet snapshot: the router's registry, every replica's
``/stats`` payload, and a roll-up that sums each distinct registry
once (``registry_id`` dedupe — thread and process backends report the
same fleet totals). PR 16 adds the windowed pair: ``GET /windows``
(member window views merged sketch-wise) and ``GET /metrics``
(Prometheus text of the fleet roll-up — ``nezha-top``'s poll target).
"""

from __future__ import annotations

import http.client
import json
import random
import socket
import threading
import time
from typing import Dict, Optional, Tuple

from nezha_tpu import faults, obs
from nezha_tpu.faults import InjectedFault
from nezha_tpu.serve.supervisor import LIVE, STARTING, RouterConfig


def register_router_instruments() -> None:
    """Pre-register (get-or-create) the router instrument set so every
    router run's summary carries all of it — a run with zero failovers
    still reports ``failovers_total = 0`` (the stable schema
    tools/check_telemetry_schema.py pins). Called at Supervisor/Router
    construction; call again after a registry reset (a benchmark that
    starts its run AFTER warmup)."""
    for c in ("retries", "failovers", "replica_restarts",
              "migrate_fallbacks"):
        obs.counter(f"router.{c}_total")
    # Fleet-wide KV reuse (PR 17): admissions where the affinity
    # scorer overrode the least-loaded pick (coverage win or cold
    # consistent-hash placement). Knob-invariant 0 when affinity
    # routing is off.
    obs.counter("router.affinity_wins_total")
    obs.gauge("router.replicas_live")
    # Elastic autoscale (PR 19): the replica count the supervisor's
    # control loop steers toward (the configured size when autoscale
    # is off).
    obs.gauge("router.autoscale_target")
    obs.histogram("router.route_s")
    # Disaggregated-tier queueing split: time to the PARKED prefill
    # answer (queue wait + prefill at the source) vs the decode
    # replica's reported TTFT for the migrated request (queue wait +
    # tail prefill + first block slice at the destination). Both empty
    # on homogeneous topologies.
    obs.histogram("router.prefill_wait_s")
    obs.histogram("router.decode_wait_s")


def _typed(status: int, kind: str, msg: str) -> Tuple[int, dict]:
    return status, {"error": msg, "error_type": kind}


# The park receipt's finish_reason (scheduler.FinishReason.PREFILLED —
# spelled locally so the router stays importable without the engine
# stack, matching run_multi's no-jax-compile contract).
FR_PREFILLED = "prefilled"


class Router:
    """Route requests across a :class:`~nezha_tpu.serve.supervisor.
    Supervisor`'s replicas. :meth:`route` is the whole contract: it
    takes the client's request payload and ALWAYS returns an
    ``(http_status, response_object)`` pair — success, a replica's own
    4xx passed through, or a typed error object; it never raises for a
    replica failure. Thread-safe: HTTP handler threads call it
    concurrently."""

    # Cross-thread state -> guarding lock (enforced by nezha-lint's
    # lock-discipline rule): handler threads bump the ledgers
    # concurrently, and the backoff RNG's stream advance is a mutation.
    _LOCK_GUARDED = {"retries": "_ledger_lock",
                     "failovers": "_ledger_lock",
                     "migrations": "_ledger_lock",
                     "migration_bytes": "_ledger_lock",
                     "migration_seconds": "_ledger_lock",
                     "migrate_fallbacks": "_ledger_lock",
                     "affinity_wins": "_ledger_lock",
                     "kv_pulls": "_ledger_lock",
                     "kv_pull_bytes": "_ledger_lock",
                     "_rng": "_rng_lock"}

    def __init__(self, supervisor, cfg: Optional[RouterConfig] = None):
        self.sup = supervisor
        self.cfg = cfg if cfg is not None else supervisor.cfg
        self._rng = random.Random(self.cfg.seed)
        self._rng_lock = threading.Lock()
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        # Plain ledgers: obs counters only count inside a telemetry run.
        self.retries = 0
        self.failovers = 0
        # Migration ledgers (disaggregated topologies): committed
        # migrations, wire bytes moved, the SUM of per-pull transfer
        # windows (bytes / seconds = the bench's mean per-pull wire
        # rate — overlapping pulls each contribute their own window),
        # and local-decode fallbacks (typed degradation, not an
        # error).
        self.migrations = 0
        self.migration_bytes = 0
        self.migration_seconds = 0.0
        self.migrate_fallbacks = 0
        # Fleet-cache ledgers (PR 17): affinity picks that overrode
        # least-loaded, committed peer pulls, and their wire bytes.
        self.affinity_wins = 0
        self.kv_pulls = 0
        self.kv_pull_bytes = 0
        self._ledger_lock = threading.Lock()
        register_router_instruments()

    # -------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Run the prober on a background thread (tests drive
        :meth:`probe_all` directly for determinism instead)."""
        self._probe_thread = threading.Thread(
            target=self._probe_loop, daemon=True, name="nezha-prober")
        self._probe_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.cfg.probe_interval_s):
            self.probe_all()

    # ---------------------------------------------------------- probing
    def probe_all(self) -> None:
        """One probe sweep over every replica that should be serving."""
        for r in self.sup.replicas():
            if r.state not in (STARTING, LIVE):
                continue
            ok, payload = self._probe(r)
            self.sup.mark_probe(r.rid, ok, payload)

    def _get_json(self, r, path: str) -> Optional[dict]:
        """GET one replica endpoint -> the parsed JSON object, or None
        on ANY failure (refused/reset/timeout/non-200/non-object) —
        the one fetch primitive the prober and the stats view share,
        so a transport fix can never land in one and miss the other."""
        conn = None
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", r.port, timeout=self.cfg.probe_timeout_s)
            conn.request("GET", path)
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                return None
            obj = json.loads(body)
            return obj if isinstance(obj, dict) else None
        except Exception:
            return None
        finally:
            if conn is not None:
                conn.close()

    def _probe(self, r) -> Tuple[bool, Optional[dict]]:
        try:
            faults.point("router.probe")
        except Exception:
            # An injected router.probe fault reads as a MISSED probe.
            return False, None
        payload = self._get_json(r, "/healthz")
        return payload is not None, payload

    def _fetch_all(self, path: str) -> Dict[int, Optional[dict]]:
        """Fetch one endpoint from every routable member CONCURRENTLY
        under one shared deadline: a wedged replica (exactly what an
        operator curls the fleet views to diagnose) costs the view one
        probe window, not one window PER wedged member; a fetch that
        misses the deadline reports that member as null."""
        fetched: Dict[int, Optional[dict]] = {}
        threads = []
        for r in self.sup.replicas():
            if r.state in (STARTING, LIVE) and r.port:
                def fetch(rep=r):
                    fetched[rep.rid] = self._get_json(rep, path)
                t = threading.Thread(target=fetch, daemon=True)
                threads.append(t)
                t.start()
        deadline = time.monotonic() + self.cfg.probe_timeout_s
        for t in threads:
            t.join(max(deadline - time.monotonic(), 0.0))
        return fetched

    # ------------------------------------------------------- live stats
    def fleet_stats(self) -> dict:
        """The live fleet snapshot ``GET /stats`` answers (stats schema
        v1, pinned by analysis/telemetry_schema.check_stats_payload):
        the router's own registry snapshot, every routable replica's
        ``/stats`` payload fetched live (None for a member that did not
        answer), and a ``fleet`` roll-up summing counters and gauges —
        one curl shows live occupancy, migration rate, and the queue
        split without touching a run dir. The roll-up sums each
        DISTINCT registry once, keyed by the ``registry_id`` every
        payload carries: with the thread replica backend all members
        (and the router itself) share this process's registry, so
        summing per member would over-count by the member count — the
        dedupe makes thread and process backends report the same fleet
        totals. Per-replica rows always show every member's payload."""
        reps = self.sup.replicas()
        fetched = self._fetch_all("/stats")
        out = obs.stats_snapshot()
        replicas = []
        fleet_counters: Dict[str, float] = {}
        fleet_gauges: Dict[str, float] = {}
        seen_regs = set()

        def roll_up(stats: dict) -> None:
            reg = stats.get("registry_id")
            if isinstance(reg, str) and reg:
                if reg in seen_regs:
                    return
                seen_regs.add(reg)
            for k, v in (stats.get("counters") or {}).items():
                fleet_counters[k] = fleet_counters.get(k, 0) + v
            for k, v in (stats.get("gauges") or {}).items():
                fleet_gauges[k] = fleet_gauges.get(k, 0) + v

        # The router's own registry joins the roll-up first: in thread
        # mode it IS every member's registry (one contribution total);
        # in process mode it contributes the router.* instruments.
        roll_up(out)
        for r in reps:
            stats = fetched.get(r.rid)
            if isinstance(stats, dict):
                roll_up(stats)
            replicas.append({"rid": r.rid, "role": r.role,
                             "port": r.port, "state": r.state,
                             "healthy": r.healthy, "stats": stats})
        return {"stats_schema_version": 1, "kind": "fleet",
                "ts": out["ts"], "enabled": out["enabled"],
                "router": out, "replicas": replicas,
                "fleet": {"counters": fleet_counters,
                          "gauges": fleet_gauges}}

    def fleet_windows(self) -> dict:
        """The fleet's rolled-up window views (``GET /windows``): every
        member's ``windows_payload()`` fetched live plus the router's
        own, merged by obs.merge_window_payloads — sketches merge
        bucket-wise (exact quantiles, never summed snapshot
        percentiles), and members sharing a registry (thread backend)
        contribute once."""
        fetched = self._fetch_all("/windows")
        payloads = [obs.windows_payload()]
        payloads.extend(p for p in fetched.values()
                        if isinstance(p, dict))
        return obs.merge_window_payloads(payloads)

    def fleet_metrics_text(self) -> str:
        """The fleet ``GET /metrics`` body: the deduped cumulative
        roll-up plus the merged window views, in Prometheus text
        format."""
        stats = self.fleet_stats()
        return obs.render_prometheus(stats.get("fleet"),
                                     self.fleet_windows())

    def wait_live(self, n: int, timeout_s: float = 300.0) -> bool:
        """Probe until ``n`` replicas are live (startup convenience for
        benchmarks/tests). Returns False on timeout."""
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            self.probe_all()
            if self.sup.live_count() >= n:
                return True
            time.sleep(0.05)
        return False

    # ---------------------------------------------------------- routing
    def route(self, payload: dict) -> Tuple[int, dict]:
        """Dispatch one request: pick the least-loaded live replica,
        forward, fail over on uncommitted replica loss. On a
        disaggregated topology (``cfg.roles`` names a prefill tier)
        the dispatch is the two-phase prefill -> migrate -> decode
        pipeline instead. Always returns ``(status, object)`` — see
        the module docstring for the error taxonomy.

        The router is the fleet's TRACE-MINTING edge: each admission
        mints a trace id (None while telemetry is disabled or the
        ``obs.set_trace_sample`` knob rolls it out) and forwards it on
        every hop — the ``trace_id`` payload field plus the
        ``X-Nezha-Trace`` header on ``/generate``, and the pull
        reference on ``/kv_export`` / ``/kv_ack`` — so every replica's
        lifecycle spans land in one stitched per-request timeline. The
        ``router.request`` span is the timeline's root fragment."""
        t0 = time.monotonic()
        tid = None
        if isinstance(payload, dict):
            client_tid = payload.get("trace_id")
            if not isinstance(client_tid, str) or not client_tid:
                # A malformed (non-string) client trace_id must neither
                # poison the pinned span schema nor crash _forward's
                # header write — it is scrubbed and replaced by the
                # router's own minting verdict.
                client_tid = None
            tid = client_tid or obs.mint_trace_id()
            # ALWAYS rewrite the field: the minted id, or "" marking
            # "routed and sampled out" — the replica scheduler treats
            # "" as explicitly untraced and never re-mints, so the
            # router stays the fleet's single sampling edge
            # (--trace-sample P yields P, not P + (1-P)P).
            payload = {**payload, "trace_id": tid or ""}
        try:
            faults.point("router.route")
            with obs.trace_context(tid):
                with obs.traced_span("router.request") as sp:
                    if isinstance(payload, dict) and payload.get("id"):
                        sp.set(request_id=payload["id"])
                    if self.cfg.disaggregated:
                        status, obj = self._route_disagg(payload)
                    else:
                        status, obj = self._route_inner(
                            json.dumps(payload).encode(), trace_id=tid,
                            payload=payload)
                    sp.set(status=status)
                    return status, obj
        except InjectedFault as e:
            return _typed(500, "injected_fault", str(e))
        finally:
            obs.histogram("router.route_s").observe(
                time.monotonic() - t0)

    def _route_inner(self, body: bytes,
                     trace_id: Optional[str] = None,
                     payload: Optional[dict] = None) -> Tuple[int, dict]:
        # Affinity routing (PR 17) needs the prompt's TOKEN ids to hash
        # against the fleet digests — text prompts (no ids until the
        # replica tokenizes) and disaggregated dispatches (payload
        # None) route least-loaded, exactly as before.
        tokens = None
        if self.cfg.affinity_routing and isinstance(payload, dict):
            pt = payload.get("prompt_tokens")
            if isinstance(pt, list) and pt \
                    and all(isinstance(t, int) for t in pt):
                tokens = pt
        excluded: set = set()
        retries = 0
        failed_over = False
        while True:
            usable = [r for r in self.sup.live_replicas()
                      if r.rid not in excluded]
            if not usable:
                if failed_over:
                    return _typed(502, "replica_lost",
                                  f"no live replica left after "
                                  f"{retries} dispatch(es) failed")
                return _typed(503, "no_live_replicas",
                              "no live replicas")
            outcome, detail, r = self._dispatch_tier(
                usable, body, trace_id=trace_id, payload=payload,
                tokens=tokens)
            if outcome == "all_full":
                return _typed(503, "queue_full",
                              f"all {detail} live replica(s) at "
                              f"capacity")
            if outcome == "ok":
                if failed_over:
                    self._count_failover()
                return 200, detail
            if outcome == "pass":           # the replica's own 4xx
                return detail
            if outcome == "timeout":
                return _typed(504, "replica_timeout", detail)
            if outcome == "committed":
                # The response had begun: the stream is committed and
                # a retry could double-serve — typed error.
                return _typed(502, "replica_lost",
                              f"replica {r.rid} lost after its "
                              f"response began: {detail}")
            # outcome == "lost": died before any response byte —
            # provably delivered nothing, safe to fail over.
            failed_over = True
            excluded.add(r.rid)
            self.sup.note_forward_failure(r.rid)
            if retries >= self.cfg.route_retries:
                return _typed(502, "replica_lost",
                              f"replica {r.rid} died before the "
                              f"first token; {retries} retr"
                              f"{'y' if retries == 1 else 'ies'} "
                              f"exhausted: {detail}")
            retries += 1
            self._count_retry(retries)
            # loop: rebuild the live set — it may have changed

    # ------------------------------------- shared dispatch + ledgers
    def _count_retry(self, attempt: int) -> None:
        with self._ledger_lock:
            self.retries += 1
        obs.counter("router.retries_total").inc()
        time.sleep(self._retry_backoff(attempt))

    def _count_failover(self) -> None:
        with self._ledger_lock:
            self.failovers += 1
        obs.counter("router.failovers_total").inc()

    def _dispatch_tier(self, cand, body: bytes,
                       trace_id: Optional[str] = None,
                       payload: Optional[dict] = None,
                       tokens: Optional[list] = None):
        """Least-loaded (or, with tokens + affinity routing, digest-
        affinity) sweep over one tier: forward to the best member,
        skipping 503-full members for this request. -> ``(outcome,
        detail, replica)`` with :meth:`_forward`'s outcomes plus
        ``("all_full", tier size, None)`` when every member refused.

        On a near-miss (a NON-chosen member's digest covers more of
        the prompt than the chosen one's) the forward carries a
        ``pull_from`` hint naming that sibling — queue-full members
        still serve as pull SOURCES (``/kv_export`` is read-only, no
        admission involved), which is exactly how a saturated owner's
        cache keeps paying off through its siblings."""
        full: set = set()
        while True:
            usable = [r for r in cand if r.rid not in full]
            if not usable:
                return "all_full", len(cand), None
            r, pull = self._pick(usable, cand, tokens)
            if pull is not None and isinstance(payload, dict):
                outcome, detail = self._forward_pull(
                    r, payload, pull, trace_id=trace_id)
            else:
                outcome, detail = self._forward(r, body,
                                                trace_id=trace_id)
            if outcome == "full":
                full.add(r.rid)
                continue
            return outcome, detail, r

    def _pick(self, usable, cand, tokens):
        """Choose the dispatch target among ``usable`` (and, on a
        near-miss, a pull source from the full tier ``cand``). ->
        ``(replica, pull_hint_or_None)``.

        Baseline is the least-loaded pick (fewest in-flight forwards,
        ties by probed queue depth, then rid). With affinity routing
        on and integer prompt tokens at hand, every usable member is
        scored ``coverage_tokens / (1 + load)`` from its freshest
        digest; the best scorer wins only when it strictly beats the
        baseline's own score — affinity never routes to a busier
        replica than the hit is worth. When NOBODY covers anything,
        the tie among minimally loaded members is broken by a
        consistent hash of the prompt's first block instead of by rid,
        so repeat users grow an owner replica. The ``router.affinity``
        fault point degrades the whole scorer to the baseline pick —
        typed, request-scoped, never an error the client sees."""
        base = min(usable, key=lambda x: (
            x.in_flight, x.last_health.get("queued", 0), x.rid))
        if not tokens or not self.cfg.affinity_routing \
                or len(self.sup.replicas()) < 2:
            return base, None
        try:
            faults.point("router.affinity")
        except InjectedFault:
            return base, None
        from nezha_tpu.serve import fleetcache
        now = time.monotonic()
        hashes_by_bs: Dict[int, list] = {}
        cover: Dict[int, tuple] = {}    # rid -> (blocks, block_size)

        def load_of(x) -> int:
            try:
                return x.in_flight + int(
                    x.last_health.get("queued", 0) or 0)
            except (TypeError, ValueError):
                return x.in_flight

        for x in cand:
            if x.probed_t <= 0:
                continue    # never probed: no digest to trust
            parsed = fleetcache.digest_entries_of(x.last_health)
            if parsed is None:
                continue
            bs, entries = parsed
            try:
                age = float(x.last_health.get("digest_age_s", 0.0))
            except (TypeError, ValueError):
                age = 0.0
            if age + (now - x.probed_t) > self.cfg.digest_stale_s:
                continue    # advisory data gone stale — ignore
            hashes = hashes_by_bs.get(bs)
            if hashes is None:
                hashes = fleetcache.prefix_hashes(tokens, bs)
                hashes_by_bs[bs] = hashes
            blocks, _tier = fleetcache.coverage(entries, hashes)
            if blocks:
                cover[x.rid] = (blocks, bs)

        def score_of(x) -> float:
            c = cover.get(x.rid)
            if c is None:
                return 0.0
            return fleetcache.score(c[0], c[1], x.in_flight,
                                    load_of(x) - x.in_flight)

        pick = base
        if cover:
            best = max(usable, key=lambda x: (score_of(x), -load_of(x),
                                              -x.rid))
            if score_of(best) > score_of(base):
                pick = best
        else:
            # Cold placement: nobody covers anything, so spread the
            # prefix deterministically across the members tied at the
            # baseline's load — least-loaded is preserved, only its
            # rid tie-break changes.
            bs = next(iter(hashes_by_bs), 16)
            key = (base.in_flight, base.last_health.get("queued", 0))
            tied = [x.rid for x in usable
                    if (x.in_flight,
                        x.last_health.get("queued", 0)) == key]
            rid = fleetcache.place_cold(tokens, bs, tied)
            if rid is not None and rid != base.rid:
                pick = next(x for x in usable if x.rid == rid)
        if pick.rid != base.rid:
            with self._ledger_lock:
                self.affinity_wins += 1
            obs.counter("router.affinity_wins_total").inc()
        # Near-miss peer pull: a sibling (any tier member, even one
        # whose queue is full — export needs no admission) covering
        # MORE than the pick gets handed to the pick as pull_from.
        pick_cov = cover.get(pick.rid, (0, 0))[0]
        src_rid, src_cov, src_bs = None, pick_cov, 0
        for x in cand:
            if x.rid == pick.rid:
                continue
            c = cover.get(x.rid)
            if c is not None and c[0] > src_cov:
                src_rid, src_cov, src_bs = x.rid, c[0], c[1]
        if src_rid is None:
            return pick, None
        src = next(x for x in cand if x.rid == src_rid)
        pull = {"host": "127.0.0.1", "port": src.port,
                "tokens": [int(t) for t in tokens[:src_cov * src_bs]],
                "blocks": src_cov, "src_rid": src.rid}
        return pick, pull

    def _forward_pull(self, r, payload: dict, pull: dict,
                      trace_id: Optional[str] = None):
        """Forward with a peer-pull hint attached, the hop bracketed
        by the pinned ``router.kv_pull_s`` span; a committed pull (the
        replica reports installed blocks) lands in the
        :attr:`kv_pulls` / :attr:`kv_pull_bytes` ledgers and the
        schema-pinned counters the replica side already bumped."""
        hint = dict(pull)
        src_rid = hint.pop("src_rid", None)
        if trace_id:
            hint["trace_id"] = trace_id
        body = json.dumps({**payload, "pull_from": hint}).encode()
        with obs.span("router.kv_pull_s", src=src_rid, dst=r.rid,
                      blocks=pull.get("blocks", 0)) as sp:
            outcome, detail = self._forward(r, body, trace_id=trace_id)
            meta = (detail.get("fleet_pull")
                    if outcome == "ok" and isinstance(detail, dict)
                    else None)
            if isinstance(meta, dict):
                sp.set(bytes=int(meta.get("bytes", 0) or 0),
                       installed=int(meta.get("installed", 0) or 0),
                       degraded=bool(meta.get("degraded")))
                if meta.get("installed"):
                    with self._ledger_lock:
                        self.kv_pulls += 1
                        self.kv_pull_bytes += int(meta.get("bytes", 0)
                                                  or 0)
        return outcome, detail

    def _src_live(self, src) -> bool:
        return any(r.rid == src.rid for r in self.sup.live_replicas())

    # ---------------------------------------------- disaggregated tiers

    def _route_disagg(self, payload: dict) -> Tuple[int, dict]:
        """The disaggregated pipeline: admit onto the prefill tier
        (``prefill_only`` parks the prompt's KV at the source), migrate
        the parked blocks to a decode-tier replica, return its answer.
        Crash-safe by phase: before the prefill answer nothing exists
        (plain failover); between park and a committed decode answer
        the request has delivered NOTHING to the client, so a lost
        source restarts the whole pipeline elsewhere and a lost decode
        replica retries the migration — bounded by ``route_retries``
        with the seeded-backoff envelope; a dead/full decode tier
        degrades to local decode on the source. The whole orchestration
        is one ``router.migrate`` span."""
        with obs.span("router.migrate") as sp:
            faults.point("router.migrate")
            rid = payload.get("id") if isinstance(payload, dict) else None
            if not rid:
                import uuid
                rid = f"mig-{uuid.uuid4().hex[:12]}"
            payload = {**payload, "id": rid}
            status, obj = self._disagg_pipeline(payload, rid, sp)
            sp.set(status=status)
            return status, obj

    def _disagg_pipeline(self, payload: dict, rid: str,
                         sp) -> Tuple[int, dict]:
        tid = payload.get("trace_id")
        pf_body = json.dumps({**payload, "prefill_only": True}).encode()
        attempts = 0          # whole-pipeline restarts (source lost)
        excluded: set = set()
        failed_over = False
        while True:
            # The prefill tier is the role=prefill members ONLY:
            # role=both replicas belong to the decode tier (and the
            # local-decode degradation) — admitting onto them would
            # put prefill bursts back on decode hardware, the exact
            # interleaving disaggregation exists to prevent.
            prefill_live = [r for r in self.sup.live_replicas()
                            if r.role == "prefill"
                            and r.rid not in excluded]
            if not prefill_live:
                # No prefill tier left: degrade to classic routing over
                # whatever is live (typed telemetry — the decode/both
                # tier serves the request end to end).
                with self._ledger_lock:
                    self.migrate_fallbacks += 1
                obs.counter("router.migrate_fallbacks_total").inc()
                sp.set(degraded="no_prefill_tier")
                return self._route_inner(json.dumps(payload).encode(),
                                         trace_id=tid)
            t_pf = time.monotonic()
            outcome, detail, src = self._dispatch_tier(prefill_live,
                                                       pf_body,
                                                       trace_id=tid)
            if outcome == "all_full":
                return _typed(503, "queue_full",
                              f"all {detail} live prefill replica(s) "
                              f"at capacity")
            if outcome == "pass":
                return detail
            if outcome == "timeout":
                return _typed(504, "replica_timeout", detail)
            if outcome == "committed":
                return _typed(502, "replica_lost",
                              f"prefill replica {src.rid} lost after "
                              f"its response began: {detail}")
            if outcome == "lost":
                self.sup.note_forward_failure(src.rid)
                excluded.add(src.rid)
                failed_over = True
                if attempts >= self.cfg.route_retries:
                    return _typed(502, "replica_lost",
                                  f"prefill dispatch failed and "
                                  f"{attempts} restart(s) exhausted: "
                                  f"{detail}")
                attempts += 1
                self._count_retry(attempts)
                continue
            # outcome == "ok": the prompt is parked at `src`.
            if detail.get("finish_reason") != FR_PREFILLED:
                # A pre-roles worker served it whole — still a valid
                # answer (rolling upgrades must not 500).
                return 200, detail
            pf_wait = time.monotonic() - t_pf
            obs.histogram("router.prefill_wait_s").observe(pf_wait)
            status, obj = self._decode_phase(payload, rid, src, sp,
                                             pf_wait)
            if status is None:
                # Source lost mid-migration with nothing delivered:
                # restart the pipeline on another prefill replica.
                excluded.add(src.rid)
                failed_over = True
                if attempts >= self.cfg.route_retries:
                    return _typed(502, "migration_failed",
                                  f"migration source replica "
                                  f"{src.rid} lost and {attempts} "
                                  f"restart(s) exhausted: {obj}")
                attempts += 1
                self._count_retry(attempts)
                continue
            if status == 200 and failed_over:
                self._count_failover()
            return status, obj

    def _decode_phase(self, payload: dict, rid: str, src, sp,
                      pf_wait: float):
        """Phase two: hand the parked span to a decode-tier replica.
        -> ``(status, obj)``, or ``(None, why)`` to signal the caller
        to restart from prefill (the source is gone and the client has
        been handed nothing — a rerun cannot double-serve)."""
        tid = payload.get("trace_id")
        # The pull reference carries the trace too: the decode replica
        # forwards it on its /kv_export + /kv_ack POSTs to the source,
        # and its own install span adopts it.
        pull = {"port": src.port, "request_id": rid}
        if tid:
            pull["trace_id"] = tid
        body = json.dumps({**payload, "pull_from": pull}).encode()
        mig_retries = 0
        excluded: set = set()
        while True:
            decode_live = [r for r in self.sup.live_replicas()
                           if r.role != "prefill"
                           and r.rid not in excluded
                           and r.rid != src.rid]
            if not decode_live:
                return self._local_decode(rid, src, sp, pf_wait,
                                          "no live decode replica")
            t_dec = time.monotonic()
            outcome, detail, dst = self._dispatch_tier(decode_live, body,
                                                       trace_id=tid)
            if outcome == "all_full":
                return self._local_decode(
                    rid, src, sp, pf_wait,
                    f"all {detail} decode replica(s) at capacity")
            if outcome == "timeout":
                return _typed(504, "replica_timeout", detail)
            if outcome == "committed":
                return _typed(502, "replica_lost",
                              f"decode replica {dst.rid} lost after "
                              f"its response began: {detail}")
            if outcome == "lost":
                # Died before answering: the parked span survives at
                # the source (or was ACKed away, which the next pull
                # surfaces as a typed 424) — retry the migration on
                # another decode member.
                self.sup.note_forward_failure(dst.rid)
                excluded.add(dst.rid)
                if mig_retries >= self.cfg.route_retries:
                    return self._local_decode(
                        rid, src, sp, pf_wait,
                        f"{mig_retries} migration retr"
                        f"{'y' if mig_retries == 1 else 'ies'} "
                        f"exhausted: {detail}")
                mig_retries += 1
                self._count_retry(mig_retries)
                continue
            if outcome == "pass":
                status, obj = detail
                if status != 424:
                    return status, obj
                # Migration dependency failed. A dead source — or a
                # live one whose PARK is gone (typed park_lost: TTL,
                # drain, or an ACK to a puller that then died) — means
                # every further pull/resume is doomed: restart from
                # prefill now instead of sweeping the tier. Otherwise
                # retry the pull through another decode member, then
                # fall back.
                if (obj.get("error_type") == "park_lost"
                        or not self._src_live(src)):
                    return None, obj.get("error", "source lost")
                excluded.add(dst.rid)
                if mig_retries >= self.cfg.route_retries:
                    return self._local_decode(
                        rid, src, sp, pf_wait,
                        f"migration failed after {mig_retries} "
                        f"retr{'y' if mig_retries == 1 else 'ies'}: "
                        f"{obj.get('error')}")
                mig_retries += 1
                self._count_retry(mig_retries)
                continue
            # outcome == "ok"
            obj = detail
            dec_wait = (float(obj["ttft_s"])
                        if obj.get("ttft_s") is not None
                        else time.monotonic() - t_dec)
            obs.histogram("router.decode_wait_s").observe(dec_wait)
            mig = obj.get("migration")
            if isinstance(mig, dict):
                with self._ledger_lock:
                    self.migrations += 1
                    self.migration_bytes += int(mig.get("bytes", 0))
                    self.migration_seconds += float(
                        mig.get("seconds", 0.0))
                # The per-request queueing split rides in the response
                # (benchmarks read it client-side; the histograms above
                # carry the same numbers for run-dir artifacts).
                mig["prefill_wait_s"] = pf_wait
                mig["decode_wait_s"] = dec_wait
                sp.set(bytes=int(mig.get("bytes", 0)),
                       blocks=int(mig.get("blocks", 0)),
                       src=src.rid, dst=dst.rid)
            return 200, obj

    def _local_decode(self, rid: str, src, sp, pf_wait: float,
                      why: str):
        """The ``role=both`` degradation: no decode replica could take
        the migration, so the SOURCE resumes the parked request and
        decodes it locally. -> ``(status, obj)``, or ``(None, why)``
        when the source is gone / the park vanished — the caller
        restarts from prefill (nothing was delivered)."""
        with self._ledger_lock:
            self.migrate_fallbacks += 1
        obs.counter("router.migrate_fallbacks_total").inc()
        sp.set(degraded=why)
        tid, _ = obs.current_trace()
        outcome, detail = self._forward(
            src, json.dumps({"resume": rid}).encode(), trace_id=tid)
        if outcome == "ok":
            obj = detail
            dec_wait = (float(obj["ttft_s"])
                        if obj.get("ttft_s") is not None else 0.0)
            obs.histogram("router.decode_wait_s").observe(dec_wait)
            obj["migration"] = {"bytes": 0, "blocks": 0, "seconds": 0.0,
                                "fallback": why,
                                "prefill_wait_s": pf_wait,
                                "decode_wait_s": dec_wait}
            return 200, obj
        if outcome == "timeout":
            return _typed(504, "replica_timeout", detail)
        if outcome == "committed":
            return _typed(502, "replica_lost",
                          f"replica {src.rid} lost after its resumed "
                          f"response began: {detail}")
        if outcome == "pass":
            status, obj = detail
            if status in (404, 424):
                # The park vanished (TTL, drain) before the resume:
                # nothing was delivered — restart from prefill.
                return None, obj.get("error", "park lost")
            return status, obj
        if outcome == "full":
            return _typed(503, "queue_full",
                          f"source replica {src.rid} refused the "
                          f"local-decode fallback: "
                          f"{detail.get('error') if isinstance(detail, dict) else detail}")
        # outcome == "lost": the source died — restart from prefill.
        self.sup.note_forward_failure(src.rid)
        return None, f"local-decode fallback failed: {detail}"

    def _retry_backoff(self, attempt: int) -> float:
        base = min(self.cfg.retry_backoff_base_s * (2 ** (attempt - 1)),
                   self.cfg.retry_backoff_max_s)
        with self._rng_lock:
            return base * (0.5 + self._rng.random())   # ±50% jitter

    def _forward(self, r, body: bytes,
                 trace_id: Optional[str] = None) -> Tuple[str, object]:
        """One dispatch to one replica -> (outcome, detail):

        - ``("ok", result)`` — 200, the finished generation
        - ``("pass", (status, obj))`` — the replica's own 4xx, passed
          through untouched (a bad request is bad on every replica)
        - ``("full", obj)`` — 503 from the replica (queue full /
          draining): unavailable for THIS request, not dead
        - ``("lost", msg)`` — failed before any response byte (connect
          refused/reset, or the replica answered 5xx declaring the
          request failed without serving it) — retryable
        - ``("committed", msg)`` — failed AFTER the response began —
          not retryable
        - ``("timeout", msg)`` — no answer within
          ``forward_timeout_s`` — not retryable (slow != dead)
        """
        self.sup.add_in_flight(r.rid, +1)
        conn = http.client.HTTPConnection(
            "127.0.0.1", r.port, timeout=self.cfg.forward_timeout_s)
        committed = False
        headers = {"Content-Type": "application/json"}
        if trace_id:
            # The header twin of the payload's trace_id field: replica
            # front ends honor either, so a proxy that re-encodes the
            # body cannot strand the trace.
            headers[obs.TRACE_HEADER] = trace_id
        try:
            conn.request("POST", "/generate", body=body, headers=headers)
            resp = conn.getresponse()
            committed = True
            raw = resp.read()
            try:
                obj = json.loads(raw)
            except ValueError:
                obj = {"error": "replica returned non-JSON"}
            if resp.status == 200:
                return "ok", obj
            if resp.status == 503:
                return "full", obj
            if resp.status >= 500:
                return "lost", (f"replica {r.rid} answered "
                                f"{resp.status}: {obj.get('error')}")
            return "pass", (resp.status, obj)
        except socket.timeout:
            return "timeout", (f"replica {r.rid} gave no answer within "
                               f"{self.cfg.forward_timeout_s}s")
        except Exception as e:
            kind = "committed" if committed else "lost"
            return kind, f"{type(e).__name__}: {e}"
        finally:
            conn.close()
            self.sup.add_in_flight(r.rid, -1)


# ---------------------------------------------------------- HTTP front end
def run_front_end(router: Router, supervisor, port: int, *,
                  ready_cb=None, drain: Optional[threading.Event] = None,
                  drain_timeout_s: float = 30.0) -> int:
    """Serve the router over stdlib HTTP: POST ``/generate`` routes
    across replicas, GET ``/healthz`` reports the replica set. Setting
    ``drain`` (the signal handlers do) closes admission (POST -> 503
    "draining", ``/healthz`` -> 503) and runs the ROLLING drain —
    replicas stop one at a time, each finishing its in-flight work, so
    capacity never hits zero before the last one — then shuts the
    server down. Mirrors ``cli/serve.run_http``'s lifecycle contract
    (non-daemon handlers flush final responses; a second signal is
    ignored)."""
    import sys
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    drain = drain if drain is not None else threading.Event()
    stop = threading.Event()

    class Handler(BaseHTTPRequestHandler):
        timeout = 60

        def log_message(self, *a):
            pass

        def _send(self, code: int, obj: dict):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/stats":
                # Live fleet view: answered even while draining — the
                # operator watching a drain is exactly who curls this.
                return self._send(200, router.fleet_stats())
            if self.path == "/windows":
                # The mergeable JSON form of the fleet roll-up (what a
                # higher-tier aggregator would scrape).
                return self._send(200, router.fleet_windows())
            if self.path == "/metrics":
                # Prometheus text: fleet-merged sketches + deduped
                # cumulative totals (nezha-top's poll target).
                body = router.fleet_metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if self.path != "/healthz":
                return self._send(404, {"error": "unknown path"})
            live = supervisor.live_count()
            if drain.is_set():
                status = "draining"
            elif live == 0:
                status = "no live replicas"
            else:
                status = "ok"
            self._send(200 if status == "ok" else 503, {
                "status": status, "replicas_live": live,
                "replicas": supervisor.describe()})

        def do_POST(self):
            if self.path != "/generate":
                return self._send(404, {"error": "unknown path"})
            if drain.is_set():
                return self._send(*_typed(503, "draining", "draining"))
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n))
            except (ValueError, json.JSONDecodeError) as e:
                return self._send(*_typed(400, "bad_request", str(e)))
            if not isinstance(payload, dict):
                return self._send(*_typed(400, "bad_request",
                                          "request must be a JSON "
                                          "object"))
            # The fleet entry point honors the same header/field pair
            # the replica front ends do — an operator tagging a repro
            # request at the router traces under THEIR id, not a
            # freshly minted one.
            obs.adopt_trace_header(self.headers, payload)
            code, obj = router.route(payload)
            self._send(code, obj)

    class Server(ThreadingHTTPServer):
        daemon_threads = False    # flush final responses at shutdown

    server = Server(("127.0.0.1", port), Handler)

    def drain_watch():
        drain.wait()
        if not stop.is_set():
            with obs.span("router.drain",
                          replicas=len(supervisor.replicas())) as sp:
                supervisor.rolling_drain(drain_timeout_s)
                sp.set(replicas_live=supervisor.live_count())
            stop.set()
        server.shutdown()

    threading.Thread(target=drain_watch, daemon=True).start()
    if ready_cb is not None:
        ready_cb(server)
    print(f"nezha-serve router listening on http://127.0.0.1:"
          f"{server.server_address[1]} "
          f"({supervisor.cfg.replicas} replicas; POST /generate, "
          f"GET /healthz)", file=sys.stderr)
    try:
        server.serve_forever(poll_interval=0.1)
    except KeyboardInterrupt:
        pass
    finally:
        stop.set()
        drain.set()     # unblock the watcher on non-signal exits
        server.server_close()
    return 0
