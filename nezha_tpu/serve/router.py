"""The multi-replica HTTP front end: health-checked routing + failover.

One process in front of N single-replica engines (spawned and restarted
by ``serve/supervisor.py``), answering the SAME protocol one replica
does (POST ``/generate``, GET ``/healthz``) so clients cannot tell one
engine from a crowd — except that losing any replica no longer loses
the service:

- **probing** — a background prober GETs each replica's ``/healthz``
  every ``probe_interval_s``; ``probe_misses`` consecutive failures
  EJECT the replica from routing, one success readmits it. A forward
  that dies on the wire ejects immediately (stronger evidence than a
  missed probe).
- **balancing** — least-loaded: the replica with the fewest
  router-tracked in-flight forwards (ties broken by its last-probed
  queue depth). A replica answering 503 (queue full / draining) is
  skipped for that request; the client sees 503 only when EVERY live
  replica refused.
- **failover** — a replica that dies BEFORE its response begins
  provably delivered nothing, so the request is re-dispatched to a
  different replica: bounded retries (``route_retries``) with the PR-4
  seeded-backoff envelope (base doubling to a cap, ±50% jitter).
  Once a response has BEGUN, the stream is committed — a death
  mid-response returns the typed ``replica_lost`` error instead of a
  retry (the replica may have observably acted; re-running it could
  double-serve). Timeouts are typed errors too, never retries: a slow
  replica is not a dead one.
- **typed errors** — every failure mode the client can see carries an
  ``error_type``: ``no_live_replicas`` / ``queue_full`` (503, nothing
  could take the request), ``replica_lost`` (502), ``replica_timeout``
  (504), ``draining`` (503), ``bad_request`` (400, passthrough),
  ``injected_fault`` (500, chaos drills). A request is NEVER silently
  dropped — the replica-kill chaos acceptance pins that.

Telemetry (schema-pinned by tools/check_telemetry_schema.py, rendered
as the report's "replicas" section): ``router.replicas_live`` gauge,
``router.retries_total`` / ``router.failovers_total`` /
``router.replica_restarts_total`` counters, ``router.route_s``
histogram, and the ``router.drain`` span around the rolling drain.
Plain attribute ledgers (:attr:`Router.retries`,
:attr:`Router.failovers`, ``Supervisor.restarts``) mirror the counters
for callers outside a telemetry run (obs counters are branch-only
no-ops while disabled). Fault points ``router.route`` and
``router.probe`` make both paths chaos-drillable.
"""

from __future__ import annotations

import http.client
import json
import random
import socket
import threading
import time
from typing import Dict, Optional, Tuple

from nezha_tpu import faults, obs
from nezha_tpu.faults import InjectedFault
from nezha_tpu.serve.supervisor import LIVE, STARTING, RouterConfig


def register_router_instruments() -> None:
    """Pre-register (get-or-create) the router instrument set so every
    router run's summary carries all of it — a run with zero failovers
    still reports ``failovers_total = 0`` (the stable schema
    tools/check_telemetry_schema.py pins). Called at Supervisor/Router
    construction; call again after a registry reset (a benchmark that
    starts its run AFTER warmup)."""
    for c in ("retries", "failovers", "replica_restarts"):
        obs.counter(f"router.{c}_total")
    obs.gauge("router.replicas_live")
    obs.histogram("router.route_s")


def _typed(status: int, kind: str, msg: str) -> Tuple[int, dict]:
    return status, {"error": msg, "error_type": kind}


class Router:
    """Route requests across a :class:`~nezha_tpu.serve.supervisor.
    Supervisor`'s replicas. :meth:`route` is the whole contract: it
    takes the client's request payload and ALWAYS returns an
    ``(http_status, response_object)`` pair — success, a replica's own
    4xx passed through, or a typed error object; it never raises for a
    replica failure. Thread-safe: HTTP handler threads call it
    concurrently."""

    # Cross-thread state -> guarding lock (enforced by nezha-lint's
    # lock-discipline rule): handler threads bump the ledgers
    # concurrently, and the backoff RNG's stream advance is a mutation.
    _LOCK_GUARDED = {"retries": "_ledger_lock",
                     "failovers": "_ledger_lock",
                     "_rng": "_rng_lock"}

    def __init__(self, supervisor, cfg: Optional[RouterConfig] = None):
        self.sup = supervisor
        self.cfg = cfg if cfg is not None else supervisor.cfg
        self._rng = random.Random(self.cfg.seed)
        self._rng_lock = threading.Lock()
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        # Plain ledgers: obs counters only count inside a telemetry run.
        self.retries = 0
        self.failovers = 0
        self._ledger_lock = threading.Lock()
        register_router_instruments()

    # -------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Run the prober on a background thread (tests drive
        :meth:`probe_all` directly for determinism instead)."""
        self._probe_thread = threading.Thread(
            target=self._probe_loop, daemon=True, name="nezha-prober")
        self._probe_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.cfg.probe_interval_s):
            self.probe_all()

    # ---------------------------------------------------------- probing
    def probe_all(self) -> None:
        """One probe sweep over every replica that should be serving."""
        for r in self.sup.replicas():
            if r.state not in (STARTING, LIVE):
                continue
            ok, payload = self._probe(r)
            self.sup.mark_probe(r.rid, ok, payload)

    def _probe(self, r) -> Tuple[bool, Optional[dict]]:
        conn = None
        try:
            faults.point("router.probe")
            conn = http.client.HTTPConnection(
                "127.0.0.1", r.port, timeout=self.cfg.probe_timeout_s)
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                return False, None
            return True, json.loads(body)
        except Exception:
            # Connection refused, reset, timeout, bad JSON, or an
            # injected router.probe fault: all the same verdict — this
            # probe was MISSED.
            return False, None
        finally:
            if conn is not None:
                conn.close()

    def wait_live(self, n: int, timeout_s: float = 300.0) -> bool:
        """Probe until ``n`` replicas are live (startup convenience for
        benchmarks/tests). Returns False on timeout."""
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            self.probe_all()
            if self.sup.live_count() >= n:
                return True
            time.sleep(0.05)
        return False

    # ---------------------------------------------------------- routing
    def route(self, payload: dict) -> Tuple[int, dict]:
        """Dispatch one request: pick the least-loaded live replica,
        forward, fail over on uncommitted replica loss. Always returns
        ``(status, object)`` — see the module docstring for the error
        taxonomy."""
        t0 = time.monotonic()
        try:
            faults.point("router.route")
            return self._route_inner(json.dumps(payload).encode())
        except InjectedFault as e:
            return _typed(500, "injected_fault", str(e))
        finally:
            obs.histogram("router.route_s").observe(
                time.monotonic() - t0)

    def _route_inner(self, body: bytes) -> Tuple[int, dict]:
        excluded: set = set()
        retries = 0
        failed_over = False
        while True:
            usable = [r for r in self.sup.live_replicas()
                      if r.rid not in excluded]
            if not usable:
                if failed_over:
                    return _typed(502, "replica_lost",
                                  f"no live replica left after "
                                  f"{retries} dispatch(es) failed")
                return _typed(503, "no_live_replicas",
                              "no live replicas")
            full: set = set()
            while True:
                cand = [r for r in usable if r.rid not in full]
                if not cand:
                    return _typed(
                        503, "queue_full",
                        f"all {len(usable)} live replica(s) at "
                        f"capacity")
                r = min(cand, key=lambda x: (
                    x.in_flight, x.last_health.get("queued", 0), x.rid))
                outcome, detail = self._forward(r, body)
                if outcome == "ok":
                    if failed_over:
                        with self._ledger_lock:
                            self.failovers += 1
                        obs.counter("router.failovers_total").inc()
                    return 200, detail
                if outcome == "pass":       # the replica's own 4xx
                    return detail
                if outcome == "full":
                    full.add(r.rid)
                    continue
                if outcome == "timeout":
                    return _typed(504, "replica_timeout", detail)
                if outcome == "committed":
                    # The response had begun: the stream is committed
                    # and a retry could double-serve — typed error.
                    return _typed(502, "replica_lost",
                                  f"replica {r.rid} lost after its "
                                  f"response began: {detail}")
                # outcome == "lost": died before any response byte —
                # provably delivered nothing, safe to fail over.
                failed_over = True
                excluded.add(r.rid)
                self.sup.note_forward_failure(r.rid)
                if retries >= self.cfg.route_retries:
                    return _typed(502, "replica_lost",
                                  f"replica {r.rid} died before the "
                                  f"first token; {retries} retr"
                                  f"{'y' if retries == 1 else 'ies'} "
                                  f"exhausted: {detail}")
                retries += 1
                with self._ledger_lock:
                    self.retries += 1
                obs.counter("router.retries_total").inc()
                time.sleep(self._retry_backoff(retries))
                break     # rebuild the live set — it may have changed

    def _retry_backoff(self, attempt: int) -> float:
        base = min(self.cfg.retry_backoff_base_s * (2 ** (attempt - 1)),
                   self.cfg.retry_backoff_max_s)
        with self._rng_lock:
            return base * (0.5 + self._rng.random())   # ±50% jitter

    def _forward(self, r, body: bytes) -> Tuple[str, object]:
        """One dispatch to one replica -> (outcome, detail):

        - ``("ok", result)`` — 200, the finished generation
        - ``("pass", (status, obj))`` — the replica's own 4xx, passed
          through untouched (a bad request is bad on every replica)
        - ``("full", obj)`` — 503 from the replica (queue full /
          draining): unavailable for THIS request, not dead
        - ``("lost", msg)`` — failed before any response byte (connect
          refused/reset, or the replica answered 5xx declaring the
          request failed without serving it) — retryable
        - ``("committed", msg)`` — failed AFTER the response began —
          not retryable
        - ``("timeout", msg)`` — no answer within
          ``forward_timeout_s`` — not retryable (slow != dead)
        """
        self.sup.add_in_flight(r.rid, +1)
        conn = http.client.HTTPConnection(
            "127.0.0.1", r.port, timeout=self.cfg.forward_timeout_s)
        committed = False
        try:
            conn.request("POST", "/generate", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            committed = True
            raw = resp.read()
            try:
                obj = json.loads(raw)
            except ValueError:
                obj = {"error": "replica returned non-JSON"}
            if resp.status == 200:
                return "ok", obj
            if resp.status == 503:
                return "full", obj
            if resp.status >= 500:
                return "lost", (f"replica {r.rid} answered "
                                f"{resp.status}: {obj.get('error')}")
            return "pass", (resp.status, obj)
        except socket.timeout:
            return "timeout", (f"replica {r.rid} gave no answer within "
                               f"{self.cfg.forward_timeout_s}s")
        except Exception as e:
            kind = "committed" if committed else "lost"
            return kind, f"{type(e).__name__}: {e}"
        finally:
            conn.close()
            self.sup.add_in_flight(r.rid, -1)


# ---------------------------------------------------------- HTTP front end
def run_front_end(router: Router, supervisor, port: int, *,
                  ready_cb=None, drain: Optional[threading.Event] = None,
                  drain_timeout_s: float = 30.0) -> int:
    """Serve the router over stdlib HTTP: POST ``/generate`` routes
    across replicas, GET ``/healthz`` reports the replica set. Setting
    ``drain`` (the signal handlers do) closes admission (POST -> 503
    "draining", ``/healthz`` -> 503) and runs the ROLLING drain —
    replicas stop one at a time, each finishing its in-flight work, so
    capacity never hits zero before the last one — then shuts the
    server down. Mirrors ``cli/serve.run_http``'s lifecycle contract
    (non-daemon handlers flush final responses; a second signal is
    ignored)."""
    import sys
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    drain = drain if drain is not None else threading.Event()
    stop = threading.Event()

    class Handler(BaseHTTPRequestHandler):
        timeout = 60

        def log_message(self, *a):
            pass

        def _send(self, code: int, obj: dict):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path != "/healthz":
                return self._send(404, {"error": "unknown path"})
            live = supervisor.live_count()
            if drain.is_set():
                status = "draining"
            elif live == 0:
                status = "no live replicas"
            else:
                status = "ok"
            self._send(200 if status == "ok" else 503, {
                "status": status, "replicas_live": live,
                "replicas": supervisor.describe()})

        def do_POST(self):
            if self.path != "/generate":
                return self._send(404, {"error": "unknown path"})
            if drain.is_set():
                return self._send(*_typed(503, "draining", "draining"))
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n))
            except (ValueError, json.JSONDecodeError) as e:
                return self._send(*_typed(400, "bad_request", str(e)))
            if not isinstance(payload, dict):
                return self._send(*_typed(400, "bad_request",
                                          "request must be a JSON "
                                          "object"))
            code, obj = router.route(payload)
            self._send(code, obj)

    class Server(ThreadingHTTPServer):
        daemon_threads = False    # flush final responses at shutdown

    server = Server(("127.0.0.1", port), Handler)

    def drain_watch():
        drain.wait()
        if not stop.is_set():
            with obs.span("router.drain",
                          replicas=len(supervisor.replicas())) as sp:
                supervisor.rolling_drain(drain_timeout_s)
                sp.set(replicas_live=supervisor.live_count())
            stop.set()
        server.shutdown()

    threading.Thread(target=drain_watch, daemon=True).start()
    if ready_cb is not None:
        ready_cb(server)
    print(f"nezha-serve router listening on http://127.0.0.1:"
          f"{server.server_address[1]} "
          f"({supervisor.cfg.replicas} replicas; POST /generate, "
          f"GET /healthz)", file=sys.stderr)
    try:
        server.serve_forever(poll_interval=0.1)
    except KeyboardInterrupt:
        pass
    finally:
        stop.set()
        drain.set()     # unblock the watcher on non-signal exits
        server.server_close()
    return 0
