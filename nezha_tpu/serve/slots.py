"""Fixed-capacity KV slot pool.

The pool owns the serving layer's only large buffers: per-layer K/V
caches shaped ``[B_max, H, L_max, D]`` (the same layout
``models/generate.init_cache`` builds, with the batch dim reinterpreted
as SLOTS). A slot is one in-flight request's cache rows; slots are
allocated host-side (plain free list — allocation must not touch the
device) and their contents are written device-side:

- prefill slices a slot's rows out of the pool (:func:`read_slot`), runs
  the prompt chunk against them at its traced offset, and writes the
  updated rows back at ``(slot, 0, 0, 0)`` (:func:`write_slot`;
  engine.py builds the jitted bucket programs),
- decode blocks append one position per EMITTING row per scan step via
  the model's per-row-position cache path (models/gpt2.py): with a
  decode horizon the engine's ``active ∧ ¬done ∧ ok`` emit mask plays
  the role ``active`` played for single-token steps, so a row that hit
  EOS / its budget / a NaN freeze mid-block stops appending exactly
  like an empty slot does.

Freeing a slot is bookkeeping only — stale K/V stays in the buffers.
That is safe by construction: a new occupant's prefill chunks overwrite
``[0, prompt_len)`` in order, and attention only ever covers positions
the request itself has written first — each chunk attends the prefix
earlier chunks wrote plus its own causal window, and the decode path
(mask or flash-decode ``lengths``) stops at ``pos``. Bucket pads beyond
the prompt write garbage K/V above ``prompt_len`` that the first decode
writes overwrite before any mask reaches them. Non-emitting rows in a
decode block (inactive slots, rows done mid-horizon) write one pad
token's K/V at their FROZEN position each scan step — always one past
the row's real content, at most at ``max_len - 1`` via the update-slice
clamp on a row that filled its capacity (such a row is always done →
retired), and never attended: the row's own ``lengths`` stop at its
content, and the next occupant rebuilds everything it will ever attend.
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
from jax import lax


class SlotPool:
    """Host-side slot bookkeeping + the pooled device cache buffers.

    ``caches`` is the per-layer list of ``{"k", "v"}`` dicts the model's
    cache path consumes. The pool hands out slot INDICES; the engine
    threads the cache pytree through its jitted programs (functional
    updates — the pool re-binds ``caches`` to each program's output).
    """

    def __init__(self, model, capacity: int, max_len: int,
                 dtype=jnp.bfloat16):
        from nezha_tpu.models.generate import init_cache
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if max_len < 1:
            raise ValueError(f"max_len must be >= 1, got {max_len}")
        self.capacity = capacity
        self.max_len = max_len
        self.dtype = dtype
        self.caches = init_cache(model, capacity, max_len, dtype)
        # LIFO free list: the most-recently-freed slot is re-used first,
        # keeping the active rows clustered low (cheap occupancy reads).
        self._free: List[int] = list(range(capacity - 1, -1, -1))

    # ----------------------------------------------------------- alloc
    def alloc(self) -> Optional[int]:
        """-> a free slot index, or None when the pool is fully occupied."""
        return self._free.pop() if self._free else None

    def free(self, slot: int) -> None:
        if not 0 <= slot < self.capacity:
            raise ValueError(f"slot {slot} out of range [0, {self.capacity})")
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free (double free)")
        self._free.append(slot)

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_active(self) -> int:
        return self.capacity - len(self._free)

    @property
    def occupancy(self) -> float:
        """Active fraction in [0, 1] — the batch-occupancy gauge value."""
        return self.num_active / self.capacity


def read_slot(pool_leaf, slot):
    """Slice one slot's rows out of a pooled cache leaf:
    ``pool_leaf [B_max, H, L_max, D]`` -> ``[1, H, L_max, D]``, ``slot``
    a traced int32 scalar. Pure — call under jit (the engine's bucket
    prefill programs run each prompt chunk against this view)."""
    return lax.dynamic_slice_in_dim(pool_leaf, slot, 1, axis=0)


def write_slot(pool_leaf, chunk_leaf, slot):
    """Write rows back into a slot of a pooled cache leaf:
    ``pool_leaf [B_max, H, L_max, D]``, ``chunk_leaf [1, H, P, D]``
    (P <= L_max), ``slot`` a traced int32 scalar. Pure — returns the
    updated leaf; call under jit (engine prefill program)."""
    zero = jnp.zeros((), jnp.int32)
    return lax.dynamic_update_slice(
        pool_leaf, chunk_leaf.astype(pool_leaf.dtype),
        (slot, zero, zero, zero))
