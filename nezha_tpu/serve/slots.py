"""KV pools for the serving engine: dense slots and ref-counted pages.

Two layouts share one slot-level contract (``alloc``/``free``/
``num_free``/``occupancy`` — the scheduler's whole view):

- :class:`SlotPool` — the dense layout: per-layer K/V buffers shaped
  ``[B_max, H, L_max, D]``, one worst-case ``max_len`` reservation per
  admitted request. Simple, but memory occupancy (not compute) caps
  concurrency: a 10-token request holds the same rows as a full one.
- :class:`PagedSlotPool` — the block-paged layout
  (``ServeConfig.kv_layout="paged"``, the default): per-layer K/V
  buffers shaped ``[num_blocks, H, block_size, D]``, a host-side free
  list of blocks with REF COUNTS, and a per-slot block table
  (``[max_blocks_per_row]`` int32) threaded into the compiled programs.
  Admission binds only the blocks the prompt needs and decode binds
  further blocks lazily as positions advance, so resident memory tracks
  tokens actually written, not ``B_max * max_len``. Block 0 is a
  reserved SCRATCH block: freed slots' table rows reset to it and
  non-emitting rows' pad writes are routed to it in-program, so a
  retired slot can never scribble on a block that was rebound to a new
  request.

On top of the ref counts the paged pool keeps a **prefix-reuse trie**
(:class:`PrefixTrie`) keyed on full blocks of prompt tokens: a request
whose prompt prefix matches cached blocks takes REFERENCES on them
instead of re-prefilling (TTFT collapses for templated traffic), and
the trie itself holds one reference per cached block so the cache
survives its donor's retirement. Writes go through
:meth:`PagedSlotPool.prepare_write`, which enforces the single
invariant everything else leans on: **a block is only ever written
while its ref count is exactly 1**. A write into a shared block
(ref > 1 — a cached prefix, or a donor's block another request now
references) first COPIES it to a fresh block and swaps the writer's
table entry (copy-on-write, counted in ``serve.kv.cow_copies_total``).
When the free list runs dry, trie-only blocks (ref == 1, held by the
cache alone) are evicted LRU-first; past that, binding raises the typed
:class:`KVBlocksExhausted` — the scheduler's backpressure signal, never
a crash. The ``serve.kv.bind`` fault point arms the same path for
chaos plans.

**Host tier** (``ServeConfig.kv_host_blocks``, int8 pools only): LRU
eviction normally *discards* a cached block, so a chat user returning
for turn N+1 after device blocks cycle pays a full cold prefill. With
a host budget configured, an evicted trie block is DEMOTED instead —
its int8 payload + per-(block, head) scales (already the migration
wire format, so the copy is lossless and bit-identical) land in a
host-side LRU keyed by the block's full prompt-prefix token path.
``bind_for_prompt`` then extends a device trie match through the host
tier: consecutively host-cached blocks past the device-matched prefix
are PROMOTED back — fresh ref == 1 allocations (the write invariant
holds by construction), the wire payload scattered in by the same
device op migration installs with, the blocks re-indexed in the trie,
and the requesting slot referencing them like any other prefix hit.
The scatter is DISPATCHED before any host bookkeeping (the engine's
``copy_to_host_async``-then-bookkeep idiom, reversed), so the bucketed
prefill chunks that follow queue behind the host→device copy instead
of the host ever blocking on it — promotion is pure data movement and
adds NO compiled programs. Promotion is exclusive (the host entry
moves, it is not copied), a failed promote (pool exhausted mid-alloc,
or the ``serve.kv.promote`` fault point) degrades to a cold prefill —
typed, counted, never an error surfaced to the request — and
``leak_check`` audits the host tier's books (entry count vs budget,
byte accounting, per-entry geometry) next to the device ref counts.
At int8, host RAM holds ~100x the device's resident conversations —
this is what makes shared-prefix reuse survive real multi-tenant
churn instead of only back-to-back templated bursts.

Stale-KV reuse invariant (regression-tested for both layouts): freeing
a slot/block is bookkeeping only — stale K/V stays in the buffers, and
that is safe by construction because a new occupant's prefill
overwrites ``[0, prompt_len)`` (or takes references to blocks holding
EXACTLY the tokens it would have written) before attention ever covers
those positions, and the decode path (mask or flash-decode ``lengths``)
stops at ``pos``. Bucket pads beyond the prompt write garbage K/V above
``prompt_len`` that the first decode writes overwrite before any mask
reaches them. Non-emitting rows in a decode block write one pad token's
K/V at their FROZEN position each scan step (dense: their own slot row;
paged: their own bound block, or scratch when inactive) — never
attended, because the row's own ``lengths`` stop at its content.
"""

from __future__ import annotations

import collections
import itertools
import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from nezha_tpu import faults, obs


class KVBlocksExhausted(RuntimeError):
    """Typed backpressure: a KV block bind found no free block (after
    eviction). Carries the ``slot`` that was being grown (None during
    admission binds). The scheduler retires/requeues instead of
    crashing the decode loop."""

    def __init__(self, msg: str, slot: Optional[int] = None):
        super().__init__(msg)
        self.slot = slot


class SlotPool:
    """Host-side slot bookkeeping + the pooled dense cache buffers.

    ``caches`` is the per-layer list of ``{"k", "v"}`` dicts the model's
    cache path consumes. The pool hands out slot INDICES; the engine
    threads the cache pytree through its jitted programs (functional
    updates — the pool re-binds ``caches`` to each program's output).
    """

    paged = False
    quantized = False
    # Host-tier accounting, layout-invariant (the serve.kv.host_*
    # gauges report 0 for dense pools, never go missing).
    host_blocks = 0
    host_blocks_used = 0
    host_bytes_resident = 0
    demotions = 0
    promotions = 0
    promote_failures = 0

    def __init__(self, model, capacity: int, max_len: int,
                 dtype=jnp.bfloat16):
        from nezha_tpu.models.generate import init_cache
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if max_len < 1:
            raise ValueError(f"max_len must be >= 1, got {max_len}")
        self.capacity = capacity
        self.max_len = max_len
        self.dtype = dtype
        cfg = model.cfg
        self._slot_bytes = (2 * cfg.num_layers * cfg.num_heads * max_len
                            * (cfg.hidden_size // cfg.num_heads)
                            * jnp.dtype(dtype).itemsize)
        self.caches = init_cache(model, capacity, max_len, dtype)
        # LIFO free list: the most-recently-freed slot is re-used first,
        # keeping the active rows clustered low (cheap occupancy reads).
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        # A second pool shadowing this one's slot lifecycle (the
        # speculative engine's DRAFT KV pool): alloc/free mirror by slot
        # INDEX, so the draft model's cache rows for request R always
        # live at the same slot as the target's, and freeing the target
        # slot can never leak the draft's blocks.
        self.mirror = None

    # ----------------------------------------------------------- alloc
    def alloc(self) -> Optional[int]:
        """-> a free slot index, or None when the pool is fully occupied."""
        slot = self._free.pop() if self._free else None
        if slot is not None and self.mirror is not None:
            self.mirror.claim(slot)
        return slot

    def claim(self, slot: int) -> None:
        """Take a SPECIFIC free slot (the mirror path: the leader pool
        chose the index). Raises if the slot is not free — lifecycle
        drift between the pools must surface, not corrupt."""
        self._free.remove(slot)

    def free(self, slot: int) -> None:
        if not 0 <= slot < self.capacity:
            raise ValueError(f"slot {slot} out of range [0, {self.capacity})")
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free (double free)")
        self._free.append(slot)
        if self.mirror is not None:
            self.mirror.free(slot)

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_active(self) -> int:
        return self.capacity - len(self._free)

    @property
    def occupancy(self) -> float:
        """Active fraction in [0, 1] — the batch-occupancy gauge value."""
        return self.num_active / self.capacity

    @property
    def blocks_used(self) -> int:
        """Dense pools have no block granularity; report reserved rows
        in slot units so the ``serve.kv.blocks_used`` gauge stays
        meaningful across layouts."""
        return self.num_active

    @property
    def bytes_resident(self) -> int:
        """Device bytes the active reservations hold (the
        ``serve.kv.bytes_resident`` gauge): dense reserves a worst-case
        ``max_len`` K/V row pair per active slot, whatever was actually
        written."""
        return self.num_active * self._slot_bytes


def read_slot(pool_leaf, slot):
    """Slice one slot's rows out of a pooled cache leaf:
    ``pool_leaf [B_max, H, L_max, D]`` -> ``[1, H, L_max, D]``, ``slot``
    a traced int32 scalar. Pure — call under jit (the engine's bucket
    prefill programs run each prompt chunk against this view)."""
    return lax.dynamic_slice_in_dim(pool_leaf, slot, 1, axis=0)


def write_slot(pool_leaf, chunk_leaf, slot):
    """Write rows back into a slot of a pooled cache leaf:
    ``pool_leaf [B_max, H, L_max, D]``, ``chunk_leaf [1, H, P, D]``
    (P <= L_max), ``slot`` a traced int32 scalar. Pure — returns the
    updated leaf; call under jit (engine prefill program)."""
    zero = jnp.zeros((), jnp.int32)
    return lax.dynamic_update_slice(
        pool_leaf, chunk_leaf.astype(pool_leaf.dtype),
        (slot, zero, zero, zero))


# --------------------------------------------------------------- paged
class _TrieNode:
    __slots__ = ("tokens", "block", "children", "parent", "tick")

    def __init__(self, tokens: Tuple[int, ...], block: int,
                 parent: Optional["_TrieNode"], tick: int):
        self.tokens = tokens
        self.block = block
        self.children: Dict[Tuple[int, ...], "_TrieNode"] = {}
        self.parent = parent
        self.tick = tick


class PrefixTrie:
    """Prefix-reuse index over FULL blocks of prompt tokens.

    Each node is one cached block keyed by the exact ``block_size``
    token tuple it holds, childed under the node for the preceding
    block — so a root-to-node path spells a prompt prefix. Only full
    blocks are indexed: a full block is never written again (writes
    happen at positions past it), so cached content is immutable by
    construction and lookups never race writers. The trie holds ONE
    pool reference per node; eviction (leaf-first, LRU by touch tick)
    drops that reference, freeing the block once no request holds it.
    """

    def __init__(self, block_size: int):
        self.block_size = block_size
        self.root: Dict[Tuple[int, ...], _TrieNode] = {}
        self._nodes: set = set()
        # Leaves maintained incrementally: eviction candidates are
        # found in O(|leaves|) instead of scanning every node — the
        # reclaim path runs under memory pressure on the per-dispatch
        # binding path, where an O(nodes) scan per freed block would
        # bite exactly when the pool is fullest.
        self._leaves: set = set()
        self._tick = itertools.count()

    def __len__(self) -> int:
        return len(self._nodes)

    @property
    def blocks(self) -> List[int]:
        return [n.block for n in self._nodes]

    def match(self, tokens: Sequence[int]) -> List[int]:
        """-> block ids of the longest cached full-block prefix of
        ``tokens`` (possibly empty). Touches matched nodes (LRU)."""
        bs = self.block_size
        out: List[int] = []
        children = self.root
        i = 0
        while i + bs <= len(tokens):
            node = children.get(tuple(int(t) for t in tokens[i:i + bs]))
            if node is None:
                break
            node.tick = next(self._tick)
            out.append(node.block)
            children = node.children
            i += bs
        return out

    def insert(self, tokens: Sequence[int], blocks: Sequence[int],
               take_ref) -> int:
        """Index the full-block prefix of ``tokens`` under ``blocks``
        (the slot's bound block ids, one per block of the prompt).
        ``take_ref(block)`` is called once per NEWLY inserted node (the
        trie's own reference). Existing nodes (same token path) are
        kept — first writer wins, later identical content just
        refreshes the LRU tick. -> number of nodes inserted."""
        bs = self.block_size
        children = self.root
        parent: Optional[_TrieNode] = None
        inserted = 0
        for bi in range(len(tokens) // bs):
            key = tuple(int(t) for t in tokens[bi * bs:(bi + 1) * bs])
            node = children.get(key)
            if node is None:
                node = _TrieNode(key, int(blocks[bi]), parent,
                                 next(self._tick))
                children[key] = node
                self._nodes.add(node)
                self._leaves.add(node)
                if parent is not None:
                    self._leaves.discard(parent)
                take_ref(node.block)
                inserted += 1
            else:
                node.tick = next(self._tick)
            parent = node
            children = node.children
        return inserted

    def evict(self, want: int, release, only=None,
              on_evict: Optional[Callable] = None) -> int:
        """Drop up to ``want`` cached blocks, leaf-first and LRU-first
        within the leaves (a parent only becomes evictable once its
        children are gone — evicting an interior node would orphan the
        path below it). ``only(block)``, when given, filters the
        candidates — the pool passes "ref count is exactly 1" so
        eviction only ever destroys entries whose release actually
        FREES a block (a leaf still bound by a live prefix-hit request
        would free nothing). ``on_evict(path_tokens, block)``, when
        given, runs for each victim BEFORE its release, with the full
        root-to-node token path — the pool's host-tier demotion hook
        (the block still holds the node's content here: full blocks
        are immutable and ref == 1 means nobody else can write it).
        ``release(block)`` drops the trie's reference. -> nodes
        actually evicted."""
        evicted = 0
        while evicted < want:
            leaves = [n for n in self._leaves
                      if only is None or only(n.block)]
            if not leaves:
                break
            victim = min(leaves, key=lambda n: n.tick)
            self._remove(victim)
            if on_evict is not None:
                on_evict(self._path_tokens(victim), victim.block)
            release(victim.block)
            evicted += 1
        return evicted

    @staticmethod
    def _path_tokens(node: _TrieNode) -> Tuple[int, ...]:
        """The full root-to-``node`` token path — the prompt prefix
        whose K/V the node's block (with its ancestors') holds. The
        host tier keys on this, never on the node's own block tokens
        alone: a block's content depends on every preceding token."""
        parts: List[Tuple[int, ...]] = []
        while node is not None:
            parts.append(node.tokens)
            node = node.parent
        return tuple(t for tok in reversed(parts) for t in tok)

    def clear(self, release) -> int:
        """Drop every cached block (the ``prefix_cache`` off-switch /
        test teardown). -> count dropped."""
        n = len(self._nodes)
        for node in self._nodes:
            release(node.block)
        self.root = {}
        self._nodes = set()
        self._leaves = set()
        return n

    def _remove(self, node: _TrieNode) -> None:
        siblings = node.parent.children if node.parent else self.root
        siblings.pop(node.tokens, None)
        self._nodes.discard(node)
        self._leaves.discard(node)
        if node.parent is not None and not node.parent.children:
            self._leaves.add(node.parent)


def _copy_block(caches: list, src, dst) -> list:
    """Device-side block copy across every layer's K and V pool:
    ``caches[l][kv] [N, H, bs, D]`` with block ``src`` copied over
    block ``dst``. The COW move. Leading-axis tree_map means every
    block-indexed leaf moves together — int8 pools' ``[N, H]`` scale
    rows copy with their blocks in the same call (the "a block and its
    scale row move together" invariant). Jitted once per pool shape (src/dst
    cross as 0-d arrays so indices never recompile); donation makes it
    an in-place rewrite of one block, not a pool copy. Deliberately NOT
    routed through the engine executor: the frozen-program contract
    ("1 step + len(prefill_buckets) entries") is pinned on the
    executor's cache, and COW is pool maintenance, not a serving
    program."""
    def leaf(x):
        blk = lax.dynamic_slice_in_dim(x, src, 1, axis=0)
        return lax.dynamic_update_slice_in_dim(x, blk, dst, axis=0)

    return jax.tree_util.tree_map(leaf, caches)


_copy_block_jit = jax.jit(_copy_block, donate_argnums=(0,))


# ------------------------------------------------- migration device ops
# Block export/install for cross-replica KV migration (serve/migrate.py
# carries the wire; the router orchestrates). Like _copy_block these are
# pool maintenance, deliberately NOT routed through the engine executor:
# the frozen-program contract is pinned on the executor's cache, and a
# migration is not a serving program. jax.jit keys on the index shape,
# so one program per distinct block count — block counts are small and
# bounded by blocks_per_slot.
def _gather_blocks_quantized(caches, idx):
    """int8 pool -> wire: the blocks ARE the wire format already (int8
    data + fp32 per-(block, head) scales), so export is a pure gather —
    a migrated block lands on the destination bit-identical."""
    return [{k: jnp.take(layer[k], idx, axis=0)
             for k in ("k", "v", "k_scale", "v_scale")}
            for layer in caches]


def _gather_quantize_blocks(caches, idx):
    """bf16/f32 pool -> wire: gather the blocks and quantize them to
    the int8+scales wire format (ops/quant.py — the EQuARX recipe the
    wire collectives use, ~4x fewer bytes than bf16). Lossy at the
    quantizer's amax/254 per-block bound; int8 pools take the lossless
    path above."""
    from nezha_tpu.ops import quant
    out = []
    for layer in caches:
        entry = {}
        for kv in ("k", "v"):
            q, s = quant.quantize_kv_block(
                jnp.take(layer[kv], idx, axis=0))
            entry[kv] = q
            entry[f"{kv}_scale"] = s
        out.append(entry)
    return out


def _scatter_blocks_quantized(caches, idx, payload):
    """Wire -> int8 pool: write int8 blocks + scale rows verbatim at
    the freshly allocated (ref == 1) indices."""
    return [{k: layer[k].at[idx].set(pay[k].astype(layer[k].dtype))
             for k in layer}
            for layer, pay in zip(caches, payload)]


def _scatter_blocks_dequant(caches, idx, payload):
    """Wire -> bf16/f32 pool: dequantize the int8 blocks to the pool
    dtype and write them at the freshly allocated indices."""
    from nezha_tpu.ops import quant
    out = []
    for layer, pay in zip(caches, payload):
        new = dict(layer)
        for kv in ("k", "v"):
            blk = quant.dequantize_kv_block(
                pay[kv], pay[f"{kv}_scale"], layer[kv].dtype)
            new[kv] = layer[kv].at[idx].set(blk)
        out.append(new)
    return out


_gather_blocks_quantized_jit = jax.jit(_gather_blocks_quantized)
_gather_quantize_blocks_jit = jax.jit(_gather_quantize_blocks)
_scatter_blocks_quantized_jit = jax.jit(_scatter_blocks_quantized,
                                        donate_argnums=(0,))
_scatter_blocks_dequant_jit = jax.jit(_scatter_blocks_dequant,
                                      donate_argnums=(0,))


class PagedSlotPool:
    """Block-paged KV pool: ref-counted blocks + per-slot block tables.

    Device state: ``caches`` (per-layer ``{"k", "v"}`` pools shaped
    ``[num_blocks, H, block_size, D]``) and — uploaded per dispatch from
    the host mirror — ``tables_host`` (``[capacity, blocks_per_slot]``
    int32; entry ``[s, i]`` is the pool block holding slot ``s``'s
    positions ``[i*bs, (i+1)*bs)``, or 0/scratch when unbound). Host
    state: the block free list, per-block ref counts, per-slot bound
    counts, and the prefix trie.

    With ``quantized=True`` (``ServeConfig.kv_dtype="int8"``) the K/V
    pools store int8 and each layer carries ``k_scale``/``v_scale``
    fp32 buffers shaped ``[num_blocks, H]`` — one absmax scale per
    (block, head), written by the in-program block-granularity
    quantizer (models/gpt2.py) and consumed by the flash-decode
    kernel's in-loop dequant. Because scales are block-indexed leaves
    of the SAME caches pytree, every lifecycle move is shared: COW
    copies a block's scale row with it, freeing/rebinding a block
    implicitly retires its stale scale (the next occupant's first
    write recomputes it — stale positions are zeroed before the
    block's absmax is taken, so a previous occupant can never inflate
    the new scale), and the stale-KV poisoning regression covers scale
    rows too.

    Invariants (the chaos tests' leak check asserts them):

    - block 0 is scratch: never allocated, never ref-counted;
    - a block is written only while its ref count is exactly 1
      (:meth:`prepare_write` COWs shared blocks first);
    - every non-free block's ref count equals (slots binding it) +
      (1 if a trie node caches it);
    - freeing the last reference returns the block to the free list.
    """

    paged = True

    def __init__(self, model, capacity: int, max_len: int,
                 dtype=jnp.bfloat16, *, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 prefix_cache: bool = True, eviction: str = "lru",
                 quantized: bool = False, host_blocks: int = 0):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if max_len < 1:
            raise ValueError(f"max_len must be >= 1, got {max_len}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if eviction not in ("lru", "none"):
            raise ValueError(
                f"eviction must be 'lru' or 'none', got {eviction!r}")
        if host_blocks < 0:
            raise ValueError(
                f"host_blocks must be >= 0, got {host_blocks}")
        if host_blocks and not quantized:
            # The host tier stores the pool's native block bytes, and
            # only int8 blocks ARE the wire format (lossless round
            # trip). A bf16 tier would silently serve quantize-dequant
            # blocks that differ from a fresh prefill — refuse rather
            # than make hit-vs-miss results diverge.
            raise ValueError(
                "host_blocks requires a quantized (int8) pool — the "
                "demoted payload is the int8+scales block verbatim")
        if host_blocks and not prefix_cache:
            raise ValueError(
                "host_blocks requires prefix_cache (demotion feeds off "
                "trie eviction; without the trie the tier is inert)")
        self.capacity = capacity
        self.max_len = max_len
        self.dtype = dtype
        self.block_size = block_size
        # Table width: every slot must be able to reach max_len.
        self.blocks_per_slot = math.ceil(max_len / block_size)
        if num_blocks is None:
            # Dense-equivalent capacity by default (+1 for scratch):
            # paged-by-default must never serve LESS than dense did.
            num_blocks = 1 + capacity * self.blocks_per_slot
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is scratch), got "
                f"{num_blocks}")
        self.num_blocks = num_blocks
        self.prefix_cache_enabled = prefix_cache
        self.eviction = eviction
        self.quantized = quantized
        cfg = model.cfg
        d = cfg.hidden_size // cfg.num_heads
        shape = (num_blocks, cfg.num_heads, block_size, d)
        if quantized:
            # ``ServeConfig.kv_dtype="int8"``: K/V blocks store int8
            # plus one fp32 absmax scale per (block, head) — the
            # ``[num_blocks, H]`` scale buffers ride IN the caches
            # pytree, so everything that moves a block (program
            # donation, COW copy, checkpoint of the tree structure)
            # moves its scale row with it by construction. Zero-init:
            # q = 0 with scale 0 dequantizes to exact zeros, same as
            # the bf16 pool's zero init.
            sshape = (num_blocks, cfg.num_heads)
            self.caches = [{"k": jnp.zeros(shape, jnp.int8),
                            "v": jnp.zeros(shape, jnp.int8),
                            "k_scale": jnp.zeros(sshape, jnp.float32),
                            "v_scale": jnp.zeros(sshape, jnp.float32)}
                           for _ in range(cfg.num_layers)]
        else:
            self.caches = [{"k": jnp.zeros(shape, dtype),
                            "v": jnp.zeros(shape, dtype)}
                           for _ in range(cfg.num_layers)]
        kv_bytes = (cfg.num_heads * block_size * d
                    * (1 if quantized else jnp.dtype(dtype).itemsize))
        scale_bytes = cfg.num_heads * 4 if quantized else 0
        # Per-block device footprint (k + v + scales, all layers) — the
        # serve.kv.bytes_resident gauge's unit and the equal-memory
        # bench's conversion rate between int8 and bf16 block budgets.
        self.bytes_per_block = 2 * cfg.num_layers * (kv_bytes
                                                     + scale_bytes)
        self.tables_host = np.zeros((capacity, self.blocks_per_slot),
                                    np.int32)
        self._free_slots: List[int] = list(range(capacity - 1, -1, -1))
        # Block 0 reserved as scratch (pad-write sink for non-emitting
        # rows) — LIFO free list over the rest.
        self._free_blocks: List[int] = list(range(num_blocks - 1, 0, -1))
        self._refs = np.zeros((num_blocks,), np.int64)
        self._bound = np.zeros((capacity,), np.int32)   # per-slot entries
        self.trie = PrefixTrie(block_size)
        self.cow_copies = 0
        self.prefix_hits = 0
        # Host tier (0 = disabled): demoted blocks' int8+scales wire
        # payloads, LRU-ordered (oldest first), keyed by the FULL
        # prompt-prefix token path that block's K/V encodes. One entry
        # is one block: per-layer {"k","v","k_scale","v_scale"} host
        # arrays shaped [1, H, bs, D] / [1, H].
        self.host_blocks = host_blocks
        self._host_tier: "collections.OrderedDict[Tuple[int, ...], list]" \
            = collections.OrderedDict()
        self._host_bytes = 0
        self.demotions = 0
        self.promotions = 0
        self.promote_failures = 0
        # Fleet tier tags (PR 17): blocks whose content arrived over a
        # PEER pull (vs local prefill / migration) — the first trie hit
        # on such a block is a fleet "peer" hit, after which the block
        # is indistinguishable from local device cache and is counted
        # as such. The per-tier hit ledger feeds the
        # serve.kv.fleet_hits_* counters; one request counts at most
        # once per tier it touched.
        self._peer_blocks: set = set()
        self.fleet_hits = {"device": 0, "host": 0, "peer": 0}
        # Mirror pool (speculative draft KV — see SlotPool.mirror):
        # slot lifecycle is mirrored by INDEX; block bookkeeping stays
        # per-pool (the draft binds its own blocks lazily, sized by the
        # draft model's geometry). leak_check recurses into it, so the
        # chaos oracles cover both pools in one call.
        self.mirror = None

    # ------------------------------------------------------ slot layer
    def alloc(self) -> Optional[int]:
        """-> a free slot index, or None when every slot is occupied.
        Blocks are bound separately (:meth:`bind_for_prompt` /
        :meth:`prepare_write`) — a fresh slot holds none."""
        slot = self._free_slots.pop() if self._free_slots else None
        if slot is not None and self.mirror is not None:
            self.mirror.claim(slot)
        return slot

    def claim(self, slot: int) -> None:
        """Take a SPECIFIC free slot (the mirror path — see
        :meth:`SlotPool.claim`). Raises when the slot is not free."""
        self._free_slots.remove(slot)

    def free(self, slot: int) -> None:
        """Release the slot and DROP ITS BLOCK REFERENCES in the same
        call (the same-iteration contract the chaos suites pin): blocks
        nobody else references return to the free list, the table row
        resets to scratch so a stale dispatch mask can never write into
        a rebound block. A mirror pool (the draft cache) frees the same
        slot — and its own blocks — in the same call."""
        if not 0 <= slot < self.capacity:
            raise ValueError(f"slot {slot} out of range [0, {self.capacity})")
        if slot in self._free_slots:
            raise ValueError(f"slot {slot} is already free (double free)")
        self.release_blocks(slot)
        self._free_slots.append(slot)
        if self.mirror is not None:
            self.mirror.free(slot)

    def release_blocks(self, slot: int) -> None:
        """Drop the slot's block references (without freeing the slot):
        the table row resets to scratch and blocks nobody else holds
        return to the free list. Used by :meth:`free` and by the
        engine's cold-prefill fallback when a prefix hit pinned the
        very blocks its own copy-on-write then needed."""
        for i in range(int(self._bound[slot])):
            self._release(int(self.tables_host[slot, i]))
        self.tables_host[slot, :] = 0
        self._bound[slot] = 0

    @property
    def num_free(self) -> int:
        return len(self._free_slots)

    @property
    def num_active(self) -> int:
        return self.capacity - len(self._free_slots)

    @property
    def occupancy(self) -> float:
        return self.num_active / self.capacity

    # ----------------------------------------------------- block layer
    @property
    def blocks_used(self) -> int:
        """Non-free, non-scratch blocks (slot-bound + trie-cached) —
        the ``serve.kv.blocks_used`` gauge value."""
        return self.num_blocks - 1 - len(self._free_blocks)

    @property
    def bytes_resident(self) -> int:
        """Device bytes the resident blocks hold (K/V data + scale rows
        when quantized) — the ``serve.kv.bytes_resident`` gauge. The
        capacity lever in one number: at the same byte budget an int8
        pool holds ~2x the blocks of a bf16 pool (scale overhead is
        ``4 / (block_size * D)`` per element)."""
        return self.blocks_used * self.bytes_per_block

    @property
    def trie_only_blocks(self) -> int:
        """Blocks held ONLY by the prefix cache (ref == 1 via a trie
        node) — the evictable count."""
        return sum(1 for b in self.trie.blocks if self._refs[b] == 1)

    def available_blocks(self) -> int:
        """Free blocks plus what eviction could reclaim — the
        scheduler's admission budget."""
        n = len(self._free_blocks)
        if self.eviction == "lru":
            n += self.trie_only_blocks
        return n

    def blocks_for_span(self, end: int) -> int:
        """Blocks covering positions ``[0, end)``."""
        return math.ceil(min(end, self.max_len) / self.block_size)

    @property
    def max_request_blocks(self) -> int:
        """The most blocks one request could ever bind."""
        return min(self.blocks_per_slot, self.num_blocks - 1)

    def _alloc_block(self, slot: Optional[int]) -> int:
        """Pop a free block (evicting LRU trie-only cache blocks if the
        list is dry). ``serve.kv.bind`` is the chaos point: an injected
        error surfaces exactly like genuine exhaustion — typed
        backpressure, request-scoped, never a crash."""
        faults.point("serve.kv.bind")
        if not self._free_blocks and self.eviction == "lru":
            # Only evict entries whose release actually frees a block
            # (ref == 1, trie-only): evicting a leaf a live request
            # still binds would destroy cache value AND free nothing —
            # exhaustion must only be raised once every reclaimable
            # block has genuinely been reclaimed (the capacity
            # available_blocks() promised admission). With a host tier
            # configured the victim's payload is demoted to host RAM
            # first instead of being discarded.
            self.trie.evict(1, self._release,
                            only=lambda b: self._refs[b] == 1,
                            on_evict=(self._demote if self.host_blocks
                                      else None))
        if not self._free_blocks:
            raise KVBlocksExhausted(
                f"no free KV blocks ({self.blocks_used}/"
                f"{self.num_blocks - 1} in use, "
                f"{len(self.trie)} cached)", slot=slot)
        b = self._free_blocks.pop()
        self._refs[b] = 1
        return b

    def _release(self, block: int) -> None:
        if block == 0:
            return
        self._refs[block] -= 1
        if self._refs[block] == 0:
            self._free_blocks.append(block)
            # A freed block's peer tag dies with it: the index will be
            # rebound to unrelated content, which must count as local.
            self._peer_blocks.discard(block)
        elif self._refs[block] < 0:
            raise AssertionError(
                f"block {block} ref count went negative (double release)")

    # ------------------------------------------------------- host tier
    @property
    def host_blocks_used(self) -> int:
        """Demoted blocks resident in the host tier — the
        ``serve.kv.host_blocks_used`` gauge value."""
        return len(self._host_tier)

    @property
    def host_bytes_resident(self) -> int:
        """Host RAM the demoted payloads hold (int8 data + fp32 scale
        rows, all layers) — the ``serve.kv.host_bytes_resident``
        gauge."""
        return self._host_bytes

    @staticmethod
    def _entry_bytes(entry: List[Dict[str, np.ndarray]]) -> int:
        return sum(a.nbytes for layer in entry for a in layer.values())

    def _host_put(self, key: Tuple[int, ...], entry: list) -> None:
        """Insert one payload at the tier's MRU end with the byte books
        adjusted and the LRU budget cap re-applied — the ONE place the
        host-tier accounting invariant (that :meth:`leak_check`'s host
        column audits) is maintained; both demotion and the failed-
        promote restore route through here."""
        old = self._host_tier.pop(key, None)
        if old is not None:
            self._host_bytes -= self._entry_bytes(old)
        self._host_tier[key] = entry
        self._host_bytes += self._entry_bytes(entry)
        # Host LRU: the budget is a hard cap — oldest entries drop
        # (for good; there is no colder tier below host RAM).
        while len(self._host_tier) > self.host_blocks:
            _, dropped = self._host_tier.popitem(last=False)
            self._host_bytes -= self._entry_bytes(dropped)

    def _demote(self, path_tokens: Tuple[int, ...], block: int) -> None:
        """Trie-eviction hook: capture ``block``'s int8 payload +
        scales into the host tier before the block returns to the free
        list. The gather is the migration export op on one index; the
        device→host copies are started async and collected immediately
        (the eviction path is about to rebind this block, so the bytes
        must land before the pool's next write — the copy overlaps the
        per-leaf ``np.asarray`` walk, not the decode hot path)."""
        idx = jnp.asarray(np.asarray([block], np.int32))
        layers = _gather_blocks_quantized_jit(self.caches, idx)
        for layer in layers:
            for arr in layer.values():
                copy_async = getattr(arr, "copy_to_host_async", None)
                if copy_async is not None:
                    copy_async()
        entry = [{k: np.asarray(v) for k, v in layer.items()}
                 for layer in layers]
        self._host_put(path_tokens, entry)
        self.demotions += 1
        obs.counter("serve.kv.demotions_total").inc()

    def _promote(self, slot: int, tokens: List[int],
                 start_blocks: int) -> int:
        """Extend a device trie match through the host tier: promote
        the longest run of consecutively host-cached blocks past the
        ``start_blocks`` device-matched ones back onto the device —
        fresh ref == 1 allocations, the wire payload scattered in by
        the migration install op, the blocks re-indexed in the trie
        and referenced by ``slot``. The scatter is DISPATCHED before
        any bookkeeping (async host→device; the prefill chunks that
        follow queue behind it on the device stream — the engine's
        copy_to_host_async-then-bookkeep idiom, reversed). Degrades to
        a cold prefill — typed, counted, nothing leaked — when the
        pool cannot hold the span or the ``serve.kv.promote`` fault
        point fires. -> blocks promoted."""
        bs = self.block_size
        # Never promote the block holding position n-1: the final
        # prompt token always re-runs (its logits seed decoding), so
        # that block would COPY-ON-WRITE immediately — one allocation
        # MORE than the cold footprint the scheduler's admission
        # budget promised (on a pool at the admission edge the COW
        # would then exhaust, throwing the whole promote away via the
        # engine's cold fallback). Capped at (n-1)//bs, a promote
        # allocates exactly the blocks a cold prefill of the same span
        # would have bound. Device-trie hits keep matching the final
        # block — they take references (0 allocations), so their COW
        # stays within budget.
        limit = min((len(tokens) - 1) // bs, self.blocks_per_slot)
        keys: List[Tuple[int, ...]] = []
        entries: List[list] = []
        bi = start_blocks
        while bi < limit:
            key = tuple(tokens[:(bi + 1) * bs])
            entry = self._host_tier.get(key)
            if entry is None:
                break
            keys.append(key)
            entries.append(entry)
            bi += 1
        if not entries:
            return 0
        with obs.span("serve.kv.promote_s", blocks=len(entries)):
            try:
                faults.point("serve.kv.promote")
            except faults.InjectedFault:
                # The pinned degrade drill: the request simply
                # prefills cold; the host entries stay resident for
                # the next hit.
                self.promote_failures += 1
                return 0
            # Exclusive move: pop the entries FIRST, so a demotion our
            # own allocations trigger (eviction under pressure) can
            # never race the host-LRU into dropping what we're reading.
            for key, entry in zip(keys, entries):
                self._host_tier.pop(key, None)
                self._host_bytes -= self._entry_bytes(entry)
            blocks: List[int] = []
            try:
                for _ in entries:
                    blocks.append(self._alloc_block(slot))
            except (KVBlocksExhausted, faults.InjectedFault):
                # Typed degrade: release what we allocated, put the
                # entries back (MRU — they were just wanted), prefill
                # cold. Admission budgeted for exactly this no-hit
                # footprint, so nothing downstream is surprised. The
                # allocs that DID succeed may each have demoted a
                # third-party block into the tier, so the restore must
                # re-apply the LRU budget cap — _host_put does.
                for b in blocks:
                    self._release(b)
                for key, entry in zip(keys, entries):
                    self._host_put(key, entry)
                self.promote_failures += 1
                return 0
            # Async host->device: dispatch the uploads + scatters NOW;
            # every line after this is host bookkeeping the copies
            # overlap. Later device work (COW, prefill chunks) takes
            # self.caches as input, so XLA's dataflow ordering — not a
            # host sync — guarantees the promoted bytes land first.
            # The install jit keys on the index SHAPE, so the span is
            # scattered in POWER-OF-TWO runs (the prefill-bucket idiom
            # one level down): an m-block promote costs popcount(m)
            # dispatches against at most log2(blocks_per_slot) compiled
            # maintenance programs, all warmable off the clock
            # (:meth:`warm_host_tier_programs`) — never one program per
            # distinct m compiling inside a measured TTFT window.
            off = 0
            while off < len(blocks):
                run = 1
                while run * 2 <= len(blocks) - off:
                    run *= 2
                idx = jnp.asarray(
                    np.asarray(blocks[off:off + run], np.int32))
                chunk = entries[off:off + run]
                payload = [
                    {k: jnp.asarray(np.concatenate(
                        [e[li][k] for e in chunk], axis=0))
                     for k in chunk[0][li]}
                    for li in range(len(chunk[0]))]
                self.caches = _scatter_blocks_quantized_jit(
                    self.caches, idx, payload)
                off += run

            def take_ref(block: int) -> None:
                self._refs[block] += 1

            # Re-index under the trie (existing device-prefix nodes are
            # kept — insert only takes refs on the NEW nodes), then
            # bind the promoted span to the slot, then drop our
            # allocation refs: each promoted block ends at ref 2 (trie
            # + slot), exactly like a device prefix hit.
            path = ([int(b) for b in self.tables_host[slot, :start_blocks]]
                    + blocks)
            self.trie.insert(tokens[:bi * bs], path, take_ref)
            for i, b in enumerate(blocks):
                self._refs[b] += 1
                self.tables_host[slot, start_blocks + i] = b
            self._bound[slot] = start_blocks + len(blocks)
            for b in blocks:
                self._release(b)
            self.promotions += len(blocks)
            obs.counter("serve.kv.promotions_total").inc(len(blocks))
        return len(blocks)

    def clear_host_tier(self) -> int:
        """Drop every demoted payload (knob flips / tests / operator
        relief valve). -> entries dropped."""
        n = len(self._host_tier)
        self._host_tier.clear()
        self._host_bytes = 0
        return n

    def warm_host_tier_programs(self) -> None:
        """Compile the demote/promote maintenance programs — the
        one-block gather plus every power-of-two scatter width up to
        ``blocks_per_slot`` (promotion batches in power-of-two runs) —
        off the measured clock, via identity rewrites of the scratch
        block (never ref-counted, content is pad garbage by contract —
        writing it with its own bytes, even ``run`` times over, changes
        nothing). Benchmarks call this during warmup so the first real
        demotion/promotion never pays a compile inside a measured TTFT
        window; skipping it costs exactly those spikes, nothing else."""
        if not (self.host_blocks and self.quantized):
            return
        one = jnp.asarray(np.zeros((1,), np.int32))
        layers = _gather_blocks_quantized_jit(self.caches, one)
        entry = [{k: np.asarray(v) for k, v in layer.items()}
                 for layer in layers]
        run = 1
        while run <= self.blocks_per_slot:
            idx = jnp.asarray(np.zeros((run,), np.int32))
            payload = [
                {k: jnp.asarray(np.repeat(v, run, axis=0))
                 for k, v in layer.items()}
                for layer in entry]
            self.caches = _scatter_blocks_quantized_jit(
                self.caches, idx, payload)
            run *= 2

    # -------------------------------------------------- prompt binding
    def bind_for_prompt(self, slot: int, tokens: Sequence[int]) -> int:
        """Admission-time binding: match the prompt's full-block prefix
        against the trie and take REFERENCES on the cached blocks
        instead of re-prefilling them; with a host tier configured,
        extend the match through host-demoted blocks (promoted back as
        fresh allocations — see :meth:`_promote`). -> ``shared_len``,
        the number of leading positions whose K/V the slot now holds
        (block-aligned, capped at ``len(tokens) - 1`` so the final
        prompt token is always re-run — its logits seed decoding). The
        cap can land the first write inside the last shared block;
        :meth:`prepare_write` COWs it then."""
        if self._bound[slot]:
            raise ValueError(f"slot {slot} already holds blocks")
        n = len(tokens)
        toks = [int(t) for t in tokens]
        shared_blocks: List[int] = []
        if self.prefix_cache_enabled:
            shared_blocks = self.trie.match(toks)
        nshared = len(shared_blocks)
        if shared_blocks:
            for i, b in enumerate(shared_blocks):
                self._refs[b] += 1
                self.tables_host[slot, i] = b
            self._bound[slot] = nshared
        promoted = 0
        if self.host_blocks and self.prefix_cache_enabled:
            promoted = self._promote(slot, toks, nshared)
            nshared += promoted
        # Fleet three-tier hit accounting (PR 17): classify where this
        # request's reused blocks came from. Peer-pulled blocks count
        # as "peer" on their FIRST reuse (then revert to plain device
        # cache); host promotions count as "host"; everything else the
        # trie matched is "device". One bump per tier per request, one
        # bump of the roll-up total per request-with-any-hit.
        if nshared:
            tiers = []
            pulled = self._peer_blocks.intersection(shared_blocks)
            if pulled:
                self._peer_blocks.difference_update(pulled)
                tiers.append("peer")
            if len(pulled) < len(shared_blocks):
                tiers.append("device")
            if promoted:
                tiers.append("host")
            for t in tiers:
                self.fleet_hits[t] += 1
                obs.counter(f"serve.kv.fleet_hits_{t}_total").inc()
            obs.counter("serve.kv.fleet_hits_total").inc()
        return min(nshared * self.block_size, n - 1)

    def count_prefix_hit(self) -> None:
        """Account one MATERIALIZED prefix hit. Called by the engine
        after the hit's write binding succeeded — not inside
        :meth:`bind_for_prompt` — so a tight-pool hit that had to fall
        back to a cold prefill never inflates the cache-win metrics."""
        self.prefix_hits += 1
        obs.counter("serve.kv.prefix_hits_total").inc()

    def register_prefix(self, slot: int, tokens: Sequence[int]) -> int:
        """Post-prefill: index the prompt's full blocks (now holding
        exactly those tokens' K/V) in the trie, which takes its own
        reference per newly cached block — the cache outlives the
        donor. -> nodes inserted."""
        if not self.prefix_cache_enabled:
            return 0
        nfull = len(tokens) // self.block_size
        if nfull == 0 or self._bound[slot] < nfull:
            return 0

        def take_ref(block: int) -> None:
            self._refs[block] += 1

        return self.trie.insert(
            list(tokens)[:nfull * self.block_size],
            [int(b) for b in self.tables_host[slot, :nfull]], take_ref)

    # ------------------------------------------------------ write path
    def prepare_write(self, slot: int, start: int, end: int) -> None:
        """Make positions ``[start, end)`` of ``slot`` writable before a
        dispatch that will write them: bind fresh blocks past the bound
        frontier, and copy-on-write any block in the span whose ref
        count exceeds 1 (shared prefix, or a donor's block the cache /
        another request references). Raises :class:`KVBlocksExhausted`
        (typed backpressure) when no block can be found."""
        bs = self.block_size
        end = min(end, self.blocks_per_slot * bs)
        first = min(start // bs, int(self._bound[slot]))
        last = math.ceil(end / bs)
        for bi in range(first, last):
            if bi < self._bound[slot]:
                b = int(self.tables_host[slot, bi])
                if self._refs[b] > 1:
                    nb = self._alloc_block(slot)
                    self.caches = _copy_block_jit(
                        self.caches, np.int32(b), np.int32(nb))
                    self.tables_host[slot, bi] = nb
                    self._release(b)
                    self.cow_copies += 1
                    obs.counter("serve.kv.cow_copies_total").inc()
            else:
                if bi != self._bound[slot]:
                    raise AssertionError(
                        f"non-contiguous bind: slot {slot} bound "
                        f"{int(self._bound[slot])} blocks, write wants "
                        f"block {bi}")
                self.tables_host[slot, bi] = self._alloc_block(slot)
                self._bound[slot] = bi + 1

    # ------------------------------------------------------- migration
    def export_block_payload(self, slot: int, nblocks: int
                             ) -> Tuple[List[Dict[str, np.ndarray]], int]:
        """Export the first ``nblocks`` bound blocks of ``slot`` in the
        int8+scales wire layout: -> (per-layer ``{"k", "k_scale", "v",
        "v_scale"}`` host arrays, total payload bytes). int8 pools
        export their blocks verbatim (a migrated block is
        bit-identical on the destination); bf16/f32 pools quantize to
        the wire format on device first (lossy at the quantizer's
        per-block amax/254 bound — the same bound
        ``serve.kv.quant_error`` samples). Export is read-only: the
        source's refs are untouched — release is the ACK's job
        (two-phase handoff, serve/migrate.py). On a head-sharded pool
        (serve/sharded) the host conversion IS the gather: the wire
        payload always carries full heads, whatever mesh the source
        ran (gather-on-export)."""
        if not 1 <= nblocks <= int(self._bound[slot]):
            raise ValueError(
                f"cannot export {nblocks} block(s) from slot {slot}: "
                f"{int(self._bound[slot])} bound")
        idx = jnp.asarray(self.tables_host[slot, :nblocks].copy())
        if self.quantized:
            layers = _gather_blocks_quantized_jit(self.caches, idx)
        else:
            layers = _gather_quantize_blocks_jit(self.caches, idx)
        host = [{k: np.asarray(v) for k, v in layer.items()}
                for layer in layers]
        nbytes = sum(a.nbytes for layer in host for a in layer.values())
        return host, nbytes

    # ------------------------------------------------------ fleet cache
    def digest_entries(self):
        """Yield ``(path_tokens, tier)`` for every cached prefix this
        pool could serve — device trie paths first (hottest first, by
        LRU tick), then host-tier keys (MRU first) — the recency order
        :func:`fleetcache.build_digest` truncates against. A bounded
        host walk (no device ops); callers hold the scheduler lock."""
        if self.prefix_cache_enabled:
            for node in sorted(self.trie._nodes,
                               key=lambda n: n.tick, reverse=True):
                yield self.trie._path_tokens(node), "device"
        for key in reversed(self._host_tier):
            yield key, "host"

    def export_prefix_payload(self, tokens: Sequence[int]
                              ) -> Tuple[List[int],
                                         List[Dict[str, np.ndarray]], int]:
        """Peer-pull export (PR 17): the longest cached full-block
        prefix of ``tokens`` this pool holds — device trie match,
        extended through consecutively host-cached blocks — gathered
        into the int8+scales wire layout WITHOUT touching any slot.
        -> ``(covered_tokens, per-layer wire arrays, payload bytes)``;
        zero coverage returns ``([], [], 0)`` (a legal empty wire —
        digests are advisory, a stale one costs one wasted probe).
        Read-only like :meth:`export_block_payload`: refs, trie and
        host tier are untouched; the source gives up nothing."""
        toks = [int(t) for t in tokens]
        bs = self.block_size
        blocks: List[int] = []
        if self.prefix_cache_enabled:
            blocks = self.trie.match(toks)
        host_entries: List[list] = []
        bi = len(blocks)
        while (bi + 1) * bs <= len(toks):
            entry = self._host_tier.get(tuple(toks[:(bi + 1) * bs]))
            if entry is None:
                break
            host_entries.append(entry)
            bi += 1
        nblocks = len(blocks) + len(host_entries)
        if nblocks == 0:
            return [], [], 0
        host: List[Dict[str, np.ndarray]] = []
        if blocks:
            idx = jnp.asarray(np.asarray(blocks, np.int32))
            if self.quantized:
                layers = _gather_blocks_quantized_jit(self.caches, idx)
            else:
                layers = _gather_quantize_blocks_jit(self.caches, idx)
            host = [{k: np.asarray(v) for k, v in layer.items()}
                    for layer in layers]
        if host_entries:
            if host:
                host = [{k: np.concatenate(
                            [layer[k]] + [e[li][k] for e in host_entries],
                            axis=0)
                         for k in layer}
                        for li, layer in enumerate(host)]
            else:
                host = [{k: np.concatenate(
                            [e[li][k] for e in host_entries], axis=0)
                         for k in host_entries[0][li]}
                        for li in range(len(host_entries[0]))]
        nbytes = sum(a.nbytes for layer in host for a in layer.values())
        return toks[:nblocks * bs], host, nbytes

    def install_block_payload(self, tokens: Sequence[int],
                              layers: List[Dict[str, np.ndarray]],
                              origin: str = "migrate") -> int:
        """Install a migrated block payload into the PREFIX CACHE:
        allocate fresh blocks (ref == 1 — the write invariant holds by
        construction, these indices are owned by nobody), scatter the
        wire data in (dequantized to the pool dtype, or verbatim into
        an int8 pool), and index the blocks in the trie keyed on
        ``tokens``' full-block prefix. The installing request then
        takes prefix-cache REFERENCES through the ordinary
        ``bind_for_prompt`` path — migration reuses the exact reuse
        machinery prefix hits already proved out. -> blocks newly
        referenced by the trie (0 when the prefix was already cached,
        the payload is empty, or the prefix cache is disabled — the
        request simply prefills cold). Raises
        :class:`KVBlocksExhausted` (typed, retryable — nothing is
        leaked) when the pool cannot hold the span, and ``ValueError``
        on a payload whose geometry does not match this pool.

        ``origin="peer"`` (PR 17 fleet pull) tags the newly indexed
        blocks so their first reuse is counted as a fleet "peer" hit;
        ``"migrate"`` (the PR 11 two-phase handoff) leaves the tier
        accounting untouched."""
        nblocks = int(layers[0]["k"].shape[0]) if layers else 0
        if nblocks == 0 or not self.prefix_cache_enabled:
            return 0
        bs = self.block_size
        if len(tokens) < nblocks * bs:
            raise ValueError(
                f"payload carries {nblocks} block(s) but only "
                f"{len(tokens)} token(s) key them "
                f"(block_size {bs})")
        shape = tuple(self.caches[0]["k"].shape[1:])
        got = tuple(layers[0]["k"].shape[1:])
        if len(layers) != len(self.caches) or got != shape:
            raise ValueError(
                f"payload geometry mismatch: {len(layers)} layer(s) of "
                f"blocks shaped {got}, pool has {len(self.caches)} "
                f"layer(s) shaped {shape}")
        blocks: List[int] = []
        try:
            for _ in range(nblocks):
                blocks.append(self._alloc_block(None))
        except KVBlocksExhausted:
            for b in blocks:
                self._release(b)
            raise
        idx = jnp.asarray(np.asarray(blocks, np.int32))
        payload = [{k: jnp.asarray(v) for k, v in layer.items()}
                   for layer in layers]
        if self.quantized:
            self.caches = _scatter_blocks_quantized_jit(
                self.caches, idx, payload)
        else:
            self.caches = _scatter_blocks_dequant_jit(
                self.caches, idx, payload)

        new_blocks: List[int] = []

        def take_ref(block: int) -> None:
            self._refs[block] += 1
            new_blocks.append(block)

        inserted = self.trie.insert(
            list(int(t) for t in tokens)[:nblocks * bs], blocks, take_ref)
        if origin == "peer":
            self._peer_blocks.update(new_blocks)
        # Drop our allocation refs: blocks the trie took stay cached at
        # ref 1 (the trie's); blocks it already had under the same
        # token path return to the free list (first writer won).
        for b in blocks:
            self._release(b)
        return inserted

    # ------------------------------------------------------- accounting
    def clear_prefix_cache(self) -> int:
        """Drop every cached block (knob flips / tests). -> count."""
        return self.trie.clear(self._release)

    def leak_check(self) -> None:
        """Assert the ref-count books balance: every non-free block is
        explained by slot bindings + trie nodes, and freeing everything
        would empty the pool. Chaos tests call this after drain.

        Quantized pools additionally assert the scale buffers kept
        their block-indexed shape: a block and its scale row share one
        index into the same pytree, which is what makes "COW carries
        scales" and "eviction frees scales" true by construction — a
        shape drift here would mean some path rebuilt the caches tree
        without them."""
        if self.quantized:
            for li, layer in enumerate(self.caches):
                for kv in ("k", "v"):
                    if jnp.dtype(layer[kv].dtype) != jnp.int8:
                        raise AssertionError(
                            f"layer {li} {kv} pool dtype drifted to "
                            f"{layer[kv].dtype} (expected int8)")
                    sc = layer.get(f"{kv}_scale")
                    if sc is None or tuple(sc.shape) != (
                            self.num_blocks, layer[kv].shape[1]):
                        raise AssertionError(
                            f"layer {li} {kv}_scale buffer missing or "
                            f"mis-shaped: "
                            f"{None if sc is None else sc.shape} "
                            f"(expected [{self.num_blocks}, "
                            f"{layer[kv].shape[1]}])")
        # Host-tier column of the oracle: entry count within budget,
        # byte books balanced, every entry shaped like this pool's
        # blocks and keyed by a whole number of full blocks. A drift
        # here means a demote/promote path moved payloads without
        # moving the accounting — the host-side twin of a ref leak.
        if self.host_blocks or self._host_tier:
            if len(self._host_tier) > self.host_blocks:
                raise AssertionError(
                    f"host tier holds {len(self._host_tier)} entries, "
                    f"budget {self.host_blocks} — the LRU cap leaked")
            nbytes = sum(self._entry_bytes(e)
                         for e in self._host_tier.values())
            if nbytes != self._host_bytes:
                raise AssertionError(
                    f"host tier byte books off: {self._host_bytes} "
                    f"recorded, {nbytes} resident")
            shape = tuple(self.caches[0]["k"].shape[1:])
            for key, entry in self._host_tier.items():
                if len(key) % self.block_size or \
                        len(key) // self.block_size == 0:
                    raise AssertionError(
                        f"host tier key length {len(key)} is not a "
                        f"whole number of blocks (bs {self.block_size})")
                if (len(entry) != len(self.caches)
                        or tuple(entry[0]["k"].shape) != (1,) + shape):
                    raise AssertionError(
                        f"host tier entry geometry drifted: "
                        f"{len(entry)} layer(s) shaped "
                        f"{tuple(entry[0]['k'].shape)}, pool has "
                        f"{len(self.caches)} layer(s) of [1, "
                        f"{', '.join(str(s) for s in shape)}] blocks")
        expect = np.zeros((self.num_blocks,), np.int64)
        for slot in range(self.capacity):
            if slot in self._free_slots:
                continue
            for i in range(int(self._bound[slot])):
                expect[self.tables_host[slot, i]] += 1
        for b in self.trie.blocks:
            expect[b] += 1
        expect[0] = 0
        if not np.array_equal(expect, self._refs):
            bad = np.flatnonzero(expect != self._refs)
            raise AssertionError(
                f"KV block ref-count leak at blocks {bad.tolist()}: "
                f"expected {expect[bad].tolist()}, "
                f"recorded {self._refs[bad].tolist()}")
        # Fleet peer tags (PR 17) may only name blocks somebody still
        # holds: a tag on a freed block would mis-count an unrelated
        # future binding as a peer hit.
        untagged = [b for b in self._peer_blocks if self._refs[b] <= 0]
        if untagged:
            raise AssertionError(
                f"peer tier tags leaked past release: blocks "
                f"{sorted(untagged)} are tagged but free")
        n_free = len(self._free_blocks)
        n_held = int(np.count_nonzero(self._refs))
        if n_free + n_held != self.num_blocks - 1:
            raise AssertionError(
                f"KV block leak: {n_free} free + {n_held} held != "
                f"{self.num_blocks - 1} allocatable")
        if self.mirror is not None:
            # Draft-pool extension of the oracle: the mirror's slot
            # free-list must agree with ours slot for slot (lifecycle
            # lockstep), and its own block books must balance too.
            if sorted(self.mirror._free_slots) != sorted(self._free_slots):
                raise AssertionError(
                    f"draft pool slot drift: mirror free "
                    f"{sorted(self.mirror._free_slots)} != "
                    f"{sorted(self._free_slots)}")
            self.mirror.leak_check()
