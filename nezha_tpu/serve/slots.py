"""Fixed-capacity KV slot pool.

The pool owns the serving layer's only large buffers: per-layer K/V
caches shaped ``[B_max, H, L_max, D]`` (the same layout
``models/generate.init_cache`` builds, with the batch dim reinterpreted
as SLOTS). A slot is one in-flight request's cache rows; slots are
allocated host-side (plain free list — allocation must not touch the
device) and their contents are written device-side:

- prefill writes a request's prompt K/V into its slot's rows via
  ``lax.dynamic_update_slice`` at ``(slot, 0, 0, 0)`` (engine.py builds
  the jitted program; :func:`write_slot` is the update it uses),
- decode steps append one position per ACTIVE row via the model's
  per-row-position cache path (models/gpt2.py).

Freeing a slot is bookkeeping only — stale K/V stays in the buffers.
That is safe by construction: a new occupant's prefill overwrites rows
``[0, P_max)``, and its decode mask only ever attends positions
``<= pos``, each of which the request itself has written first (prefill
pads beyond the prompt are likewise never attended: the first decode
write lands at ``pos = prompt_len`` before the mask reaches it).
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
from jax import lax


class SlotPool:
    """Host-side slot bookkeeping + the pooled device cache buffers.

    ``caches`` is the per-layer list of ``{"k", "v"}`` dicts the model's
    cache path consumes. The pool hands out slot INDICES; the engine
    threads the cache pytree through its jitted programs (functional
    updates — the pool re-binds ``caches`` to each program's output).
    """

    def __init__(self, model, capacity: int, max_len: int,
                 dtype=jnp.bfloat16):
        from nezha_tpu.models.generate import init_cache
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if max_len < 1:
            raise ValueError(f"max_len must be >= 1, got {max_len}")
        self.capacity = capacity
        self.max_len = max_len
        self.dtype = dtype
        self.caches = init_cache(model, capacity, max_len, dtype)
        # LIFO free list: the most-recently-freed slot is re-used first,
        # keeping the active rows clustered low (cheap occupancy reads).
        self._free: List[int] = list(range(capacity - 1, -1, -1))

    # ----------------------------------------------------------- alloc
    def alloc(self) -> Optional[int]:
        """-> a free slot index, or None when the pool is fully occupied."""
        return self._free.pop() if self._free else None

    def free(self, slot: int) -> None:
        if not 0 <= slot < self.capacity:
            raise ValueError(f"slot {slot} out of range [0, {self.capacity})")
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free (double free)")
        self._free.append(slot)

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_active(self) -> int:
        return self.capacity - len(self._free)

    @property
    def occupancy(self) -> float:
        """Active fraction in [0, 1] — the batch-occupancy gauge value."""
        return self.num_active / self.capacity


def write_slot(pool_leaf, chunk_leaf, slot):
    """Write one request's prefill rows into a slot of a pooled cache
    leaf: ``pool_leaf [B_max, H, L_max, D]``, ``chunk_leaf [1, H, P, D]``
    (P <= L_max), ``slot`` a traced int32 scalar. Pure — returns the
    updated leaf; call under jit (engine prefill program)."""
    zero = jnp.zeros((), jnp.int32)
    return lax.dynamic_update_slice(
        pool_leaf, chunk_leaf.astype(pool_leaf.dtype),
        (slot, zero, zero, zero))
