"""Fault-tolerant paged-block migration between serving replicas.

The disaggregated prefill/decode topology (serve/router.py roles) moves
a finished prompt's KV from the prefill replica that computed it to the
decode replica that will stream from it. This module is the wire: the
int8+scales block payload (ops/quant.py — the EQuARX recipe the wire
collectives already use, ~4x fewer bytes than shipping bf16), the HTTP
pull client the decode side runs, and the ``/kv_export`` / ``/kv_ack``
handler bodies both replica front ends (``cli/serve.run_http`` and the
supervisor's thread worker) mount.

The protocol is PULL-BASED and TWO-PHASE, designed so a crash at any
point leaves exactly one owner of the request — or a typed, retryable
failure — never a leak and never a double-free:

1. **park** — the router admits the request onto a prefill replica with
   ``prefill_only``; the scheduler prefills the prompt and PARKS the
   slot (blocks held, refs untouched) under a TTL instead of decoding.
2. **pull** — the decode replica (handed ``pull_from`` by the router)
   POSTs ``/kv_export`` to the source: the source exports the parked
   prompt's full-block prefix through the wire format — a read-only
   gather; source refs are NOT released.
3. **install** — the decode replica allocates fresh blocks (ref == 1,
   the write invariant by construction), scatters the payload in, and
   indexes the blocks in its own prefix trie. The request it then
   submits locally takes cache references through ``bind_for_prompt``
   and prefills only the uncached tail — migration reuses the exact
   shared-prefix machinery PR 7 proved out, including copy-on-write.
4. **ACK** — only now does the decode side POST ``/kv_ack``; the source
   frees the parked slot and its refs. A lost ACK (or a decode replica
   that died after pulling) is absorbed by the park TTL: the source
   reclaims the blocks itself, so at worst the prompt's KV briefly
   exists twice — the REQUEST is still decoded exactly once, by
   whoever holds it.

Failure is typed end to end: any pull/install failure raises
:class:`MigrationError`, which the replica front end answers as HTTP
424 (``error_type: "migration_failed"``) — the router's signal to retry
the migration against another decode replica, fall back to local decode
on the source (``resume``), or re-run the whole request. Chaos enters
through the pinned fault points ``replica.kv_export`` and
``replica.kv_install`` (scheduler-side) and ``router.migrate``
(router-side); ``serve.kv.migrations_total`` / ``serve.kv.
migration_bytes`` count committed installs (schema-pinned).

The same wire carries the fleet PEER PULL (PR 17, serve/fleetcache):
``/kv_export`` in **tokens mode** exports the longest cached
full-block prefix of an arbitrary prompt straight out of the source's
prefix trie + host tier — no park, no ACK, read-only on the source —
and :func:`pull_prefix_into` installs it on the destination tagged
``origin="peer"``. Peer-pull failure is ``kind="kv_pull_failed"`` and
the front ends degrade to a cold prefill instead of answering 424:
unlike a migration (which owns the request), a peer pull is a cache
optimization the request never depends on.

The wire is MESH-BLIND (tensor-sharded serving, serve/sharded): a
source running a head-sharded pool exports via GATHER-ON-EXPORT — the
pool's block gather converts to host arrays, which assembles the
full-head payload from the M shards — and an install scatters into
whatever mesh the destination runs, so parked prompts migrate between
replicas of ANY mesh sizes without a protocol change. A per-shard pull
(M parallel transfers, no gather) is the noted follow-up when transfer
bandwidth, not protocol simplicity, becomes the bottleneck.
"""

from __future__ import annotations

import base64
import http.client
import json
import time
from typing import Dict, List, Sequence, Tuple

import numpy as np

from nezha_tpu import faults, obs
from nezha_tpu.serve.slots import KVBlocksExhausted

WIRE_VERSION = 1

# Wire dtypes per payload key — the int8+scales block layout.
_WIRE_DTYPES = {"k": np.int8, "v": np.int8,
                "k_scale": np.float32, "v_scale": np.float32}


class MigrationError(RuntimeError):
    """Typed migration failure (source gone, payload mismatch, pool
    exhausted, injected fault). The replica front end answers it as
    HTTP 424 with ``error_type = kind`` — ``"migration_failed"``
    (retryable: the router tries another decode member, then the
    local-decode fallback) or ``"park_lost"`` (the source answered
    but no longer holds the park — TTL expired, drained, or already
    ACKed to a puller that then died: every further pull or resume is
    doomed, so the router restarts from prefill immediately). Never a
    silent drop and never a crash of the decode loop."""

    def __init__(self, msg: str, kind: str = "migration_failed"):
        super().__init__(msg)
        self.kind = kind


# ------------------------------------------------------------ wire codec
def encode_wire(tokens: Sequence[int],
                layers: List[Dict[str, np.ndarray]],
                block_size: int) -> dict:
    """Block payload -> JSON-safe wire object (arrays as base64 of raw
    bytes + explicit geometry, so the installer can validate before it
    touches its pool)."""

    def b64(a: np.ndarray) -> str:
        return base64.b64encode(
            np.ascontiguousarray(a).tobytes()).decode("ascii")

    nbytes = sum(a.nbytes for layer in layers for a in layer.values())
    if layers:
        n, heads, bs, d = layers[0]["k"].shape
    else:
        n, heads, bs, d = 0, 0, block_size, 0
    return {"v": WIRE_VERSION,
            "tokens": [int(t) for t in tokens],
            "block_size": int(block_size), "nblocks": int(n),
            "heads": int(heads), "head_dim": int(d),
            "num_layers": len(layers), "nbytes": int(nbytes),
            "layers": [{k: b64(layer[k]) for k in _WIRE_DTYPES}
                       for layer in layers]}


def decode_wire(obj: dict) -> Tuple[List[int],
                                    List[Dict[str, np.ndarray]], int]:
    """Wire object -> (tokens, per-layer host arrays, payload bytes).
    Raises :class:`MigrationError` on anything malformed — a corrupt
    payload must fail typed BEFORE any pool state is touched."""
    try:
        if obj.get("v") != WIRE_VERSION:
            raise ValueError(f"wire version {obj.get('v')!r} != "
                             f"{WIRE_VERSION}")
        tokens = [int(t) for t in obj["tokens"]]
        n, heads = int(obj["nblocks"]), int(obj["heads"])
        bs, d = int(obj["block_size"]), int(obj["head_dim"])
        layers: List[Dict[str, np.ndarray]] = []
        for entry in obj["layers"]:
            layer = {}
            for key, dtype in _WIRE_DTYPES.items():
                raw = base64.b64decode(entry[key])
                shape = ((n, heads, bs, d) if dtype == np.int8
                         else (n, heads))
                arr = np.frombuffer(raw, dtype=dtype)
                if arr.size != int(np.prod(shape)):
                    raise ValueError(
                        f"payload {key!r} carries {arr.size} elements, "
                        f"geometry says {shape}")
                layer[key] = arr.reshape(shape)
            layers.append(layer)
        if len(layers) != int(obj["num_layers"]):
            raise ValueError(f"{len(layers)} layer(s) decoded, header "
                             f"says {obj['num_layers']}")
        return tokens, layers, int(obj["nbytes"])
    except MigrationError:
        raise
    except Exception as e:
        raise MigrationError(
            f"malformed migration payload: {type(e).__name__}: {e}")


# -------------------------------------------------------- handler bodies
def _handle_prefix_export(scheduler, obj) -> Tuple[int, dict]:
    """``/kv_export`` TOKENS mode (fleet peer pull, PR 17): export the
    longest cached full-block prefix of the given tokens — a read-only
    cache probe with no park, no request and no ACK. Zero coverage is
    a 200 with an empty wire (digests are advisory; a stale entry
    costs the puller one wasted probe, never an error)."""
    tokens = obj.get("tokens")
    if not isinstance(tokens, list) or \
            not all(isinstance(t, int) for t in tokens):
        return 400, {"error": "tokens (list of ints) required",
                     "error_type": "bad_request"}
    try:
        wire = scheduler.export_prefix(tokens)
    except faults.InjectedFault as e:
        return 500, {"error": str(e), "error_type": "injected_fault"}
    except MigrationError as e:
        return 409, {"error": str(e), "error_type": e.kind}
    return 200, wire


def handle_kv_export(scheduler, obj) -> Tuple[int, dict]:
    """POST ``/kv_export`` body: the source side of the pull. Two
    modes share the endpoint (and therefore the wire format):
    ``request_id`` pulls a PARKED request's prefix (the PR 11
    two-phase migration — refs released only by ``/kv_ack``), while
    ``tokens`` probes the prefix CACHE (the PR 17 fleet peer pull —
    read-only, nothing to ACK). Every failure is typed."""
    if isinstance(obj, dict) and "request_id" not in obj \
            and "tokens" in obj:
        return _handle_prefix_export(scheduler, obj)
    rid = obj.get("request_id") if isinstance(obj, dict) else None
    if not isinstance(rid, str) or not rid:
        return 400, {"error": "request_id (string) required",
                     "error_type": "bad_request"}
    try:
        wire = scheduler.export_parked(rid)
    except KeyError:
        return 404, {"error": f"request {rid!r} is not parked here",
                     "error_type": "migration_failed"}
    except faults.InjectedFault as e:
        return 500, {"error": str(e), "error_type": "injected_fault"}
    except MigrationError as e:
        return 409, {"error": str(e), "error_type": "migration_failed"}
    return 200, wire


def handle_kv_ack(scheduler, obj) -> Tuple[int, dict]:
    """POST ``/kv_ack`` body: the COMMIT of the two-phase handoff — the
    decode side holds its own copy, so the source releases the parked
    slot and its block refs. Idempotent: acking an already-released
    (or TTL-expired) park answers ``released: false`` rather than
    erroring, so a duplicate ACK can never double-free."""
    rid = obj.get("request_id") if isinstance(obj, dict) else None
    if not isinstance(rid, str) or not rid:
        return 400, {"error": "request_id (string) required",
                     "error_type": "bad_request"}
    return 200, {"id": rid, "released": scheduler.ack_parked(rid)}


def dispatch_kv_endpoint(scheduler, path: str,
                         raw_body: bytes) -> Tuple[int, dict]:
    """One shared body-parse + route for the migration endpoints —
    both replica front ends (``cli/serve.run_http`` and the
    supervisor's thread worker) mount ``/kv_export`` / ``/kv_ack``
    through this, so the wire protocol cannot drift between them."""
    try:
        obj = json.loads(raw_body)
    except ValueError as e:
        return 400, {"error": str(e)}
    handler = (handle_kv_export if path == "/kv_export"
               else handle_kv_ack)
    return handler(scheduler, obj)


# ---------------------------------------------------------- pull client
def _post_json(host: str, port: int, path: str, obj: dict,
               timeout_s: float) -> Tuple[int, dict]:
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request("POST", path, body=json.dumps(obj).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        raw = resp.read()
        try:
            return resp.status, json.loads(raw)
        except ValueError:
            return resp.status, {"error": "non-JSON response"}
    finally:
        conn.close()


def pull_into(scheduler, pull: dict, timeout_s: float = 120.0) -> dict:
    """The decode side's whole migration: pull the span from the source
    named by ``pull`` (``{"port": ..., "request_id": ...}``), install it
    into this replica's pool + prefix trie, then ACK the source. ->
    meta ``{"bytes", "blocks", "installed", "seconds", "acked"}`` for
    the response's ``migration`` block (the bench's GB/s numerator).
    Raises :class:`MigrationError` on any failure — by the install
    invariants nothing is leaked on either side (the source still owns
    its parked blocks until the ACK; a failed install released every
    block it allocated)."""
    if not isinstance(pull, dict):
        raise MigrationError("pull_from must be an object")
    try:
        port = int(pull["port"])
        rid = str(pull["request_id"])
    except (KeyError, TypeError, ValueError):
        raise MigrationError(
            "pull_from requires integer 'port' and string 'request_id'")
    host = str(pull.get("host", "127.0.0.1"))
    # The pull reference carries the request's trace id (the router put
    # it there): the whole transfer hop — export POST, install, ACK —
    # is ONE serve.kv_install fragment of the stitched timeline (the
    # "migration transfer" segment of the TTFT decomposition), and the
    # id is forwarded to the source on both kv endpoints so its export
    # fragment cross-references. Untraced pulls record nothing.
    tid = pull.get("trace_id")
    kv_body = {"request_id": rid}
    if tid:
        kv_body["trace_id"] = tid
    with obs.trace_context(tid):
        with obs.traced_span("serve.kv_install", request_id=rid) as sp:
            t0 = time.monotonic()
            try:
                status, wire = _post_json(host, port, "/kv_export",
                                          kv_body, timeout_s)
            except Exception as e:
                raise MigrationError(f"kv_export pull from {host}:{port} "
                                     f"failed: {type(e).__name__}: {e}")
            if status != 200:
                raise MigrationError(
                    f"kv_export from {host}:{port} answered {status}: "
                    f"{wire.get('error') if isinstance(wire, dict) else wire}",
                    # A live source answering 404 means the park itself
                    # is gone (TTL / drain / already committed
                    # elsewhere) — no other decode member's pull can
                    # succeed either.
                    kind="park_lost" if status == 404
                    else "migration_failed")
            tokens, layers, nbytes = decode_wire(wire)
            try:
                installed = scheduler.install_migrated(tokens, layers,
                                                       nbytes)
            except faults.InjectedFault as e:
                raise MigrationError(f"kv_install injected fault: {e}")
            except KVBlocksExhausted as e:
                raise MigrationError(
                    f"kv_install found no free blocks: {e}")
            except ValueError as e:
                raise MigrationError(
                    f"kv_install rejected the payload: {e}")
            # COMMIT: the copy is ours — release the source.
            # Best-effort: a lost ACK costs the source nothing but its
            # park TTL (it reclaims the blocks itself); the request is
            # already safe here.
            try:
                status, _ = _post_json(host, port, "/kv_ack", kv_body,
                                       timeout_s)
                acked = status == 200
            except Exception:
                acked = False
            nblocks = int(layers[0]["k"].shape[0]) if layers else 0
            sp.set(bytes=nbytes, blocks=nblocks, acked=acked)
            return {"bytes": nbytes, "blocks": nblocks,
                    "installed": installed,
                    "seconds": time.monotonic() - t0, "acked": acked}


def pull_prefix_into(scheduler, pull: dict,
                     timeout_s: float = 30.0) -> dict:
    """The destination side of a fleet PEER pull (PR 17): fetch the
    covering prefix blocks named by ``pull`` (``{"host", "port",
    "tokens"}`` — the router's near-miss hint) from the sibling
    replica's cache over ``/kv_export`` tokens mode, and install them
    into this pool's prefix trie tagged ``origin="peer"``. One-phase
    and read-only on the source: there is no park and no ACK — the
    source keeps its copy, the destination gains one. -> meta
    ``{"bytes", "blocks", "installed", "seconds"}`` for the response's
    ``fleet_pull`` block. Raises :class:`MigrationError` with
    ``kind="kv_pull_failed"`` on ANY failure (injected fault, source
    dead mid-transfer, malformed payload, pool exhausted) — the
    caller's contract is to degrade to a cold prefill, never to error
    the request: a peer pull is an optimization, not a dependency.
    ``replica.kv_pull`` is the pinned chaos knob, armed at entry so an
    injected delay stretches the transfer window the mid-pull SIGKILL
    drill kills the source inside."""
    if not isinstance(pull, dict):
        raise MigrationError("pull_from must be an object",
                             kind="kv_pull_failed")
    try:
        port = int(pull["port"])
        tokens = [int(t) for t in pull["tokens"]]
    except (KeyError, TypeError, ValueError):
        raise MigrationError(
            "peer pull_from requires integer 'port' and a token list",
            kind="kv_pull_failed")
    host = str(pull.get("host", "127.0.0.1"))
    tid = pull.get("trace_id")
    body = {"tokens": tokens}
    if tid:
        body["trace_id"] = tid
    t0 = time.monotonic()
    try:
        faults.point("replica.kv_pull")
    except faults.InjectedFault as e:
        raise MigrationError(f"kv_pull injected fault: {e}",
                             kind="kv_pull_failed")
    try:
        status, wire = _post_json(host, port, "/kv_export", body,
                                  timeout_s)
    except Exception as e:
        raise MigrationError(
            f"peer kv_export from {host}:{port} failed: "
            f"{type(e).__name__}: {e}", kind="kv_pull_failed")
    if status != 200:
        raise MigrationError(
            f"peer kv_export from {host}:{port} answered {status}: "
            f"{wire.get('error') if isinstance(wire, dict) else wire}",
            kind="kv_pull_failed")
    try:
        tokens_out, layers, nbytes = decode_wire(wire)
        installed = scheduler.install_pulled(tokens_out, layers, nbytes)
    except MigrationError as e:
        raise MigrationError(str(e), kind="kv_pull_failed")
    except faults.InjectedFault as e:
        raise MigrationError(f"kv_pull install injected fault: {e}",
                             kind="kv_pull_failed")
    except KVBlocksExhausted as e:
        raise MigrationError(
            f"kv_pull install found no free blocks: {e}",
            kind="kv_pull_failed")
    except ValueError as e:
        raise MigrationError(
            f"kv_pull install rejected the payload: {e}",
            kind="kv_pull_failed")
    nblocks = int(layers[0]["k"].shape[0]) if layers else 0
    return {"bytes": nbytes, "blocks": nblocks, "installed": installed,
            "seconds": time.monotonic() - t0}
