"""Mixed-precision dtype policies.

Reference parity: nezha's bf16 compute / fp32 master-weight path exercised by
the GPT-2 and Wide-ResNet-101 benchmark configs (SURVEY.md §2 "mixed
precision"). TPU-first design: parameters live in fp32 (master copy), compute
runs in bf16 so matmuls/convs hit the MXU at full rate, and reductions /
normalization statistics stay in fp32 for numerical safety. bf16 on TPU needs
no loss scaling (8-bit exponent), unlike fp16; a dynamic loss-scale is still
provided in `nezha_tpu.train.mixed_precision` for parity.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Policy:
    """What dtype each class of value uses.

    - ``param_dtype``: storage dtype of trainable parameters (master copy).
    - ``compute_dtype``: dtype activations and weights are cast to for the
      forward/backward math (bf16 keeps the MXU at full throughput).
    - ``output_dtype``: dtype of layer outputs (normally compute dtype).
    """

    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    output_dtype: Any = None  # None -> same as compute_dtype

    def cast_to_compute(self, x):
        return jnp.asarray(x, self.compute_dtype)

    def cast_to_param(self, x):
        return jnp.asarray(x, self.param_dtype)

    def cast_output(self, x):
        out = self.output_dtype or self.compute_dtype
        return jnp.asarray(x, out)


def f32_policy() -> Policy:
    return Policy(jnp.float32, jnp.float32)


def bf16_policy() -> Policy:
    """fp32 master params, bf16 compute — the standard TPU training policy."""
    return Policy(jnp.float32, jnp.bfloat16)


DEFAULT_POLICY = f32_policy()
