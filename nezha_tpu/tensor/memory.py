"""Host<->device transfer and device-memory introspection.

Reference parity: `pkg/tensor`'s device allocator + H2D/D2H copies
(SURVEY.md §2). On TPU, allocation is XLA/PJRT's job; the framework-level
concerns that remain are explicit placement (with shardings), transfer, and
accounting.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np


def to_device(tree: Any, sharding: Optional[jax.sharding.Sharding] = None) -> Any:
    """Move a pytree of host arrays onto device(s).

    With a ``sharding``, arrays land already laid out across the mesh so no
    resharding copy happens inside the first jit'd step.
    """
    if sharding is None:
        return jax.device_put(tree)
    return jax.device_put(tree, sharding)


def to_host(tree: Any) -> Any:
    """Fetch a pytree of device arrays back to host numpy (blocking)."""
    return jax.tree_util.tree_map(np.asarray, tree)


def tree_bytes(tree: Any) -> int:
    """Total bytes of all array leaves in a pytree."""
    sizes = [
        x.size * x.dtype.itemsize
        for x in jax.tree_util.tree_leaves(tree)
        if hasattr(x, "dtype")
    ]
    return int(sum(sizes))


def device_memory_stats(device: Optional[jax.Device] = None) -> dict:
    """Per-device memory stats when the backend exposes them (TPU does).

    Defaults to the first LOCAL device: in a multi-process job,
    ``jax.devices()[0]`` is host 0's device, whose stats a non-primary host
    can't read — each host reports its own HBM."""
    dev = device or jax.local_devices()[0]
    try:
        stats = dev.memory_stats()
    except Exception:  # CPU backend has none
        stats = None
    return stats or {}


def memory_metrics(device: Optional[jax.Device] = None) -> dict:
    """The two live/peak HBM numbers worth logging every step, with stable
    metric names (empty off-TPU — the CPU backend exposes no stats)."""
    stats = device_memory_stats(device)
    out = {}
    if "bytes_in_use" in stats:
        out["hbm_bytes_in_use"] = int(stats["bytes_in_use"])
    if "peak_bytes_in_use" in stats:
        out["hbm_peak_bytes"] = int(stats["peak_bytes_in_use"])
    return out
