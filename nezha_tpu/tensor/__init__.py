"""Tensor & memory layer.

TPU-native equivalent of the reference's `pkg/tensor` (SURVEY.md §1: tensor
type, device allocator, host<->device copies, fp32/bf16 dtypes). On TPU the
device allocator is XLA/PJRT's — `jax.Array` IS the device buffer — so this
layer provides what remains framework-level: dtype policies for mixed
precision, explicit host<->device transfer helpers, buffer donation helpers,
and device/memory introspection.
"""

from nezha_tpu.tensor.policy import Policy, DEFAULT_POLICY, bf16_policy, f32_policy
from nezha_tpu.tensor.memory import (
    to_device,
    to_host,
    device_memory_stats,
    memory_metrics,
    tree_bytes,
)

__all__ = [
    "Policy",
    "DEFAULT_POLICY",
    "bf16_policy",
    "f32_policy",
    "to_device",
    "to_host",
    "device_memory_stats",
    "memory_metrics",
    "tree_bytes",
]
