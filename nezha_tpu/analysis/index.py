"""The shared AST/source index every lint rule runs over.

Before this package existed, each of the three ``tools/check_*.py``
validators walked the source tree on its own — three ``os.walk`` loops,
three regex dialects, zero shared parsing. The index is the one walk:
every ``.py`` file under the configured roots is read ONCE and parsed
ONCE (``ast.parse``), with parent back-links attached so rules can ask
"what function/class encloses this node" without re-deriving it. Rules
receive the index and never touch the filesystem themselves (non-Python
artifacts — RUNBOOK tables, committed BENCH records — go through
:meth:`SourceIndex.read_text`, which caches too).

Stdlib-only by design: the analysis subpackage itself never imports
jax (the ``tools/check_*.py`` shims exploit this with a namespace stub
to stay runnable on jaxless boxes — the ``nezha-lint`` console script
lives in ``nezha_tpu.cli`` and does import the package), and the whole
tree (~140 files) indexes in well under a second.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterator, List, Optional, Tuple

# What `nezha-lint` covers by default: the package, the checker shims,
# and the benchmark drivers. tests/ is deliberately NOT indexed (rules
# lint product source; the fault-points rule reads tests as text via
# read_text to verify coverage).
DEFAULT_ROOTS: Tuple[str, ...] = ("nezha_tpu", "tools", "benchmarks")
DEFAULT_EXTRA_FILES: Tuple[str, ...] = ("bench.py",)


@dataclasses.dataclass
class Module:
    """One parsed source file."""

    rel: str                  # repo-relative posix path (stable in keys)
    path: str                 # absolute path
    text: str
    tree: ast.Module
    parents: Dict[ast.AST, ast.AST]   # child node -> parent node


def _attach_parents(tree: ast.Module) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, None for anything dynamic
    (calls, subscripts) — rules match call targets by this string."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    """Dotted name of a call's target (``obs.counter``, ``self.executor.
    run``), None when the callee is itself computed."""
    return dotted_name(call.func)


def str_arg(call: ast.Call, pos: int = 0) -> Optional[str]:
    """The literal string at positional arg ``pos``, None when absent or
    non-literal (f-strings and variables are skipped, never guessed)."""
    if len(call.args) > pos:
        a = call.args[pos]
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return a.value
    return None


class SourceIndex:
    """Parsed view of the repo for one lint run.

    ``parse_errors`` holds ``(rel, message)`` for files that failed to
    parse — the runner turns those into findings (a tree that does not
    parse must fail the lint, not silently shrink its coverage).
    """

    def __init__(self, root: str,
                 roots: Tuple[str, ...] = DEFAULT_ROOTS,
                 extra_files: Tuple[str, ...] = DEFAULT_EXTRA_FILES):
        self.root = os.path.abspath(root)
        self.modules: Dict[str, Module] = {}
        self.parse_errors: List[Tuple[str, str]] = []
        self._text_cache: Dict[str, Optional[str]] = {}
        paths: List[str] = []
        for sub in roots:
            base = os.path.join(self.root, sub)
            for dirpath, dirnames, files in os.walk(base):
                dirnames.sort()
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        paths.append(os.path.join(dirpath, fn))
        for extra in extra_files:
            p = os.path.join(self.root, extra)
            if os.path.isfile(p):
                paths.append(p)
        for path in paths:
            rel = os.path.relpath(path, self.root).replace(os.sep, "/")
            try:
                with open(path, encoding="utf-8") as f:
                    text = f.read()
                tree = ast.parse(text, filename=rel)
            except (OSError, SyntaxError, ValueError) as e:
                self.parse_errors.append((rel, f"{type(e).__name__}: {e}"))
                continue
            self.modules[rel] = Module(
                rel=rel, path=path, text=text, tree=tree,
                parents=_attach_parents(tree))

    def __iter__(self) -> Iterator[Module]:
        for rel in sorted(self.modules):
            yield self.modules[rel]

    def read_text(self, rel: str) -> Optional[str]:
        """Text of any repo file (RUNBOOK, tests, JSON records), cached;
        None when absent."""
        if rel not in self._text_cache:
            try:
                with open(os.path.join(self.root, rel),
                          encoding="utf-8") as f:
                    self._text_cache[rel] = f.read()
            except OSError:
                self._text_cache[rel] = None
        return self._text_cache[rel]

    # ----------------------------------------------------- AST helpers
    def enclosing(self, mod: Module, node: ast.AST,
                  kinds: tuple) -> Optional[ast.AST]:
        cur = mod.parents.get(node)
        while cur is not None:
            if isinstance(cur, kinds):
                return cur
            cur = mod.parents.get(cur)
        return None

    def qualname(self, mod: Module, node: ast.AST) -> str:
        """Dotted path of enclosing defs/classes (``Cls.method.inner``),
        "" at module level — the line-number-free context baseline keys
        anchor on."""
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = mod.parents.get(cur)
        return ".".join(reversed(parts))

    def functions(self, mod: Module) -> Iterator[ast.AST]:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node
