"""Which functions run under a JAX trace — the scope of the hot-path rules.

``host-sync-in-hot-path`` and ``traced-value-branch`` only make sense
inside function bodies that jit/pallas traces: a ``float()`` on a host
value is fine in the scheduler but a recompile (or a
``TracerBoolConversionError``) inside a step program. Static detection
is necessarily heuristic; this module errs toward the repo's actual
idioms:

1. **decorated** — ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)``
   (and the other tracing transforms in :data:`TRACING_CALLS`);
2. **handed to a tracer** — any local function whose NAME appears inside
   the arguments of a ``jax.jit(...)`` / ``lax.scan(...)`` /
   ``pl.pallas_call(...)`` / ``lax.while_loop`` / ... call, including
   through ``functools.partial`` nesting (how the Pallas kernels are
   bound);
3. **builder convention** — an inner ``def`` returned by an enclosing
   ``_build_*`` function (the serve engine's program builders: the
   returned closures are dispatched through the donating Executor and
   jitted there — ``serve/engine.py`` step/prefill);
4. **transitive** — a function referenced by name from the body of any
   traced function in the same module (the ``core``/``body`` helpers the
   builders share, the ``_block_step`` math the kernel variants share).

Cross-module tracedness (a model method called from a traced program in
another file) is out of scope: resolving it statically would need whole-
program type inference, and the in-module rules already cover the paths
the contracts name (engine builders, pallas kernels).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from nezha_tpu.analysis.index import Module, dotted_name

# Call targets (matched on the LAST dotted component) whose function
# arguments get traced.
TRACING_CALLS: Set[str] = {
    "jit", "scan", "while_loop", "fori_loop", "cond", "switch",
    "pallas_call", "vmap", "pmap", "shard_map", "remat", "checkpoint",
    "grad", "value_and_grad", "custom_jvp", "custom_vjp",
    "associative_scan",
}

# Mesh collectives that only ever execute under a shard_map/pmap
# lowering: a function whose body ISSUES one is a traced body even
# when no in-module shard_map call references it by name — the ring-
# attention library helpers (parallel/ring.py, serve/sharded/
# seq_prefill.py) are handed to shard_map cross-module, so the ring
# hop loops they build would otherwise sit outside the hot-path
# rules' scope. The reason string deliberately says "shard_map" so
# the mesh-host-side-tables rule roots at these bodies too.
COLLECTIVE_CALLS: Set[str] = {"ppermute", "all_to_all", "pshuffle"}

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)


def _decorator_traces(dec: ast.AST) -> bool:
    """True for ``@jit``-family decorators, bare or partial-wrapped."""
    if isinstance(dec, ast.Call):
        name = dotted_name(dec.func) or ""
        if name.rsplit(".", 1)[-1] in TRACING_CALLS:
            return True
        if name.rsplit(".", 1)[-1] == "partial":
            return any(_decorator_traces(a) for a in
                       list(dec.args) + [k.value for k in dec.keywords])
        return False
    name = dotted_name(dec) or ""
    return name.rsplit(".", 1)[-1] in TRACING_CALLS


def traced_functions(mod: Module) -> Dict[ast.AST, str]:
    """-> {FunctionDef node: one-line reason it is considered traced}."""
    by_name: Dict[str, List[ast.AST]] = {}
    assigns: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, _FuncDef):
            by_name.setdefault(node.name, []).append(node)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    assigns.setdefault(t.id, []).append(node.value)

    traced: Dict[ast.AST, str] = {}

    def mark(fn: ast.AST, reason: str) -> None:
        if fn not in traced:
            traced[fn] = reason

    for node in ast.walk(mod.tree):
        if isinstance(node, _FuncDef):
            for dec in node.decorator_list:
                if _decorator_traces(dec):
                    mark(node, "decorated with a tracing transform")
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    last = (dotted_name(sub.func) or "").rsplit(
                        ".", 1)[-1]
                    if last in COLLECTIVE_CALLS:
                        mark(node, f"issues mesh collective {last}() "
                                   f"(shard_map-lowered body)")
                        break
        if isinstance(node, ast.Call):
            cn = dotted_name(node.func) or ""
            if cn.rsplit(".", 1)[-1] in TRACING_CALLS:
                for arg in list(node.args) + [k.value for k in
                                              node.keywords]:
                    for sub in ast.walk(arg):
                        if not isinstance(sub, ast.Name):
                            continue
                        if sub.id in by_name:
                            for fn in by_name[sub.id]:
                                mark(fn, f"passed to "
                                         f"{cn.rsplit('.', 1)[-1]}()")
                        elif sub.id in assigns:
                            # `kernel = functools.partial(_decode_
                            # kernel, ...)` then `pallas_call(kernel,
                            # ...)` — resolve one assignment hop. Only
                            # REFERENCES to a def count: in
                            # `mesh = _mesh(devs)` the def is CALLED
                            # and the variable holds its result, not
                            # the function.
                            for rhs in assigns[sub.id]:
                                callees = {id(c.func) for c in
                                           ast.walk(rhs)
                                           if isinstance(c, ast.Call)}
                                for s2 in ast.walk(rhs):
                                    if (isinstance(s2, ast.Name)
                                            and id(s2) not in callees
                                            and s2.id in by_name):
                                        for fn in by_name[s2.id]:
                                            mark(fn, f"bound to "
                                                 f"{sub.id} passed to "
                                                 f"{cn.rsplit('.', 1)[-1]}"
                                                 f"()")
        # Builder convention: `def _build_x(): def f(...): ...;
        # return f` — the returned closure is the compiled program.
        if (isinstance(node, _FuncDef)
                and node.name.startswith("_build")):
            inner = {n.name: n for n in node.body
                     if isinstance(n, _FuncDef)}
            # Inner defs may sit one level down (if/else variants).
            for stmt in ast.walk(node):
                if isinstance(stmt, _FuncDef) and stmt is not node:
                    inner.setdefault(stmt.name, stmt)
            for stmt in ast.walk(node):
                if isinstance(stmt, ast.Return) and stmt.value is not None:
                    for sub in ast.walk(stmt.value):
                        if (isinstance(sub, ast.Name)
                                and sub.id in inner):
                            mark(inner[sub.id],
                                 f"program built by {node.name}()")

    # Transitive closure: helpers called from traced bodies trace too.
    changed = True
    while changed:
        changed = False
        for fn, reason in list(traced.items()):
            for sub in ast.walk(fn):
                if (isinstance(sub, ast.Name)
                        and isinstance(sub.ctx, ast.Load)
                        and sub.id in by_name):
                    for callee in by_name[sub.id]:
                        if callee not in traced and callee is not fn:
                            traced[callee] = (f"called from traced "
                                              f"{getattr(fn, 'name', '?')}()")
                            changed = True
    return traced


# Attributes that are STATIC on a traced array — reading them yields
# Python values, so branching on them is fine (`if q.shape[0] == 1:`).
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "sharding",
                 "is_fully_replicated", "itemsize"}

# Dotted prefixes whose call results are device values.
_DEVICE_BASES = ("jnp.", "jax.numpy.", "lax.", "jax.lax.", "jax.random.",
                 "jax.nn.", "jnn.")


def _is_device_call(call: ast.Call) -> bool:
    name = dotted_name(call.func) or ""
    return name.startswith(_DEVICE_BASES) or name in ("jnp", "lax")


def only_static_use(root: ast.AST, leaf: ast.Name) -> bool:
    """True when ``leaf`` appears under a ``.shape``-style static-
    metadata access within ``root`` (so it contributes no device
    value). Shared by the taint propagation here and the branch-test
    check in rules/traced_branch.py — ONE definition of "static", so
    the two can never disagree on an attribute."""
    for sub in ast.walk(root):
        if isinstance(sub, ast.Attribute) and sub.attr in _STATIC_ATTRS:
            for inner in ast.walk(sub):
                if inner is leaf:
                    return True
    return False


def device_tainted(fn: ast.AST, *,
                   include_params: bool = True) -> Set[str]:
    """Names inside a traced function that (conservatively) hold traced
    array values: positional/vararg parameters (keyword-only params are
    excluded — the repo binds statics like ``scale``/``block_k`` through
    ``functools.partial`` keywords) plus anything assigned from a
    ``jnp.``/``lax.``/``jax.`` call or from arithmetic over already-
    tainted names. Taint does NOT flow through ``.shape``/``.dtype``-
    style static attributes."""
    tainted: Set[str] = set()
    if include_params:
        args = fn.args
        for a in list(args.posonlyargs) + list(args.args):
            tainted.add(a.arg)
        if args.vararg is not None:
            tainted.add(args.vararg.arg)

    def expr_tainted(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and _is_device_call(sub):
                return True
            if (isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
                    and sub.id in tainted
                    and not only_static_use(node, sub)):
                return True
        return False

    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AugAssign):
                targets, value = [node.target], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None or not expr_tainted(value):
                continue
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name) and sub.id not in tainted:
                        tainted.add(sub.id)
                        changed = True
    return tainted
