"""Built-in ``nezha-lint`` rules. Each module registers itself via
``@rule(name, contract)``; :func:`nezha_tpu.analysis.core.load_rules`
imports them all, and adding a rule is adding a module here plus the
RUNBOOK table row."""
