"""Rule ``mesh-host-side-tables``: pool bookkeeping never mutates
inside a ``shard_map``-lowered body.

The sharded serve engine's whole design rests on one split: KV *bytes*
live device-side, head-sharded over the mesh, while every piece of
pool *bookkeeping* — the per-slot block tables, the block free list,
ref counts, per-slot bound counts, and the prefix trie — stays
host-side, single, and layout-identical to the single-device pool
(serve/sharded/pool.py). A block-table or free-list mutation inside a
``shard_map``-lowered body would either trace-crash (host containers
inside a trace), or worse: silently fork the bookkeeping per shard, so
two devices disagree about which block a slot owns — the stale-write /
double-bind corruption the write-at-ref==1 invariant exists to make
impossible.

Scope: the ``shard_map``-lowered subset of the traced-body index the
host-sync rule already builds (:mod:`nezha_tpu.analysis.traced`) —
functions passed to ``shard_map(...)`` plus everything transitively
called from their bodies in the same module. Flagged mutations:

- assignments (plain/aug/ann, including subscript stores like
  ``self.tables_host[slot, i] = b``) whose target touches one of the
  host-state attributes;
- mutating method calls (``append``/``pop``/``insert``/``evict``/...)
  whose receiver chain touches one of them.

Reads stay legal — a shard_map body may consume an UPLOADED copy of
the tables as an operand; it may never write the host mirror.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from nezha_tpu.analysis.core import Finding, rule
from nezha_tpu.analysis.index import SourceIndex
from nezha_tpu.analysis.traced import traced_functions

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)

# The host-side pool bookkeeping state (PagedSlotPool and its sharded
# subclass). Renaming one of these fields means updating this set — the
# rule's fixture test fails otherwise.
HOST_TABLE_STATE = frozenset({
    "tables_host", "_free_blocks", "_free_slots", "_refs", "_bound",
    "trie",
})

# Method names that mutate their receiver (list/dict/set/trie surface).
_MUTATORS = frozenset({
    "append", "extend", "pop", "remove", "insert", "clear", "add",
    "discard", "update", "setdefault", "evict",
})


def shard_map_bodies(mod) -> Dict[ast.AST, str]:
    """The ``shard_map``-lowered slice of the traced-body index:
    functions the shared :func:`traced_functions` walk attributes to a
    ``shard_map(...)`` call, plus the transitive in-module closure of
    functions their bodies reference — the same closure rule the
    host-sync scope uses, rooted narrower."""
    traced = traced_functions(mod)
    bodies: Dict[ast.AST, str] = {
        fn: reason for fn, reason in traced.items()
        if "shard_map" in reason}
    by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, _FuncDef):
            by_name.setdefault(node.name, []).append(node)
    changed = True
    while changed:
        changed = False
        for fn in list(bodies):
            for sub in ast.walk(fn):
                if (isinstance(sub, ast.Name)
                        and isinstance(sub.ctx, ast.Load)
                        and sub.id in by_name):
                    for callee in by_name[sub.id]:
                        if callee not in bodies and callee is not fn:
                            bodies[callee] = (
                                f"called from shard_map-lowered "
                                f"{getattr(fn, 'name', '?')}()")
                            changed = True
    return bodies


def _touched_state(node: ast.AST):
    """Host-state attributes referenced anywhere under ``node``."""
    return sorted({sub.attr for sub in ast.walk(node)
                   if isinstance(sub, ast.Attribute)
                   and sub.attr in HOST_TABLE_STATE})


@rule("mesh-host-side-tables",
      "block-table / free-list / trie state is host-side only — never "
      "mutated inside a shard_map-lowered body")
def check(index: SourceIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in index:
        bodies = shard_map_bodies(mod)
        for fn, reason in bodies.items():
            qual = index.qualname(mod, fn)
            for node in ast.walk(fn):
                hits, what = [], None
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        hits.extend(_touched_state(t))
                    what = "assignment to"
                elif (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _MUTATORS):
                    hits = _touched_state(node.func.value)
                    what = f".{node.func.attr}() on"
                for name in sorted(set(hits)):
                    findings.append(Finding(
                        file=mod.rel, line=node.lineno,
                        rule="mesh-host-side-tables",
                        symbol=qual, detail=name,
                        message=(f"{what} host-side pool state "
                                 f"{name!r} inside shard_map-lowered "
                                 f"{qual or '<module>'} ({reason}) — "
                                 f"bookkeeping would fork per shard")))
    return findings
