"""Rule ``bench-records``: committed perf records are real measurements.

The lint-rule face of :mod:`nezha_tpu.analysis.bench_records` (whose
module docstring tells the BENCH_r03–r05 story): every committed
``BENCH_*.json`` at the repo root must be valid JSON, a genuine
measurement, and platform-labeled — or explicitly superseded in
BENCH_NOTES.md. Running it through ``nezha-lint`` means one invocation
covers source contracts and committed artifacts alike."""

from __future__ import annotations

import re
from typing import List

from nezha_tpu.analysis.bench_records import check_dir
from nezha_tpu.analysis.core import Finding, rule
from nezha_tpu.analysis.index import SourceIndex

_FILE_RE = re.compile(r"(BENCH_\w+\.json)")


@rule("bench-records",
      "every committed BENCH_*.json is valid JSON, a real measurement "
      "(rc==0 + parsed metric, or by_platform slots), and platform-"
      "labeled — or superseded in BENCH_NOTES.md")
def check(index: SourceIndex) -> List[Finding]:
    findings: List[Finding] = []
    for msg in check_dir(index.root):
        m = _FILE_RE.search(msg)
        fname = m.group(1) if m else "BENCH_NOTES.md"
        findings.append(Finding(
            file=fname, line=0, rule="bench-records",
            symbol="record", detail=msg.split(":", 1)[-1].strip()[:60],
            message=msg))
    return findings
