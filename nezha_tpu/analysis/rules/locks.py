"""Rule ``lock-discipline``: declared cross-thread state mutates only
under its declared lock.

The serve layer is deliberately multi-threaded — HTTP handler threads
call ``Scheduler.submit`` against the decode loop, the router's prober
and forwards race the supervisor's monitor tick — and the free list,
block tables, in-flight ledgers, and replica records are all mutated
from more than one thread. The convention this rule enforces is
EXPLICIT declaration:

- a class declares its guarded state in a ``_LOCK_GUARDED`` class
  attribute: ``{"_queue": "_lock", "retries": "_ledger_lock", ...}``
  (attribute name -> the ``self.<lock>`` that must be held);
- every write to a declared attribute (assignment, augmented
  assignment, ``del``, subscript store, or a state-advancing method
  call — ``append``/``pop``/``update``/``add``/``random``/... ) must
  happen lexically inside ``with self.<lock>:`` in the same method;
- a method whose whole body runs with a lock already held by its
  caller says so in its docstring with the marker ``[holds: <lock>]``
  (the scheduler's ``_admit``/``_decode`` internals — the marker is
  the documentation the contract always deserved);
- ``__init__`` is exempt (construction happens-before publication).

Nested functions inherit the held set of their enclosing ``with``
block — right for the repo's ``_dispatch``-style immediately-called
closures; a closure stashed and called later from another thread would
need its own declaration."""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from nezha_tpu.analysis.core import Finding, rule
from nezha_tpu.analysis.index import Module, SourceIndex, dotted_name

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)

# Method names that advance state on the receiver. Collection mutators
# plus the instrument/RNG state-advancers the serve layer guards
# (``self._rng.random()`` consumes the shared stream).
MUTATORS: Set[str] = {
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "discard", "clear", "update", "add",
    "setdefault", "sort", "reverse", "set", "inc",
    "random", "randint", "randrange", "choice", "shuffle", "sample",
    "seed", "getrandbits", "uniform",
}

_HOLDS_RE = re.compile(r"\[holds:\s*([A-Za-z0-9_,\s]+)\]")


def _declared_guards(cls: ast.ClassDef) -> Optional[Dict[str, str]]:
    """The class's ``_LOCK_GUARDED`` dict literal, None when absent."""
    for node in cls.body:
        if isinstance(node, ast.Assign):
            names = [dotted_name(t) for t in node.targets]
            if "_LOCK_GUARDED" not in names:
                continue
            if isinstance(node.value, ast.Dict):
                out: Dict[str, str] = {}
                for k, v in zip(node.value.keys, node.value.values):
                    if (isinstance(k, ast.Constant)
                            and isinstance(v, ast.Constant)):
                        out[str(k.value)] = str(v.value)
                return out
    return None


def _marker_locks(fn: ast.AST) -> Set[str]:
    doc = ast.get_docstring(fn) or ""
    locks: Set[str] = set()
    for m in _HOLDS_RE.finditer(doc):
        for name in m.group(1).split(","):
            locks.add(name.strip())
    return locks


def _with_locks(item: ast.withitem) -> Optional[str]:
    """The ``self.<lock>`` name a with-item acquires, else None."""
    expr = item.context_expr
    name = dotted_name(expr)
    if name and name.startswith("self."):
        return name[len("self."):]
    return None


def _mutated_attr(node: ast.AST) -> Optional[str]:
    """The ``self.<attr>`` a statement/expression mutates, else None.

    Any store/del whose access chain is rooted at ``self.<attr>``
    counts (``self._replicas[rid].in_flight += 1`` mutates
    ``_replicas``-reachable state), as does a MUTATORS method call on
    such a chain."""
    target: Optional[ast.AST] = None
    if isinstance(node, (ast.Assign,)):
        for t in node.targets:
            root = _self_root(t)
            if root:
                return root
        return None
    if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        target = node.target
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            root = _self_root(t)
            if root:
                return root
        return None
    elif isinstance(node, ast.Call) and isinstance(node.func,
                                                   ast.Attribute):
        if node.func.attr in MUTATORS:
            target = node.func.value
    if target is None:
        return None
    return _self_root(target)


def _self_root(node: ast.AST) -> Optional[str]:
    """``_attr`` when the expression chain bottoms out at
    ``self._attr``, else None."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        node = node.value
    return None


@rule("lock-discipline",
      "writes to state declared in a class's _LOCK_GUARDED map happen "
      "inside `with self.<lock>:` (or a method marked `[holds: lock]`)")
def check(index: SourceIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in index:
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            guards = _declared_guards(cls)
            if not guards:
                continue
            for item in cls.body:
                if not isinstance(item, _FuncDef):
                    continue
                if item.name == "__init__":
                    continue
                held = _marker_locks(item)
                for stmt in item.body:
                    _visit(stmt, held, mod, cls, item, guards, findings)
    return findings


def _visit(node: ast.AST, held: Set[str], mod: Module,
           cls: ast.ClassDef, method: ast.AST,
           guards: Dict[str, str], findings: List[Finding]) -> None:
    """Recursive walk carrying the held-lock set; ``with self.<lock>:``
    bodies (wherever they nest) extend it."""
    if isinstance(node, ast.With):
        acquired = {l for l in (_with_locks(i) for i in node.items)
                    if l is not None}
        for item in node.items:
            _visit(item.context_expr, held, mod, cls, method, guards,
                   findings)
        for child in node.body:
            _visit(child, held | acquired, mod, cls, method, guards,
                   findings)
        return
    attr = _mutated_attr(node)
    if attr is not None and attr in guards:
        need = guards[attr]
        if need not in held:
            findings.append(Finding(
                file=mod.rel, line=node.lineno, rule="lock-discipline",
                symbol=f"{cls.name}.{method.name}",
                detail=attr,
                message=(f"write to lock-guarded `self.{attr}` outside "
                         f"`with self.{need}` in {cls.name}."
                         f"{method.name} — declared cross-thread state "
                         f"(add the with-block, or mark the method "
                         f"`[holds: {need}]` if the caller holds it)")))
    for child in ast.iter_child_nodes(node):
        _visit(child, held, mod, cls, method, guards, findings)
