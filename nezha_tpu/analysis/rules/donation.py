"""Rule ``use-after-donate``: a donated buffer is dead after dispatch.

The serve engine donates the pooled KV caches into every compiled
program (``Executor(donate_argnums=(1,))``), the COW block copy donates
the caches pytree (``jax.jit(_copy_block, donate_argnums=(0,))``), and
the train/parallel wires donate optimizer state. Donation is the reason
decode doesn't copy the whole pool per token — and it makes the passed
buffer INVALID the moment the call returns. Touching it afterwards is a
``RuntimeError: Array has been deleted`` in the best case and a silent
read of freed storage in the paged pool's worst case (PR 7's stale-KV
invariant exists because of exactly this class of bug).

The rule resolves three donation-site shapes statically:

1. ``f = jax.jit(fn, donate_argnums=(i, ...))`` then ``f(a, b, ...)``
   — positional args at the donated indices;
2. ``self.X = Executor(donate_argnums=(i, ...))`` then
   ``self.X.run(fn, a0, a1, ...)`` — ``run``'s first arg is the
   function, so donated positions shift by one;
3. ``jax.jit(fn, donate_argnums=...)(args...)`` called inline.

After a donating call, any later LOAD of the exact argument expression
(a plain name or a dotted ``self.pool.caches`` path) in the same
function is flagged, unless a STORE to that path (or a prefix of it)
re-bound it first — the engine's ``self.pool.caches = out[...]``
rebind is the blessed pattern. Aliased reads (``c = self.pool.caches``
before the call) are out of scope; the rule catches the shapes the
repo actually writes."""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from nezha_tpu.analysis.core import Finding, rule
from nezha_tpu.analysis.index import (Module, SourceIndex, dotted_name)

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)


def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """The literal donate_argnums of a jax.jit/Executor call, None when
    absent/empty. A conditional ``(0,) if donate else ()`` counts as
    donating (the lint must hold in the donating configuration)."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        node = kw.value
        if isinstance(node, ast.IfExp):
            node = (node.body if isinstance(node.body, ast.Tuple)
                    and node.body.elts else node.orelse)
        if isinstance(node, ast.Tuple):
            out = tuple(e.value for e in node.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int))
            return out or None
    return None


def _collect_sites(mod: Module) -> Tuple[Dict[str, Tuple[int, ...]],
                                         Dict[str, Tuple[int, ...]]]:
    """-> (jitted var name -> donated positions,
           executor attr name -> donated fn-arg positions).

    Jitted names are module/class/function locals assigned from
    ``jax.jit(..., donate_argnums=...)``; executor attrs come from
    ``self.X = Executor(donate_argnums=...)`` anywhere in the module."""
    jitted: Dict[str, Tuple[int, ...]] = {}
    executors: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        call = node.value
        cn = dotted_name(call.func) or ""
        donated = _donated_positions(call)
        if donated is None:
            continue
        if cn.rsplit(".", 1)[-1] == "jit":
            for t in node.targets:
                name = dotted_name(t)
                if name:
                    jitted[name] = donated
        elif cn.rsplit(".", 1)[-1] == "Executor":
            for t in node.targets:
                name = dotted_name(t)
                if name and name.startswith("self."):
                    executors[name[len("self."):]] = donated
    return jitted, executors


def _donating_call(call: ast.Call, jitted, executors
                   ) -> Optional[List[ast.AST]]:
    """The donated argument expressions of this call, None if it is not
    a known donating call."""
    cn = dotted_name(call.func)
    if cn in jitted:
        idxs = jitted[cn]
        return [call.args[i] for i in idxs if i < len(call.args)]
    if cn and cn.startswith("self.") and cn.endswith(".run"):
        attr = cn[len("self."):-len(".run")]
        if attr in executors:
            idxs = executors[attr]
            # run(fn, *args): fn-arg i is run's positional i + 1.
            return [call.args[i + 1] for i in idxs
                    if i + 1 < len(call.args)]
    # Inline jax.jit(f, donate_argnums=...)(args...)
    if isinstance(call.func, ast.Call):
        inner = dotted_name(call.func.func) or ""
        if inner.rsplit(".", 1)[-1] == "jit":
            donated = _donated_positions(call.func)
            if donated:
                return [call.args[i] for i in donated
                        if i < len(call.args)]
    return None


def _pos(node: ast.AST) -> Tuple[int, int]:
    return (getattr(node, "end_lineno", node.lineno),
            getattr(node, "end_col_offset", 0))


def _field_of(parent: ast.AST, child: ast.AST) -> Optional[str]:
    for field, value in ast.iter_fields(parent):
        if value is child:
            return field
        if isinstance(value, list) and any(v is child for v in value):
            return field
    return None


def _branch_exclusive(mod: Module, a: ast.AST, b: ast.AST) -> bool:
    """True when ``a`` and ``b`` sit in opposite arms of the same
    ``if``/``else`` — one can never execute after the other in a single
    pass, so a donation in one arm does not kill a read in the sibling
    (the engine's paged/dense dispatch pairs)."""
    a_chain: dict = {}
    cur, parent = a, mod.parents.get(a)
    while parent is not None:
        a_chain[id(parent)] = cur
        cur, parent = parent, mod.parents.get(parent)
    cur, parent = b, mod.parents.get(b)
    while parent is not None:
        if id(parent) in a_chain:
            # Lowest common ancestor: exclusivity is decided here and
            # only here (above it the two share every branch arm).
            if isinstance(parent, ast.If):
                fa = _field_of(parent, a_chain[id(parent)])
                fb = _field_of(parent, cur)
                return {fa, fb} == {"body", "orelse"}
            return False
        cur, parent = parent, mod.parents.get(parent)
    return False


@rule("use-after-donate",
      "arguments passed at donate_argnums positions (Executor caches, "
      "jitted COW/step donations) must not be read after the call "
      "until re-bound")
def check(index: SourceIndex) -> List[Finding]:
    findings: List[Finding] = []
    seen = set()
    for mod in index:
        jitted, executors = _collect_sites(mod)
        if not jitted and not executors:
            continue
        for fn in index.functions(mod):
            for f in _check_function(index, mod, fn, jitted, executors):
                # A nested def is walked by its own pass AND its
                # enclosing function's (ast.walk descends) — keep one
                # finding per location.
                loc = (f.file, f.line, f.detail)
                if loc not in seen:
                    seen.add(loc)
                    findings.append(f)
    return findings


def _check_function(index, mod, fn, jitted, executors) -> List[Finding]:
    # Every donating call inside this function, with the dotted paths it
    # kills and the source position it happens at.
    kills: List[Tuple[Tuple[int, int], ast.Call, str]] = []
    rebinds: List[Tuple[Tuple[int, int], str]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            donated = _donating_call(node, jitted, executors)
            if not donated:
                continue
            for arg in donated:
                path = dotted_name(arg)
                if path:
                    kills.append((_pos(node), node, path))
            # `self.caches = jitted(self.caches, ...)`: the enclosing
            # assignment's target STORES after the call returns, even
            # though it lexically precedes it — synthesize the store
            # just past the call so the same-statement rebind revives
            # the path.
            stmt = mod.parents.get(node)
            while stmt is not None and not isinstance(
                    stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                           ast.FunctionDef, ast.AsyncFunctionDef)):
                stmt = mod.parents.get(stmt)
            if isinstance(stmt, (ast.Assign, ast.AnnAssign,
                                 ast.AugAssign)):
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                after = (_pos(node)[0], _pos(node)[1] + 1)
                for t in targets:
                    for sub in ast.walk(t):
                        tpath = dotted_name(sub)
                        if tpath:
                            rebinds.append((after, tpath))
    if not kills:
        return []

    # All loads/stores of dotted paths in the function, in source order.
    events: List[Tuple[Tuple[int, int], str, str, ast.AST]] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.Name, ast.Attribute)):
            path = dotted_name(node)
            if path is None:
                continue
            ctx = getattr(node, "ctx", None)
            if isinstance(ctx, (ast.Store, ast.Del)):
                events.append(((node.lineno, node.col_offset), "store",
                               path, node))
            elif isinstance(ctx, ast.Load):
                events.append(((node.lineno, node.col_offset), "load",
                               path, node))
    for rpos, rpath in rebinds:
        events.append((rpos, "store", rpath, fn))
    events.sort(key=lambda e: e[0])

    findings: List[Finding] = []
    qual = index.qualname(mod, fn)
    for kpos, kcall, path in kills:
        for epos, kind, epath, enode in events:
            if epos <= kpos:
                continue
            if enode is not fn and _branch_exclusive(mod, kcall, enode):
                # The sibling `if`/`else` arm: this event can never
                # execute after the donation in one pass — it neither
                # violates nor revives.
                continue
            if kind == "store" and (path == epath
                                    or path.startswith(epath + ".")):
                break        # re-bound: the donated path is live again
            if kind == "load" and (epath == path
                                   or epath.startswith(path + ".")):
                findings.append(Finding(
                    file=mod.rel, line=enode.lineno,
                    rule="use-after-donate",
                    symbol=qual, detail=path,
                    message=(f"`{epath}` read after being donated at "
                             f"line {kcall.lineno} (donate_argnums) — "
                             f"the buffer is invalidated by dispatch; "
                             f"re-bind it from the program output "
                             f"before any further use")))
                break        # one finding per donation site
    return findings
