"""Rule ``traced-value-branch``: no Python control flow on traced values.

``if`` / ``while`` / ``assert`` (and ternary ``x if c else y``) on a
value produced by ``jnp``/``lax`` inside a jit/scan/pallas body is a
``TracerBoolConversionError`` at a distance: it traces fine in the
author's quick test (concrete inputs), then explodes — or worse, bakes
one branch in silently — the first time the function is actually
compiled. The in-program idiom is ``jnp.where`` / ``lax.cond`` /
``lax.select``; this rule points there the moment the Python keyword
lands.

Taint is conservative-by-construction (:func:`nezha_tpu.analysis.
traced.device_tainted`): positional parameters of a traced function and
anything assigned from device namespaces are traced; keyword-only
params (the ``functools.partial``-bound statics of the Pallas kernels)
and ``.shape``/``.dtype`` metadata are not."""

from __future__ import annotations

import ast
from typing import List

from nezha_tpu.analysis.core import Finding, rule
from nezha_tpu.analysis.index import SourceIndex, dotted_name
from nezha_tpu.analysis.rules.host_sync import walk_own
from nezha_tpu.analysis.traced import (_is_device_call, device_tainted,
                                       only_static_use,
                                       traced_functions)


# Predicates that are static even though they live in a device
# namespace (dtype classification happens at trace time).
_STATIC_PREDICATES = {"jnp.issubdtype", "jnp.isdtype", "jnp.result_type",
                      "jnp.promote_types", "jax.numpy.issubdtype"}


def _test_tainted(test: ast.AST, tainted: set) -> bool:
    # Identity tests never convert to bool — `x is None` on a tracer is
    # legal (and idiomatic for optional-operand plumbing); recurse
    # through and/or/not so compound identity guards stay legal too.
    if isinstance(test, ast.BoolOp):
        return any(_test_tainted(v, tainted) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _test_tainted(test.operand, tainted)
    if isinstance(test, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
        return False
    if isinstance(test, ast.Call):
        cn = dotted_name(test.func) or ""
        if cn in ("isinstance", "callable", "hasattr", "len") \
                or cn in _STATIC_PREDICATES:
            return False
    for sub in ast.walk(test):
        if isinstance(sub, ast.Call) and _is_device_call(sub):
            return True
        if (isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
                and sub.id in tainted):
            # `.shape`-style metadata reads are static (the SAME set
            # the taint propagation uses — traced.only_static_use); a
            # bare tainted name in a test is a bool() on a tracer.
            if not only_static_use(test, sub):
                return True
    return False


@rule("traced-value-branch",
      "no Python if/while/assert on jnp/lax-produced values inside "
      "traced function bodies (TracerBoolConversionError at a distance)")
def check(index: SourceIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in index:
        traced = traced_functions(mod)
        for fn, reason in traced.items():
            # Parameters are NOT tainted: traced helpers routinely take
            # static config through positional params (`op`, `causal`,
            # `interpret`), and branching on those is how a trace
            # specializes. Only values PRODUCED by device namespaces in
            # this body are certain tracers.
            tainted = device_tainted(fn, include_params=False)
            qual = index.qualname(mod, fn)
            for node in walk_own(fn, set(traced)):
                kind = None
                test = None
                if isinstance(node, ast.If):
                    kind, test = "if", node.test
                elif isinstance(node, ast.While):
                    kind, test = "while", node.test
                elif isinstance(node, ast.Assert):
                    kind, test = "assert", node.test
                elif isinstance(node, ast.IfExp):
                    kind, test = "ternary if", node.test
                if test is None or not _test_tainted(test, tainted):
                    continue
                snippet = ast.unparse(test)
                if len(snippet) > 40:
                    snippet = snippet[:37] + "..."
                findings.append(Finding(
                    file=mod.rel, line=node.lineno,
                    rule="traced-value-branch",
                    symbol=qual, detail=f"{kind} {snippet}",
                    message=(f"Python `{kind}` on traced value "
                             f"`{snippet}` inside traced function "
                             f"{qual or '<module>'} ({reason}) — use "
                             f"jnp.where / lax.cond instead")))
    return findings
