"""Rule ``host-sync-in-hot-path``: no host synchronization inside
traced program bodies.

The contract this enforces is the one PR 5's whole design rests on: the
decode hot path is device-resident, and its throughput claim
(``serve.host_gap_s``) dies the moment someone reintroduces a host
round-trip inside a compiled program body — a ``.block_until_ready()``,
an ``.item()`` / ``float()`` on a device value, an ``np.asarray``
materialization, a ``print``, a file open, a ``time.sleep``. Inside a
traced function those either crash at trace time (concretization),
silently execute at TRACE time only (print/time — a misleading no-op in
steady state), or force a sync. All of them are wrong; none should wait
for a chaos test to flake three PRs later.

Scope: every function :mod:`nezha_tpu.analysis.traced` identifies as
traced — jit-decorated, handed to scan/while_loop/pallas_call, the
serve engine's ``_build_*`` program closures, and their in-module
helpers.

The rule also pins the tiered-KV contract: the paged pool's HOST-TIER
buffers (``_host_tier`` and friends — plain numpy, host RAM) are pool
maintenance and must never be touched inside a traced body; promotion
is an async copy dispatched BEFORE the prefill programs, not state the
programs read."""

from __future__ import annotations

import ast
from typing import List

from nezha_tpu.analysis.core import Finding, rule
from nezha_tpu.analysis.index import SourceIndex, dotted_name
from nezha_tpu.analysis.traced import device_tainted, traced_functions

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)


def walk_own(fn: ast.AST, skip: set):
    """Walk ``fn``'s body, pruning nested defs in ``skip`` (they are
    traced functions in their own right and get their own pass —
    without pruning every violation inside them would be reported
    twice, once per enclosing symbol, destabilizing baseline keys)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, _FuncDef) and node in skip:
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))

# Method calls that synchronize (or concretize) a device value.
_SYNC_METHODS = {"block_until_ready", "item", "tolist",
                 "copy_to_host_async", "__array__"}
# Bare-name calls that are host effects inside a traced body.
_HOST_CALLS = {"print", "open", "input", "breakpoint"}
# `module.attr` calls that are host effects / host materialization.
_HOST_DOTTED = {
    "np.asarray", "np.array", "np.copy", "np.frombuffer", "np.save",
    "np.load", "numpy.asarray", "numpy.array", "numpy.copy",
    "jax.device_get", "jax.block_until_ready", "jax.debug.breakpoint",
    "time.sleep", "time.time", "time.monotonic", "time.perf_counter",
    "time.process_time", "os.system", "subprocess.run",
}
# Builtins that concretize — flagged only when their argument is a
# device-tainted value (float(0.5) literals and closure scalars stay
# legal inside traced code).
_CONCRETIZERS = {"float", "int", "bool", "complex"}
# Host-tier KV buffers (the paged pool's demoted-block store,
# serve/slots.py): plain numpy in an OrderedDict, readable only from
# host code. ANY touch inside a traced body — read or write — is
# wrong twice over: it executes at trace time only (a silent no-op in
# steady state, exactly like print), and promotion/demotion are
# host-side pool maintenance by contract (the frozen-program set must
# never grow a host dependency). Attribute ACCESS is flagged, not just
# calls — `caches[...] = pool._host_tier[key]` has no call to catch.
_HOST_TIER_ATTRS = {"_host_tier", "host_blocks_used",
                    "host_bytes_resident", "clear_host_tier",
                    "_demote", "_promote"}


@rule("host-sync-in-hot-path",
      "no host sync/IO (block_until_ready, .item(), float()/np.asarray "
      "on device values, print/open/time) and no host-tier KV buffer "
      "access inside traced program bodies")
def check(index: SourceIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in index:
        traced = traced_functions(mod)
        for fn, reason in traced.items():
            # Params excluded from taint: positional params of traced
            # helpers are often static config, and float()/int() on
            # those is legal trace specialization. jnp/lax-produced
            # values are the certain tracers.
            tainted = device_tainted(fn, include_params=False)
            qual = index.qualname(mod, fn)
            for node in walk_own(fn, set(traced)):
                if (isinstance(node, ast.Attribute)
                        and node.attr in _HOST_TIER_ATTRS):
                    findings.append(Finding(
                        file=mod.rel, line=node.lineno,
                        rule="host-sync-in-hot-path",
                        symbol=qual, detail=f".{node.attr}",
                        message=(f"host-tier KV buffer `.{node.attr}` "
                                 f"touched inside traced function "
                                 f"{qual or '<module>'} ({reason}) — "
                                 f"the host spill tier is host-side "
                                 f"pool maintenance, never compiled "
                                 f"program state")))
                    continue
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                flag = None
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _SYNC_METHODS:
                    flag = f".{node.func.attr}()"
                elif name in _HOST_DOTTED:
                    flag = f"{name}()"
                elif name in _HOST_CALLS:
                    flag = f"{name}()"
                elif name in _CONCRETIZERS and node.args:
                    arg = node.args[0]
                    arg_is_tainted = any(
                        isinstance(s, ast.Name) and s.id in tainted
                        for s in ast.walk(arg))
                    if arg_is_tainted:
                        flag = f"{name}() on a traced value"
                if flag is not None:
                    findings.append(Finding(
                        file=mod.rel, line=node.lineno,
                        rule="host-sync-in-hot-path",
                        symbol=qual, detail=flag,
                        message=(f"{flag} inside traced function "
                                 f"{qual or '<module>'} ({reason}) — "
                                 f"host sync/IO on the compiled hot "
                                 f"path")))
    return findings
