"""Rule ``telemetry-schema``: source instrument names match the pins.

``tools/check_telemetry_schema.py`` validates run-dir CAPTURES; this
rule closes the other half of the loop at the SOURCE: every literal
``obs.counter("...")`` / ``obs.gauge`` / ``obs.histogram`` /
``obs.span`` whose name falls in a pinned namespace (``serve.`` /
``router.`` / ``dist.`` / ``checkpoint.``) must be a member of the
pinned set for its instrument kind — and of the RIGHT kind (a
``serve.ttft_s`` counter would be a schema violation even though the
name exists as a histogram). A typo'd instrument therefore fails the
lint when the call site lands, instead of surfacing as a blank
dashboard panel after the capture ships.

Typed events (PR 16) face the same contract: a literal
``obs.record_event("...")`` kind under the ``watchdog.`` / ``slo.``
namespaces must be a member of the pinned ``EVENT_KINDS`` registry —
alert routing and ``nezha-telemetry --slo`` key on event kinds exactly
as dashboards key on instrument names.

Dynamic names (f-strings, variables) are skipped, never guessed — the
run-dir validator still catches those at capture time."""

from __future__ import annotations

import ast
from typing import List

from nezha_tpu.analysis import telemetry_schema as ts
from nezha_tpu.analysis.core import Finding, rule
from nezha_tpu.analysis.index import SourceIndex, call_name, str_arg

_KIND_SETS = {
    "counter": ("counter", ts.PINNED_COUNTERS),
    "gauge": ("gauge", ts.PINNED_GAUGES),
    "histogram": ("histogram", ts.PINNED_HISTOGRAMS),
}


@rule("telemetry-schema",
      "literal obs.counter/gauge/histogram/span names under the pinned "
      "namespaces are members of the pinned schema sets (right name AND "
      "right instrument kind); literal obs.record_event kinds under "
      "watchdog./slo. are members of the pinned event registry")
def check(index: SourceIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in index:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node) or ""
            if not cn.startswith("obs."):
                continue
            kind = cn[len("obs."):]
            # The retroactive (emit_span) and trace-gated (traced_span)
            # forms record into the same span stream — their literal
            # names face the identical pinned-registry contract.
            if kind in ("emit_span", "traced_span"):
                kind = "span"
            name = str_arg(node)
            if name is None:
                continue
            # faults.injected_total rides in the serve set but is not
            # namespace-prefixed; only pinned namespaces are enforced.
            if kind == "record_event":
                if not name.startswith(ts.EVENT_KIND_PREFIXES):
                    continue
                if name not in ts.EVENT_KINDS:
                    findings.append(_finding(
                        index, mod, node, name,
                        f"event kind {name!r} is not in the pinned "
                        f"event registry (EVENT_KINDS) for its "
                        f"namespace — add it to "
                        f"analysis/telemetry_schema.py (and the "
                        f"RUNBOOK event taxonomy) deliberately"))
                continue
            if kind == "span":
                if not name.startswith(ts.PINNED_SPAN_PREFIXES):
                    continue
                if name not in ts.PINNED_SPANS:
                    findings.append(_finding(
                        index, mod, node, name,
                        f"span name {name!r} is not in the pinned span "
                        f"registry for its namespace — add it to "
                        f"analysis/telemetry_schema.py (and the docs) "
                        f"deliberately"))
                continue
            if kind not in _KIND_SETS:
                continue
            if not name.startswith(ts.PINNED_METRIC_PREFIXES):
                continue
            label, members = _KIND_SETS[kind]
            if name in members:
                continue
            other = [k for k, (_, s) in _KIND_SETS.items()
                     if k != kind and name in s]
            if other:
                msg = (f"{name!r} is pinned as a {other[0]} but used "
                       f"as a {label} — instrument kind mismatch")
            else:
                msg = (f"{label} name {name!r} is not in the pinned "
                       f"schema for its namespace — add it to "
                       f"analysis/telemetry_schema.py (and "
                       f"register_*_instruments) deliberately")
            findings.append(_finding(index, mod, node, name, msg))
    return findings


def _finding(index, mod, node, name, msg) -> Finding:
    return Finding(file=mod.rel, line=node.lineno,
                   rule="telemetry-schema",
                   symbol=index.qualname(mod, node), detail=name,
                   message=msg)
