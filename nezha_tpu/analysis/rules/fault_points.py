"""Rule ``fault-points``: the chaos-knob registry stays pinned.

The AST port of ``tools/check_fault_points.py`` (which is now a shim
over this module): every ``faults.point("...")`` / ``faults.corrupt(
"...")`` call site under ``nezha_tpu/`` must be **unique** (one site
per name — hit counts and plan rules stay unambiguous), **documented**
(the RUNBOOK fault-point table), **tested** (named somewhere under
``tests/``), and **pinned** (the discovered set equals
:data:`EXPECTED_POINTS` exactly, so a point cannot appear or vanish
without this file changing deliberately).

The AST form is strictly better than the old regex: only genuine
``Call`` nodes with literal names register, so docstring examples can
never count as call sites (the old walker had to exclude the whole
faults package for that). The exclusion stays anyway — the injector's
own internals are plumbing, not registered points."""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional

from nezha_tpu.analysis.core import Finding, rule
from nezha_tpu.analysis.index import (SourceIndex, call_name, str_arg)

# The frozen registry: every faults.point()/corrupt() call site in the
# tree, by name. Adding a fault point means adding it HERE (and to the
# RUNBOOK table + a test) in the same change.
EXPECTED_POINTS = frozenset({
    "serve.prefill", "serve.prefill.logits",
    "serve.step", "serve.step.logits",
    "checkpoint.save", "dist.join",
    # Multi-replica serving (router/supervisor front end):
    "router.route", "router.probe", "supervisor.spawn", "replica.exec",
    # Paged KV pool: armed at every block bind (admission, lazy decode
    # growth, COW) — an injected error surfaces as the same typed
    # KVBlocksExhausted backpressure genuine exhaustion produces.
    "serve.kv.bind",
    # Disaggregated prefill/decode migration (serve/migrate.py): the
    # router's orchestration entry, the source-side block export, and
    # the destination-side install — each failure surfaces typed
    # (injected_fault / migration_failed) and is retried, fallen back,
    # or restarted by the router, never silently dropped.
    "router.migrate", "replica.kv_export", "replica.kv_install",
    # Speculative decoding: armed on the carried logits after every
    # speculative step dispatch — a nan/inf rule poisons one victim
    # row (the in-program tripwire retires ONLY that request, zero
    # slot/block leaks in either pool), an error rule raises typed
    # InjectedFault into the scheduler's bounded-retry envelope.
    "serve.spec.verify",
    # Tiered KV host spill (serve/slots.py): armed at the start of
    # every host->device block promotion — an injected error degrades
    # the request to a cold prefill (typed, counted in the pool's
    # promote_failures ledger), never an error surfaced to the client
    # and never a leaked block on either tier.
    "serve.kv.promote",
    # Fleet-wide KV reuse (PR 17, serve/fleetcache): the affinity
    # scorer inside Router._pick — an injected error degrades THAT
    # request's pick to plain least-loaded, never a client-visible
    # error — and the peer-pull client (migrate.pull_prefix_into) —
    # an injected error (or delay, the mid-pull SIGKILL drill's
    # window-stretcher) surfaces as MigrationError kind
    # "kv_pull_failed" and the replica front end degrades the request
    # to a cold prefill, zero blocks leaked on either side.
    "router.affinity", "replica.kv_pull",
    # Train->serve checkpoint resharding (serve/sharded/reshard.py):
    # armed at the start of every reshard — an injected error surfaces
    # as the same typed ReshardError a corrupt/missing leaf produces,
    # and the sharded engine REFUSES TO START rather than serving
    # garbage weights.
    "serve.reshard",
    # Multi-tenant scheduling (PR 19). scheduler.preempt: armed before
    # every preemption — an injected error is the failed-demotion
    # drill, the scheduler lets the victim keep decoding and the
    # target waits for ordinary retirement (typed degradation, never a
    # client-visible error). supervisor.scale: armed at every elastic
    # autoscale decision — an injected error skips that scale action;
    # pressure re-evaluates next tick and the fleet holds its size (a
    # failed SPAWN afterwards still counts against the PR 6 circuit
    # breaker via supervisor.spawn).
    "scheduler.preempt", "supervisor.scale",
    # Sequence-sharded prefill (PR 20, serve/sharded/engine.py): armed
    # at the head of every prefill() under prefill_mode=sequence —
    # an injected error raises typed InjectedFault into the
    # scheduler's standard prefill-error envelope: ONLY the victim
    # request retires (FinishReason.ERROR), zero slot/block/scale
    # leaks on any shard, and the engine keeps serving.
    "serve.prefill.seq",
})
SOURCE_PREFIX = "nezha_tpu/"
EXCLUDE_PREFIX = "nezha_tpu/faults/"
RUNBOOK = os.path.join("docs", "RUNBOOK.md")
TESTS_DIR = "tests"


def find_points_in_index(index: SourceIndex) -> Dict[str, List[str]]:
    """-> {point name: [repo-relative files registering it]}."""
    points: Dict[str, List[str]] = {}
    for mod in index:
        if not mod.rel.startswith(SOURCE_PREFIX) \
                or mod.rel.startswith(EXCLUDE_PREFIX):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) in ("faults.point", "faults.corrupt"):
                name = str_arg(node)
                if name is not None:
                    points.setdefault(name, []).append(mod.rel)
    return points


def _tests_blob(index: SourceIndex) -> str:
    chunks: List[str] = []
    tests_root = os.path.join(index.root, TESTS_DIR)
    for dirpath, _, files in os.walk(tests_root):
        for fn in sorted(files):
            if fn.endswith(".py"):
                rel = os.path.relpath(os.path.join(dirpath, fn),
                                      index.root)
                text = index.read_text(rel)
                if text:
                    chunks.append(text)
    return "\n".join(chunks)


def check_index(index: SourceIndex,
                expected: Optional[frozenset] = None) -> List[Finding]:
    """The rule body; ``expected`` overrides the pinned set (fixture
    trees in tests pin their own)."""
    expected = EXPECTED_POINTS if expected is None else expected
    findings: List[Finding] = []

    def add(name: str, msg: str, file: str = RUNBOOK.replace(os.sep, "/"),
            line: int = 0) -> None:
        findings.append(Finding(file=file, line=line, rule="fault-points",
                                symbol="registry", detail=name,
                                message=msg))

    points = find_points_in_index(index)
    if not points:
        add("<none>", f"no faults.point()/faults.corrupt() call sites "
                      f"found under {SOURCE_PREFIX}")
        return findings
    for name, files in sorted(points.items()):
        if len(files) > 1:
            add(name, f"fault point {name!r} registered at "
                      f"{len(files)} call sites ({', '.join(files)}) — "
                      f"names must be unique", file=files[0])
    for name in sorted(set(points) - expected):
        add(name, f"fault point {name!r} is not in EXPECTED_POINTS — "
                  f"add it to the pinned registry (and the RUNBOOK "
                  f"table) deliberately", file=points[name][0])
    for name in sorted(expected - set(points)):
        add(name, f"pinned fault point {name!r} has no faults.point()/"
                  f"corrupt() call site under {SOURCE_PREFIX} — the "
                  f"registry lost a point")
    runbook = index.read_text(RUNBOOK.replace(os.sep, "/")) or ""
    tests_blob = _tests_blob(index)
    for name in sorted(points):
        # Boundary-anchored match: a point whose name prefixes another's
        # ("serve.step" vs "serve.step.logits") must NOT pass vacuously
        # via its sibling's mentions.
        exact = re.compile(
            rf"(?<![A-Za-z0-9_.]){re.escape(name)}(?![A-Za-z0-9_.])")
        if not exact.search(runbook):
            add(name, f"fault point {name!r} is not documented in "
                      f"{RUNBOOK}", file=points[name][0])
        if not exact.search(tests_blob):
            add(name, f"fault point {name!r} is not covered by any test "
                      f"under {TESTS_DIR}/", file=points[name][0])
    return findings


@rule("fault-points",
      "every faults.point()/corrupt() site is unique, RUNBOOK-"
      "documented, test-covered, and matches the pinned EXPECTED_POINTS")
def check_rule(index: SourceIndex) -> List[Finding]:
    return check_index(index)


# ------------------------------------------------- legacy shim surface
def find_points(root: str) -> Dict[str, List[str]]:
    """Standalone-compatible entry (tools/check_fault_points.py)."""
    return find_points_in_index(SourceIndex(root, roots=("nezha_tpu",),
                                            extra_files=()))


def check(root: str) -> List[str]:
    """-> list of violation strings (empty = registry is clean) — the
    exact contract the legacy checker exposed to tests."""
    index = SourceIndex(root, roots=("nezha_tpu",), extra_files=())
    return [f.message for f in check_index(index)]
