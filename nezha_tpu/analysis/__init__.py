"""nezha_tpu.analysis — static invariant checking for this repo.

Every performance and robustness claim the serving/training stack ships
rests on contracts that used to be enforced only at runtime (or by three
bespoke regex walkers in ``tools/``): the device-resident decode loop
dies if a host sync sneaks into a program body, the paged pool dies if
a donated caches pytree is touched after dispatch, the scheduler's free
list corrupts if an unlocked thread writes it. This package checks
those contracts AT ANALYSIS TIME — the same compile-it-and-verify-it
move the related work applies to collectives programs (GC3,
arXiv:2201.11840), applied to the codebase itself.

Architecture: one :class:`~nezha_tpu.analysis.index.SourceIndex` (every
file parsed once) + a pluggable rule registry
(:mod:`~nezha_tpu.analysis.core`) + a committed suppression baseline
(:mod:`~nezha_tpu.analysis.baseline`). The ``nezha-lint`` CLI
(``nezha_tpu/cli/lint.py``) and the tier-1 suite drive it; the legacy
``tools/check_*.py`` entry points are shims over the same rules.

Stdlib-only: rules parse source, they never import it — fixture trees
in tests lint fine without jax, and the whole repo lints in ~1 s.
"""

from nezha_tpu.analysis.baseline import (BaselineError, apply_baseline,
                                         load_baseline, write_baseline)
from nezha_tpu.analysis.core import (Finding, Rule, RULES, load_rules,
                                     run_rules)
from nezha_tpu.analysis.index import SourceIndex

__all__ = [
    "SourceIndex", "Finding", "Rule", "RULES", "load_rules", "run_rules",
    "BaselineError", "load_baseline", "apply_baseline", "write_baseline",
]
