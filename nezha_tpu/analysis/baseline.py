"""The suppression baseline: accepted findings, each with a reason.

``tools/lint_baseline.json`` is the committed ledger of findings the
repo has LOOKED AT and decided to keep — never a mute button. Shape:

    {"version": 1,
     "suppressions": [
       {"key": "<rule>:<file>:<symbol>:<detail>",
        "justification": "one line on why this is intentionally kept"}]}

Keys are line-free (see :class:`~nezha_tpu.analysis.core.Finding`), so
unrelated edits don't churn the file — but the key dies with the code
it describes, and a STALE entry (key matching no current finding) fails
the lint: a suppression must never outlive its violation, or the next
identical violation would be silently pre-forgiven.

An entry with an empty/placeholder justification is invalid: the whole
point is that every accepted finding carries its one-line why."""

from __future__ import annotations

import json
import os
from typing import Dict, List, Sequence, Tuple

from nezha_tpu.analysis.core import Finding

DEFAULT_BASELINE = os.path.join("tools", "lint_baseline.json")
VERSION = 1


class BaselineError(ValueError):
    """Malformed baseline file — fails the lint with its message."""


PLACEHOLDER_JUSTIFICATION = ("TODO: justify (the baseline will not "
                             "load until this is a real reason)")


def load_baseline(path: str, strict: bool = True) -> Dict[str, str]:
    """-> {key: justification}. A missing file is an empty baseline;
    a malformed one raises :class:`BaselineError`. ``strict=False``
    accepts placeholder/empty justifications (still rejecting
    structural damage) — ONLY for regeneration, which must read the
    existing entries' text to preserve it, never for suppression."""
    if not os.path.isfile(path):
        return {}
    try:
        with open(path) as f:
            data = json.load(f)
    except ValueError as e:
        raise BaselineError(f"{path}: not valid JSON ({e})")
    if not isinstance(data, dict) or data.get("version") != VERSION:
        raise BaselineError(
            f"{path}: expected an object with version == {VERSION}")
    sups = data.get("suppressions")
    if not isinstance(sups, list):
        raise BaselineError(f"{path}: 'suppressions' must be a list")
    out: Dict[str, str] = {}
    for i, s in enumerate(sups):
        if not isinstance(s, dict) or not isinstance(s.get("key"), str):
            raise BaselineError(
                f"{path}: suppressions[{i}] must be an object with a "
                f"string 'key'")
        just = s.get("justification")
        if not isinstance(just, str):
            raise BaselineError(
                f"{path}: suppressions[{i}] ({s['key']!r}) "
                f"'justification' must be a string")
        if strict and (not just.strip()
                       or just.strip().lower().startswith("todo")):
            raise BaselineError(
                f"{path}: suppressions[{i}] ({s['key']!r}) needs a real "
                f"one-line justification (empty/TODO is not one)")
        if s["key"] in out:
            raise BaselineError(
                f"{path}: duplicate suppression key {s['key']!r}")
        out[s["key"]] = just.strip()
    return out


def apply_baseline(findings: Sequence[Finding],
                   baseline: Dict[str, str]
                   ) -> Tuple[List[Finding], List[str]]:
    """-> (unsuppressed findings, stale baseline keys). Stale keys are
    violations in their own right (the caller reports them)."""
    present = {f.key for f in findings}
    kept = [f for f in findings if f.key not in baseline]
    stale = sorted(k for k in baseline if k not in present)
    return kept, stale


def write_baseline(findings: Sequence[Finding], path: str,
                   justifications: Dict[str, str] = None,
                   default_justification: str = PLACEHOLDER_JUSTIFICATION
                   ) -> None:
    """Write a baseline accepting exactly ``findings``. Existing
    justifications (pass the loaded map) are preserved per key; new
    keys get ``default_justification``, which DEFAULTS to the
    placeholder a strict load rejects — a regenerated baseline cannot
    silently launder unreviewed findings into accepted ones."""
    justifications = justifications or {}
    entries = []
    for f in sorted(findings):
        if f.key in {e["key"] for e in entries}:
            continue
        entries.append({
            "key": f.key,
            "justification": justifications.get(
                f.key, default_justification),
            # Context for the human editing the file; never matched.
            "note": f"{f.file}:{f.line} {f.message}"[:200],
        })
    data = {"version": VERSION, "suppressions": entries}
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=False)
        f.write("\n")
