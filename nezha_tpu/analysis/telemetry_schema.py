"""Frozen-schema validation for telemetry: pinned names + run-dir checks.

Two consumers share the pinned sets below:

- :func:`check_run_dir` validates a CAPTURED run directory
  (metrics.jsonl / spans.jsonl / summary.json) against schema v1 — the
  runtime side, called from tier-1 tests on real captures and from the
  ``tools/check_telemetry_schema.py`` shim standalone;
- the ``telemetry-schema`` lint rule
  (:mod:`nezha_tpu.analysis.rules.telemetry`) validates the SOURCE —
  every literal instrument name under a pinned namespace must be a
  member of these sets, so a typo'd or unregistered name fails the
  lint when the code changes, not when a dashboard goes blank.

The run-dir contract (obs/sink.py) is an interface other tooling reads
— dashboards, the ``nezha-telemetry`` report, downstream analysis — so
drift must fail fast. Schema v1:

    metrics.jsonl   one JSON object per line; "step" int >= 0, "ts"
                    float; other values JSON scalars
    spans.jsonl     one JSON object per line; "name" str, "t0"/"t1"
                    floats with t1 >= t0, "dur_s" float, "attrs" object;
                    optionally the trace record fields "trace_id"/
                    "span_id"/"parent_id" (non-empty strings — the
                    distributed-tracing stitch key)
    summary.json    schema_version == 1; counters/gauges/histograms/
                    collectives objects; compile_cache with int
                    hits/misses; slowest_spans list of span records

This module also pins the LIVE ``GET /stats`` payload
(:func:`check_stats_payload`, stats schema v1): the replica shape
(``obs.stats_snapshot()``) and the router's fleet aggregate.
"""

from __future__ import annotations

import json
import os
import re
from typing import List

SCHEMA_VERSION = 1
_HIST_KEYS = {"count", "sum", "min", "max", "mean", "p50", "p90", "p99"}
_SUMMARY_KEYS = {"schema_version", "counters", "gauges", "histograms",
                 "collectives", "compile_cache", "num_spans",
                 "slowest_spans"}

# Serving-run schema (nezha-serve / benchmarks/serving.py): the scheduler
# pre-registers this full instrument set, so a summary that carries the
# marker counter must carry ALL of them — dashboards key on the names
# (ttft, tpot, queue_depth, batch_occupancy, rejected_total, errors, ...).
_SERVE_MARKER = "serve.admitted_total"
_SERVE_COUNTERS = {"serve.admitted_total", "serve.rejected_total",
                   "serve.expired_total", "serve.retired_total",
                   "serve.tokens_total", "serve.prefill.chunks_total",
                   "serve.errors_total", "serve.step_retries_total",
                   "faults.injected_total",
                   # Paged-KV pool (PR 8): requests that took cached
                   # prefix references instead of re-prefilling, and
                   # copy-on-write block copies. Layout-invariant: a
                   # dense-layout run reports 0s, never omits them.
                   "serve.kv.prefix_hits_total",
                   "serve.kv.cow_copies_total",
                   # Cross-replica KV migration (PR 11, disaggregated
                   # prefill/decode tiers): committed installs and
                   # their int8-wire bytes. Topology-invariant: a
                   # homogeneous run reports 0s, never omits them.
                   "serve.kv.migrations_total",
                   "serve.kv.migration_bytes",
                   # Tiered KV host spill (PR 15): trie blocks demoted
                   # to host RAM on eviction / promoted back on a
                   # returning prefix hit. Knob-invariant: runs with
                   # no host tier report 0s, never omit them.
                   "serve.kv.demotions_total",
                   "serve.kv.promotions_total",
                   # Fleet-wide KV reuse (PR 17, serve/fleetcache):
                   # requests that reused cached prefix blocks, split
                   # by tier of origin (own device trie / own host
                   # tier / a sibling's peer pull), plus the wire
                   # bytes peer pulls installed. Knob-invariant:
                   # single-replica and affinity-off runs report 0s,
                   # never omit them.
                   "serve.kv.fleet_hits_total",
                   "serve.kv.fleet_hits_device_total",
                   "serve.kv.fleet_hits_host_total",
                   "serve.kv.fleet_hits_peer_total",
                   "serve.kv.pull_bytes",
                   # Speculative decoding (PR 13): draft tokens
                   # proposed and accepted across all verify windows.
                   # Knob-invariant: a non-speculative run reports 0s,
                   # never omits them.
                   "serve.spec.draft_tokens_total",
                   "serve.spec.accepted_total",
                   # Tensor-sharded serving (PR 14): trace-shape
                   # estimate of the cross-shard collective payload the
                   # mesh moved. Topology-invariant: single-device runs
                   # report 0, never omit it.
                   "serve.mesh.collective_bytes",
                   # Flash-prefill kernel (PR 18): per-layer int8 K/V
                   # block writes fused into the kernel epilogue
                   # instead of the gather/requant round-trip. 0 on
                   # the XLA prefill path or a non-int8 pool.
                   "serve.prefill.fused_writes_total",
                   # Sequence-sharded prefill (PR 20): ppermute hops
                   # the ring variant's chunks paid. Mode-invariant:
                   # replicated and ulysses runs report 0, never omit
                   # it.
                   "serve.prefill.ring_hops_total",
                   # Multi-tenant scheduling (PR 19): decodes suspended
                   # to the trie/host tier for a higher-priority
                   # admission, suspends re-admitted, and per-tenant
                   # typed queue-cap sheds (also counted into
                   # rejected_total — that counter stays the ALL-sheds
                   # ledger). Knob-invariant: preemption-off runs
                   # report 0s, never omit them.
                   "serve.preemptions_total",
                   "serve.resumes_total",
                   "serve.tenant_over_limit_total"}
_SERVE_GAUGES = {"serve.queue_depth", "serve.batch_occupancy",
                 "serve.kv.blocks_used",
                 # KV quantization (PR 9): device bytes the resident KV
                 # holds and the storage width in bits (8 = int8 blocks
                 # + per-block scales, 16/32 = plain bf16/f32 pools).
                 # Layout/dtype-invariant: every serving run reports
                 # them.
                 "serve.kv.bytes_resident", "serve.kv.quant_bits",
                 # Tiered KV host spill (PR 15): occupancy of the
                 # host-side LRU of demoted blocks (0 without a tier).
                 "serve.kv.host_blocks_used",
                 "serve.kv.host_bytes_resident",
                 # Tensor-sharded serving (PR 14): the mesh size this
                 # engine spans (1 = classic single-device engine).
                 "serve.mesh.devices",
                 # Flash-prefill kernel (PR 18): 1 when paged prefill
                 # chunks dispatch through the Pallas kernel, 0 on the
                 # composed XLA path — dashboards label the prefill
                 # line with the active impl from this alone.
                 "serve.prefill.kernel_active",
                 # Sequence-sharded prefill (PR 20): the mesh shards
                 # each prefill chunk spans — 0 in replicated mode, M
                 # in sequence mode on a 1xM mesh. Dashboards label
                 # the prefill line's parallelism mode from this
                 # alone.
                 "serve.prefill.seq_shards",
                 # Multi-tenant scheduling (PR 19): requests currently
                 # suspended awaiting resume (0 with preemption off).
                 "serve.preempted_live"}
_SERVE_HISTOGRAMS = {"serve.ttft_s", "serve.tpot_s",
                     "serve.prefill.bucket_len",
                     # Decode-horizon instruments (PR 5): host time
                     # between consecutive step dispatches, and the
                     # tokens-per-dispatch ceiling each block ran at.
                     "serve.host_gap_s", "serve.decode.horizon",
                     # Per-block max-abs dequant error sampled at each
                     # prefill-chunk write (count 0 on bf16 runs).
                     "serve.kv.quant_error",
                     # Speculative decoding (PR 13): accepted-prefix
                     # length per verify window, in DRAFT tokens
                     # (tokens-per-verify = value + 1; count 0 on
                     # non-speculative runs).
                     "serve.spec.accepted_len",
                     # Multi-tenant scheduling (PR 19): the per-
                     # priority-class TTFT split (every first token
                     # lands in serve.ttft_s AND its class's
                     # histogram) — the view that shows interactive
                     # latency holding while batch absorbs preemption.
                     "serve.ttft_s.interactive", "serve.ttft_s.batch",
                     "serve.ttft_s.background"}

# Router-run schema (nezha-serve --replicas N / benchmarks/serving.py
# --replicas): the supervisor/router pair pre-registers this full set,
# so a summary carrying the marker counter must carry ALL of it — a run
# with zero failovers still reports failovers_total = 0.
_ROUTER_MARKER = "router.retries_total"
_ROUTER_COUNTERS = {"router.retries_total", "router.failovers_total",
                    "router.replica_restarts_total",
                    # Disaggregated topologies: local-decode (and
                    # no-prefill-tier) degradations — typed fallbacks,
                    # 0 on homogeneous runs.
                    "router.migrate_fallbacks_total",
                    # Fleet-wide KV reuse (PR 17): admissions where
                    # the affinity scorer overrode the least-loaded
                    # pick (coverage win or cold consistent-hash
                    # placement). 0 with affinity routing off.
                    "router.affinity_wins_total"}
_ROUTER_GAUGES = {"router.replicas_live",
                  # Elastic autoscale (PR 19): the replica count the
                  # supervisor's control loop is steering toward
                  # (equal to the configured size when autoscale is
                  # off).
                  "router.autoscale_target"}
_ROUTER_HISTOGRAMS = {"router.route_s",
                      # The queueing-delay split of the disaggregated
                      # pipeline: time to the parked prefill answer vs
                      # the decode replica's TTFT for the migrated
                      # request (both empty on homogeneous runs).
                      "router.prefill_wait_s", "router.decode_wait_s"}

# Dist-run schema: any run that touched the coordinator (any dist.*
# counter present — join() pre-registers the pair) must carry the full
# failure-accounting set, so a world that never retried still reports
# join_retries_total = 0.
_DIST_COUNTERS = {"dist.join_retries_total", "dist.heartbeat_lost_total"}

# Checkpoint-layer counters: pinned for the SOURCE rule only (run-dir
# summaries carry them ad hoc — a training run that never saw a corrupt
# checkpoint reports nothing, so there is no marker-counter contract to
# validate in a capture).
_CHECKPOINT_COUNTERS = {"checkpoint.corrupt_total"}

# Watchdog/SLO self-instrumentation (PR 16): pinned for the SOURCE rule
# only — they appear only in runs that started a watchdog thread, so
# there is no marker-counter contract in captures.
_OBS_COUNTERS = {"watchdog.checks_total", "watchdog.events_total",
                 "watchdog.check_errors_total",
                 "slo.evaluations_total", "slo.violations_total"}
_OBS_GAUGES = {"slo.burn_rate_max"}

# Span-name registry for the namespaces this module owns: spans under
# serve./checkpoint./dist./router. are an interface (reports and
# dashboards key on them), so an unknown name in those namespaces is
# drift — add new spans HERE (and to the emitting layer's docs)
# deliberately.
_PINNED_SPAN_PREFIXES = ("serve.", "checkpoint.", "dist.", "router.")
_PINNED_SPANS = {
    "serve.prefill", "serve.decode_attention", "serve.drain",
    "checkpoint.save", "checkpoint.verify",
    "dist.join", "dist.barrier", "dist.failure", "dist.leave",
    "router.drain",
    # One span per disaggregated-pipeline orchestration: prefill
    # dispatch -> KV migration -> decode answer (attrs carry src/dst
    # rids, wire bytes, and any degradation taken).
    "router.migrate",
    # Distributed request tracing (PR 12): the per-request lifecycle
    # fragments nezha-telemetry --trace stitches into one timeline.
    # Every one carries trace_id/span_id (and usually a request_id
    # attr); emitted ONLY for traced requests, so volume follows
    # --trace-sample.
    "router.request",        # the root fragment, minted at the router
    "serve.queue_wait",      # submit -> admission
    "serve.prefill.chunk",   # one per prefill bucket dispatch
    "serve.park",            # prefill_only park -> ack/resume/TTL/drain
    "serve.kv_export",       # source side of the migration pull
    "serve.kv_install",      # decode side: export POST+install+ACK
    "serve.decode_window",   # one per decode dispatch the request rode
    "serve.decode",          # decode residency + first-token milestone
    # Tensor-sharded serving (PR 14): the train->serve checkpoint
    # resharding window (nezha-reshard / nezha-serve --mesh startup) —
    # attrs carry source format, step, and mesh size.
    "serve.reshard_s",
    # Tiered KV host spill (PR 15): one span per host->device
    # promotion — the async-copy window dispatched ahead of the
    # bucketed prefill (attrs carry the block count).
    "serve.kv.promote_s",
    # Fleet-wide KV reuse (PR 17): one span per near-miss peer pull
    # the router orchestrated — brackets the whole forward-with-
    # pull_from hop (attrs carry src/dst rids, blocks, wire bytes,
    # and whether the replica degraded to a cold prefill).
    "router.kv_pull_s",
    # Flash-prefill kernel (PR 18): brackets one chunk's dispatch
    # through the Pallas prefill program (attrs carry the bucket
    # width). Absent entirely on the XLA prefill path.
    "serve.prefill.kernel_s",
    # Sequence-sharded prefill (PR 20): brackets one whole prefill()
    # under prefill_mode=sequence — every chunk of the prompt sharded
    # over the mesh's sequence axis. Absent entirely in replicated
    # mode.
    "serve.prefill.seq_s",
    # Multi-tenant scheduling (PR 19): brackets one preemption — trie
    # indexing of the victim's bound blocks through slot release
    # (attrs carry the victim's request_id, priority, and emitted
    # token count). Absent entirely with preemption off.
    "serve.preempt_s",
}

# Namespaces whose METRIC names (counter/gauge/histogram) the source
# rule pins, with the full membership per instrument kind.
PINNED_METRIC_PREFIXES = ("serve.", "router.", "dist.", "checkpoint.",
                          "watchdog.", "slo.")
PINNED_COUNTERS = (_SERVE_COUNTERS | _ROUTER_COUNTERS | _DIST_COUNTERS
                   | _CHECKPOINT_COUNTERS | _OBS_COUNTERS)
PINNED_GAUGES = _SERVE_GAUGES | _ROUTER_GAUGES | _OBS_GAUGES
PINNED_HISTOGRAMS = _SERVE_HISTOGRAMS | _ROUTER_HISTOGRAMS
PINNED_SPANS = _PINNED_SPANS
PINNED_SPAN_PREFIXES = _PINNED_SPAN_PREFIXES

# ------------------------------------------------- events.jsonl schema
# The typed watchdog/SLO event stream (PR 16; obs/registry.record_event
# -> obs/sink.write_event). Kinds under the watchdog./slo. namespaces
# are an interface — alert routing and nezha-telemetry --slo key on
# them — so the registry below is the ONLY place new kinds are minted
# (the source lint rule checks literal record_event kinds against it).
EVENT_SCHEMA_VERSION = 1
EVENT_KIND_PREFIXES = ("watchdog.", "slo.")
EVENT_KINDS = {
    "watchdog.queue_depth_sustained",   # queue never drained a window
    "watchdog.ttft_regression",         # p99 vs trailing baseline
    "watchdog.replica_flap",            # restarts-per-window threshold
    "watchdog.slo_burn",                # error-budget burn-rate alert
    "slo.eval",                         # one record per SLO evaluation
}
EVENT_SEVERITIES = ("info", "warning", "critical")


def check_events_jsonl(path: str, errors: List[str]) -> None:
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                errors.append(f"events.jsonl:{i}: not valid JSON")
                continue
            if not isinstance(rec, dict):
                errors.append(f"events.jsonl:{i}: not an object")
                continue
            if rec.get("event_schema_version") != EVENT_SCHEMA_VERSION:
                errors.append(
                    f"events.jsonl:{i}: event_schema_version must be "
                    f"{EVENT_SCHEMA_VERSION}, got "
                    f"{rec.get('event_schema_version')!r}")
            if not _is_num(rec.get("ts")):
                errors.append(f"events.jsonl:{i}: 'ts' must be a number")
            kind = rec.get("kind")
            if not isinstance(kind, str) or not kind:
                errors.append(f"events.jsonl:{i}: 'kind' must be a "
                              f"non-empty string")
            elif (kind.startswith(EVENT_KIND_PREFIXES)
                    and kind not in EVENT_KINDS):
                errors.append(f"events.jsonl:{i}: kind {kind!r} is not "
                              f"in the pinned event registry "
                              f"(EVENT_KINDS) for its namespace")
            if rec.get("severity") not in EVENT_SEVERITIES:
                errors.append(f"events.jsonl:{i}: 'severity' must be one "
                              f"of {list(EVENT_SEVERITIES)}, got "
                              f"{rec.get('severity')!r}")
            if not isinstance(rec.get("source"), str):
                errors.append(f"events.jsonl:{i}: 'source' must be a "
                              f"string")
            if not isinstance(rec.get("detail"), dict):
                errors.append(f"events.jsonl:{i}: 'detail' must be an "
                              f"object")


# --------------------------------------------- /metrics exposition pins
# The Prometheus-text exposition contract (obs/timeseries.py renders
# it; a unit test pins both sides to these values). Every sample name
# carries the prefix; windowed samples are labeled with one of the
# window labels; histogram quantile samples with one of the quantile
# labels. Scrapers (nezha-top, external Prometheus) key on this shape.
EXPOSITION_PREFIX = "nezha_"
EXPOSITION_WINDOW_LABELS = ("10s", "60s", "300s")
EXPOSITION_QUANTILE_LABELS = ("p50", "p90", "p99")

_EXPO_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{([^}]*)\})?\s+(-?[0-9.eE+]+"
    r"|[+-]?Inf|NaN)$")
_EXPO_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def check_metrics_exposition(text: str) -> List[str]:
    """-> schema violations of one ``GET /metrics`` body (empty =
    valid): every non-comment line a well-formed sample, every name
    under the pinned prefix, window/quantile label values drawn from
    the pinned vocabularies."""
    errors: List[str] = []
    for i, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _EXPO_SAMPLE_RE.match(line)
        if not m:
            errors.append(f"metrics:{i}: not a valid exposition sample")
            continue
        name, raw_labels = m.group(1), m.group(2)
        if not name.startswith(EXPOSITION_PREFIX):
            errors.append(f"metrics:{i}: sample name {name!r} lacks the "
                          f"pinned {EXPOSITION_PREFIX!r} prefix")
        labels = dict(_EXPO_LABEL_RE.findall(raw_labels)) \
            if raw_labels else {}
        w = labels.get("window")
        if w is not None and w not in EXPOSITION_WINDOW_LABELS:
            errors.append(f"metrics:{i}: window label {w!r} not in "
                          f"{list(EXPOSITION_WINDOW_LABELS)}")
        q = labels.get("quantile")
        if q is not None and q not in EXPOSITION_QUANTILE_LABELS:
            errors.append(f"metrics:{i}: quantile label {q!r} not in "
                          f"{list(EXPOSITION_QUANTILE_LABELS)}")
    return errors


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _check_span(rec, where: str, errors: List[str]) -> None:
    if not isinstance(rec, dict):
        errors.append(f"{where}: span record is not an object")
        return
    if not isinstance(rec.get("name"), str):
        errors.append(f"{where}: span 'name' must be a string")
    for k in ("t0", "t1", "dur_s"):
        if not _is_num(rec.get(k)):
            errors.append(f"{where}: span '{k}' must be a number")
    if (_is_num(rec.get("t0")) and _is_num(rec.get("t1"))
            and rec["t1"] < rec["t0"]):
        errors.append(f"{where}: span t1 < t0")
    if not isinstance(rec.get("attrs"), dict):
        errors.append(f"{where}: span 'attrs' must be an object")
    # Trace record fields (distributed tracing, PR 12): optional — an
    # untraced span carries none of them — but when present they must
    # be non-empty strings, a trace_id never rides without its span_id,
    # and a parent link never rides without a trace (the stitcher keys
    # on exactly this shape).
    for k in ("trace_id", "span_id", "parent_id"):
        if k in rec and not (isinstance(rec[k], str) and rec[k]):
            errors.append(f"{where}: span {k!r} must be a non-empty "
                          f"string when present")
    if "trace_id" in rec and "span_id" not in rec:
        errors.append(f"{where}: span carries trace_id without span_id")
    if "parent_id" in rec and "trace_id" not in rec:
        errors.append(f"{where}: span carries parent_id without "
                      f"trace_id")
    name = rec.get("name")
    if (isinstance(name, str) and name.startswith(_PINNED_SPAN_PREFIXES)
            and name not in _PINNED_SPANS):
        errors.append(f"{where}: span name {name!r} is not in the pinned "
                      f"span registry (_PINNED_SPANS) for its namespace")


def check_metrics_jsonl(path: str, errors: List[str]) -> None:
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                errors.append(f"metrics.jsonl:{i}: not valid JSON")
                continue
            if not isinstance(rec, dict):
                errors.append(f"metrics.jsonl:{i}: not an object")
                continue
            step = rec.get("step")
            if not (isinstance(step, int) and not isinstance(step, bool)
                    and step >= 0):
                errors.append(f"metrics.jsonl:{i}: 'step' must be an int "
                              f">= 0, got {step!r}")
            if not _is_num(rec.get("ts")):
                errors.append(f"metrics.jsonl:{i}: 'ts' must be a number")
            for k, v in rec.items():
                if not isinstance(v, (int, float, str, bool, type(None))):
                    errors.append(f"metrics.jsonl:{i}: value for {k!r} is "
                                  f"not a JSON scalar")


def check_spans_jsonl(path: str, errors: List[str]) -> None:
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                errors.append(f"spans.jsonl:{i}: not valid JSON")
                continue
            _check_span(rec, f"spans.jsonl:{i}", errors)


def check_summary_json(path: str, errors: List[str]) -> None:
    try:
        with open(path) as f:
            summary = json.load(f)
    except ValueError:
        errors.append("summary.json: not valid JSON")
        return
    if not isinstance(summary, dict):
        errors.append("summary.json: not an object")
        return
    if summary.get("schema_version") != SCHEMA_VERSION:
        errors.append(f"summary.json: schema_version must be "
                      f"{SCHEMA_VERSION}, got "
                      f"{summary.get('schema_version')!r}")
    missing = _SUMMARY_KEYS - set(summary)
    if missing:
        errors.append(f"summary.json: missing key(s) {sorted(missing)}")
    for section in ("counters", "gauges"):
        vals = summary.get(section)
        if not isinstance(vals, dict):
            errors.append(f"summary.json: '{section}' must be an object")
            continue
        for k, v in vals.items():
            if not _is_num(v):
                errors.append(f"summary.json: {section}[{k!r}] must be a "
                              f"number")
    hists = summary.get("histograms")
    if isinstance(hists, dict):
        for k, h in hists.items():
            if not isinstance(h, dict) or not _HIST_KEYS <= set(h):
                errors.append(f"summary.json: histograms[{k!r}] must "
                              f"carry {sorted(_HIST_KEYS)}")
    else:
        errors.append("summary.json: 'histograms' must be an object")
    coll = summary.get("collectives")
    if isinstance(coll, dict):
        for op, row in coll.items():
            if not isinstance(row, dict):
                errors.append(f"summary.json: collectives[{op!r}] must be "
                              f"an object")
                continue
            for field in ("calls", "payload_bytes"):
                if field in row and not _is_num(row[field]):
                    errors.append(f"summary.json: collectives[{op!r}]"
                                  f".{field} must be a number")
    else:
        errors.append("summary.json: 'collectives' must be an object")
    cc = summary.get("compile_cache")
    if isinstance(cc, dict):
        for field in ("hits", "misses"):
            v = cc.get(field)
            if not (isinstance(v, int) and not isinstance(v, bool)):
                errors.append(f"summary.json: compile_cache.{field} must "
                              f"be an int")
    else:
        errors.append("summary.json: 'compile_cache' must be an object")
    slowest = summary.get("slowest_spans")
    if isinstance(slowest, list):
        for j, rec in enumerate(slowest):
            _check_span(rec, f"summary.json: slowest_spans[{j}]", errors)
    else:
        errors.append("summary.json: 'slowest_spans' must be a list")
    _check_serving(summary, errors)
    _check_router(summary, errors)
    _check_dist(summary, errors)


def _check_serving(summary: dict, errors: List[str]) -> None:
    """Serving-run summaries (marker: serve.admitted_total) must carry
    the complete pinned serve instrument set."""
    counters = summary.get("counters")
    if not isinstance(counters, dict) or _SERVE_MARKER not in counters:
        return
    for name in sorted(_SERVE_COUNTERS - set(counters)):
        errors.append(f"summary.json: serving run missing counter "
                      f"{name!r}")
    gauges = summary.get("gauges")
    gauges = gauges if isinstance(gauges, dict) else {}
    for name in sorted(_SERVE_GAUGES - set(gauges)):
        errors.append(f"summary.json: serving run missing gauge {name!r}")
    hists = summary.get("histograms")
    hists = hists if isinstance(hists, dict) else {}
    for name in sorted(_SERVE_HISTOGRAMS - set(hists)):
        errors.append(f"summary.json: serving run missing histogram "
                      f"{name!r}")


def _check_router(summary: dict, errors: List[str]) -> None:
    """Router-run summaries (marker: router.retries_total) must carry
    the complete pinned router instrument set."""
    counters = summary.get("counters")
    if not isinstance(counters, dict) or _ROUTER_MARKER not in counters:
        return
    for name in sorted(_ROUTER_COUNTERS - set(counters)):
        errors.append(f"summary.json: router run missing counter "
                      f"{name!r}")
    gauges = summary.get("gauges")
    gauges = gauges if isinstance(gauges, dict) else {}
    for name in sorted(_ROUTER_GAUGES - set(gauges)):
        errors.append(f"summary.json: router run missing gauge {name!r}")
    hists = summary.get("histograms")
    hists = hists if isinstance(hists, dict) else {}
    for name in sorted(_ROUTER_HISTOGRAMS - set(hists)):
        errors.append(f"summary.json: router run missing histogram "
                      f"{name!r}")


def _check_dist(summary: dict, errors: List[str]) -> None:
    """Runs that touched the coordinator (any ``dist.*`` counter) must
    carry the complete failure-accounting counter set."""
    counters = summary.get("counters")
    if not isinstance(counters, dict):
        return
    if not any(k.startswith("dist.") for k in counters):
        return
    for name in sorted(_DIST_COUNTERS - set(counters)):
        errors.append(f"summary.json: dist run missing counter {name!r}")


# --------------------------------------------------- live /stats schema
# The GET /stats payload contract (stats schema v1). Two shapes share
# it: a REPLICA payload (obs.stats_snapshot() — one registry's live
# counters/gauges/histogram summaries) and the router's FLEET payload
# (its own snapshot + every replica's, + a summed roll-up). Extra keys
# are allowed (a replica may add its role); the pinned core may not
# drift — dashboards curl this mid-run.
STATS_SCHEMA_VERSION = 1


def _check_stats_metrics(obj: dict, where: str,
                         errors: List[str]) -> None:
    for section in ("counters", "gauges"):
        vals = obj.get(section)
        if not isinstance(vals, dict):
            errors.append(f"{where}: '{section}' must be an object")
            continue
        for k, v in vals.items():
            if not _is_num(v):
                errors.append(f"{where}: {section}[{k!r}] must be a "
                              f"number")


def _check_stats_replica(obj: dict, where: str,
                         errors: List[str]) -> None:
    if obj.get("stats_schema_version") != STATS_SCHEMA_VERSION:
        errors.append(f"{where}: stats_schema_version must be "
                      f"{STATS_SCHEMA_VERSION}, got "
                      f"{obj.get('stats_schema_version')!r}")
    if not _is_num(obj.get("ts")):
        errors.append(f"{where}: 'ts' must be a number")
    if not isinstance(obj.get("enabled"), bool):
        errors.append(f"{where}: 'enabled' must be a bool")
    _check_stats_metrics(obj, where, errors)
    hists = obj.get("histograms")
    if isinstance(hists, dict):
        for k, h in hists.items():
            if not isinstance(h, dict) or not _HIST_KEYS <= set(h):
                errors.append(f"{where}: histograms[{k!r}] must carry "
                              f"{sorted(_HIST_KEYS)}")
    else:
        errors.append(f"{where}: 'histograms' must be an object")


def check_stats_payload(obj) -> List[str]:
    """-> schema violations of one ``GET /stats`` response body (empty
    = valid). Accepts both the replica shape and the router's fleet
    shape, dispatching on ``kind``."""
    errors: List[str] = []
    if not isinstance(obj, dict):
        return ["stats payload is not an object"]
    kind = obj.get("kind")
    if kind == "replica":
        _check_stats_replica(obj, "stats", errors)
    elif kind == "fleet":
        if obj.get("stats_schema_version") != STATS_SCHEMA_VERSION:
            errors.append(f"stats: stats_schema_version must be "
                          f"{STATS_SCHEMA_VERSION}, got "
                          f"{obj.get('stats_schema_version')!r}")
        if not _is_num(obj.get("ts")):
            errors.append("stats: 'ts' must be a number")
        router = obj.get("router")
        if isinstance(router, dict):
            _check_stats_replica(router, "stats.router", errors)
        else:
            errors.append("stats: 'router' must be an object")
        replicas = obj.get("replicas")
        if isinstance(replicas, list):
            for i, row in enumerate(replicas):
                where = f"stats.replicas[{i}]"
                if not isinstance(row, dict):
                    errors.append(f"{where}: must be an object")
                    continue
                if not _is_num(row.get("rid")):
                    errors.append(f"{where}: 'rid' must be a number")
                for k in ("role", "state"):
                    if not isinstance(row.get(k), str):
                        errors.append(f"{where}: {k!r} must be a "
                                      f"string")
                if not isinstance(row.get("healthy"), bool):
                    errors.append(f"{where}: 'healthy' must be a bool")
                stats = row.get("stats")
                if stats is not None:      # None = member unreachable
                    if isinstance(stats, dict):
                        _check_stats_replica(stats, where + ".stats",
                                             errors)
                    else:
                        errors.append(f"{where}: 'stats' must be an "
                                      f"object or null")
        else:
            errors.append("stats: 'replicas' must be a list")
        fleet = obj.get("fleet")
        if isinstance(fleet, dict):
            _check_stats_metrics(fleet, "stats.fleet", errors)
        else:
            errors.append("stats: 'fleet' must be an object")
    else:
        errors.append(f"stats: 'kind' must be 'replica' or 'fleet', "
                      f"got {kind!r}")
    return errors


def check_run_dir(run_dir: str) -> List[str]:
    """-> list of schema violations (empty = valid). All three original
    artifacts are required — a run dir missing one is itself a
    violation. ``events.jsonl`` (PR 16) is validated when present but
    never required, so pre-PR16 captures stay valid."""
    errors: List[str] = []
    for name, checker in (("metrics.jsonl", check_metrics_jsonl),
                          ("spans.jsonl", check_spans_jsonl),
                          ("summary.json", check_summary_json)):
        path = os.path.join(run_dir, name)
        if not os.path.isfile(path):
            errors.append(f"{name}: missing from {run_dir}")
            continue
        checker(path, errors)
    events = os.path.join(run_dir, "events.jsonl")
    if os.path.isfile(events):
        check_events_jsonl(events, errors)
    return errors
