"""Findings, the rule registry, and the runner.

A rule is a named check over the :class:`~nezha_tpu.analysis.index.
SourceIndex` that returns :class:`Finding`s. Registration is one
decorator; ``nezha-lint --list-rules`` and the RUNBOOK rule table are
generated from the same registry, so a rule cannot exist without a
name and a one-line contract.

Finding keys are deliberately LINE-FREE: ``rule:file:symbol:detail``
(symbol = enclosing def/class qualname, detail = the flagged name or a
short discriminator). A suppression in ``tools/lint_baseline.json``
therefore survives unrelated edits that shift line numbers, but dies
with the code it describes — moving the violation to another function
or renaming the flagged state invalidates the key, and the stale entry
fails the lint until the baseline is updated deliberately.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

from nezha_tpu.analysis.index import SourceIndex


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One rule violation at one source location."""

    file: str             # repo-relative path
    line: int             # 1-based; 0 = whole-file finding
    rule: str
    message: str
    symbol: str = ""      # enclosing def/class qualname ("" = module)
    detail: str = ""      # stable discriminator within the symbol

    @property
    def key(self) -> str:
        """Line-free suppression key for the baseline."""
        return f"{self.rule}:{self.file}:{self.symbol}:{self.detail}"

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "symbol": self.symbol, "detail": self.detail,
                "message": self.message, "key": self.key}


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    contract: str       # one line: the invariant this rule enforces
    check: Callable[[SourceIndex], List[Finding]]


RULES: Dict[str, Rule] = {}


def rule(name: str, contract: str):
    """Register a rule: ``@rule("lock-discipline", "writes to ...")``
    over a ``check(index) -> List[Finding]`` function."""
    def deco(fn):
        if name in RULES:
            raise ValueError(f"duplicate rule name {name!r}")
        RULES[name] = Rule(name=name, contract=contract, check=fn)
        return fn
    return deco


def run_rules(index: SourceIndex,
              names: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the selected rules (default: all) over one index. Files that
    failed to parse surface as ``syntax`` findings regardless of the
    selection — a rule cannot vouch for source it never saw."""
    unknown = [n for n in (names or []) if n not in RULES]
    if unknown:
        raise KeyError(
            f"unknown rule(s) {unknown}; available: {sorted(RULES)}")
    findings = [Finding(file=rel, line=0, rule="syntax",
                        message=f"file does not parse: {msg}",
                        detail="parse")
                for rel, msg in index.parse_errors]
    for name in sorted(names if names is not None else RULES):
        findings.extend(RULES[name].check(index))
    return sorted(findings)


def load_rules() -> None:
    """Import every built-in rule module (each registers itself)."""
    from nezha_tpu.analysis.rules import (  # noqa: F401
        bench_records, donation, fault_points, host_sync, locks,
        mesh_tables, telemetry, traced_branch)
