"""Committed BENCH_*.json hygiene — the validation core.

BENCH_r03–r05 taught the lesson: a bench run that DIED (the axon TPU
tunnel was down, ``jax.devices()`` raised) was committed as if it were
a measurement, and the perf trajectory silently carried three crash
records until a reader noticed the ``rc: 1``. Every committed record
must be

- **valid JSON**, and
- a **real measurement** — either a driver round record (``rc == 0``
  with a non-null parsed metric) or a ``nezha-bench`` baseline
  (non-empty ``by_platform`` slots), and
- **platform-labeled** — a top-level ``platform``/``backend`` field, a
  platform inside ``parsed``, or ``by_platform`` keys — so a CPU
  fallback number can never masquerade as (or overwrite) a TPU anchor,

UNLESS the file is explicitly listed in ``BENCH_NOTES.md`` under a
``## Superseded records`` heading (one ``- FILENAME — reason`` bullet
per record). Superseding is the ONLY way to keep a bad record
committed: the crash stays visible as history, the notes say why, and
a NEW crash record fails the build the moment it lands.

Consumed by the ``bench-records`` lint rule and by the
``tools/check_bench_record.py`` shim (tier-1 via
tests/test_bench_record.py)."""

from __future__ import annotations

import glob
import json
import os
import re
from typing import List, Set

_NOTES = "BENCH_NOTES.md"
_SUPERSEDED_HEADING = "superseded records"


def superseded_records(root: str) -> Set[str]:
    """Filenames listed under BENCH_NOTES.md's ``## Superseded
    records`` heading (empty set when the file or heading is absent)."""
    path = os.path.join(root, _NOTES)
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return set()
    out: Set[str] = set()
    in_section = False
    for line in text.splitlines():
        if line.lstrip().startswith("#"):
            in_section = (_SUPERSEDED_HEADING
                          in line.lstrip("#").strip().lower())
            continue
        if in_section:
            m = re.search(r"(BENCH_\w+\.json)", line)
            if m:
                out.add(m.group(1))
    return out


def _platform_label(rec: dict) -> str:
    """The record's platform label, '' when unlabeled."""
    for key in ("platform", "backend"):
        v = rec.get(key)
        if isinstance(v, str) and v:
            return v
    parsed = rec.get("parsed")
    if isinstance(parsed, dict):
        for key in ("platform", "backend"):
            v = parsed.get(key)
            if isinstance(v, str) and v:
                return v
    by = rec.get("by_platform")
    if isinstance(by, dict) and by:
        return ",".join(sorted(str(k) for k in by))
    return ""


def check_record(path: str) -> List[str]:
    """-> violations for one committed record file (empty = valid)."""
    name = os.path.basename(path)
    try:
        with open(path) as f:
            rec = json.load(f)
    except OSError as e:
        return [f"{name}: unreadable ({e})"]
    except ValueError:
        return [f"{name}: not valid JSON"]
    if not isinstance(rec, dict):
        return [f"{name}: record must be a JSON object"]
    errors: List[str] = []
    if "rc" in rec:
        # Driver round record: {n, cmd, rc, tail, parsed}.
        if rec.get("rc") != 0:
            errors.append(
                f"{name}: CRASH RECORD (rc={rec.get('rc')!r}) — not a "
                f"measurement; mark it superseded in {_NOTES} or drop "
                f"it")
        elif not isinstance(rec.get("parsed"), dict) \
                or "value" not in rec["parsed"]:
            errors.append(
                f"{name}: rc=0 but no parsed metric — the run printed "
                f"nothing measurable")
    elif "by_platform" in rec:
        by = rec.get("by_platform")
        if not isinstance(by, dict) or not by:
            errors.append(f"{name}: 'by_platform' must be a non-empty "
                          f"object of per-platform slots")
    else:
        errors.append(
            f"{name}: unrecognized record shape (neither a driver "
            f"round record with 'rc' nor a nezha-bench 'by_platform' "
            f"baseline)")
    if not errors and not _platform_label(rec):
        errors.append(
            f"{name}: no platform label (top-level 'platform'/"
            f"'backend', parsed.platform, or by_platform keys) — "
            f"unlabeled numbers cannot be gated per-platform")
    return errors


def check_dir(root: str) -> List[str]:
    """Validate every committed BENCH_*.json under ``root`` (skipping
    records superseded in BENCH_NOTES.md). -> violations."""
    errors: List[str] = []
    skip = superseded_records(root)
    paths = sorted(glob.glob(os.path.join(root, "BENCH_*.json")))
    if not paths:
        return [f"no BENCH_*.json records found under {root}"]
    for path in paths:
        if os.path.basename(path) in skip:
            continue
        errors.extend(check_record(path))
    return errors
