"""Thin re-export: the metrics primitives moved into the telemetry
subsystem (``nezha_tpu.obs.metrics``) so the registry, run sinks, and the
step-timing/JSONL tooling live in one layer. Import from here (or
``nezha_tpu.utils``) keeps working."""

from nezha_tpu.obs.metrics import (  # noqa: F401
    MetricsLogger,
    StepTimer,
    read_metrics,
)

__all__ = ["MetricsLogger", "StepTimer", "read_metrics"]
