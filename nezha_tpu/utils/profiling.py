"""Thin re-export: the jax.profiler wrappers moved into the telemetry
subsystem (``nezha_tpu.obs.trace``). Import from here (or
``nezha_tpu.utils``) keeps working."""

from nezha_tpu.obs.trace import (  # noqa: F401
    Tracer,
    annotate,
    profile_trace,
)

__all__ = ["Tracer", "annotate", "profile_trace"]
