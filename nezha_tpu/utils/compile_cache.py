"""Persistent XLA compilation cache setup, shared by every entry point.

One helper so the suite (tests/conftest.py), the driver entries
(__graft_entry__.py), and the bench harness (bench.py) cannot drift on the
cache location or the min-compile-time threshold (JAX's 1.0 s default
would silently skip the sub-second tiny-preset programs the suite and
dryrun compile most — and those recur by the hundred across the suite's
engine builds, so the threshold here is 0: cache every compile).

The cache is SAME-MACHINE only — serialized executables embed host CPU
features — so it lives in the (gitignored) repo-root ``.jax_cache/``;
override with ``JAX_COMPILATION_CACHE_DIR``.
"""

from __future__ import annotations

import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def enable_persistent_compile_cache(min_compile_secs: float = 0.0) -> str:
    """Point jax at the repo's persistent compile cache; returns the dir.

    Call any time before the programs of interest compile (the cache is
    consulted per-compile, not at backend init). Safe no-op on jax
    versions without the knobs.
    """
    import jax

    cache = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(_REPO_ROOT, ".jax_cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
    except Exception:
        pass
    return cache
