"""Rank-tagged structured logging.

Every process in a multi-host job logs through here; records carry the
rank set at rendezvous so interleaved output from a pod stays attributable
(the role the reference's per-rank log prefixes played).
"""

from __future__ import annotations

import logging
import os
import sys

_RANK: int = int(os.environ.get("NEZHA_RANK", "0"))
_CONFIGURED = False


def set_rank(rank: int) -> None:
    """Record this process's rank (call after dist.join)."""
    global _RANK
    _RANK = int(rank)


class _RankFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        record.rank = _RANK
        return True


def get_logger(name: str = "nezha_tpu") -> logging.Logger:
    """Logger with ``[rank N]``-tagged lines on stderr. Level from
    ``$NEZHA_LOG_LEVEL`` (default INFO)."""
    global _CONFIGURED
    logger = logging.getLogger(name)
    if not _CONFIGURED:
        root = logging.getLogger("nezha_tpu")
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(
            "%(asctime)s [rank %(rank)s] %(levelname)s %(name)s: %(message)s"))
        handler.addFilter(_RankFilter())
        root.addHandler(handler)
        root.setLevel(os.environ.get("NEZHA_LOG_LEVEL", "INFO").upper())
        root.propagate = False
        _CONFIGURED = True
    return logger
