"""Auxiliary subsystems: tracing/profiling, metrics, structured logging
(SURVEY.md §5 — the reference's evidence here was thin, so this package is
sized to what a training framework needs on TPU: XLA-aware profiling via
jax.profiler, JSONL metrics with async-dispatch-aware step timing, and a
rank-tagged logger)."""

from nezha_tpu.utils.compile_cache import enable_persistent_compile_cache
from nezha_tpu.utils.logging import get_logger, set_rank
from nezha_tpu.utils.metrics import MetricsLogger, StepTimer
from nezha_tpu.utils.profiling import Tracer, annotate, profile_trace

__all__ = [
    "enable_persistent_compile_cache",
    "get_logger",
    "set_rank",
    "MetricsLogger",
    "StepTimer",
    "Tracer",
    "annotate",
    "profile_trace",
]
