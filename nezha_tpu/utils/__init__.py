"""Auxiliary subsystems: tracing/profiling, metrics, structured logging
(SURVEY.md §5 — the reference's evidence here was thin, so this package is
sized to what a training framework needs on TPU: XLA-aware profiling via
jax.profiler, JSONL metrics with async-dispatch-aware step timing, and a
rank-tagged logger).

The metrics/profiling primitives now live in the unified telemetry
subsystem (``nezha_tpu.obs`` — registry, run-scoped sinks, and the
``nezha-telemetry`` report CLI); this package re-exports them under their
long-standing names."""

from nezha_tpu.utils.compile_cache import enable_persistent_compile_cache
from nezha_tpu.utils.logging import get_logger, set_rank
from nezha_tpu.utils.metrics import MetricsLogger, StepTimer
from nezha_tpu.utils.profiling import Tracer, annotate, profile_trace

__all__ = [
    "enable_persistent_compile_cache",
    "get_logger",
    "set_rank",
    "MetricsLogger",
    "StepTimer",
    "Tracer",
    "annotate",
    "profile_trace",
]
