"""Dynamic MLM masking over real token streams — BERT pretraining data.

Wraps any ``{"tokens": [B, S]}`` / ``[B, S+1]`` integer-token batch stream
(the native ``TokenLoader``'s GPT-shape windows included — the trailing
next-token column is dropped) with the BERT masking recipe, re-rolled per
batch (dynamic masking, the RoBERTa refinement of BERT's static dumps):

- ``mask_rate`` of positions are selected for prediction;
- of those, 80% are replaced with ``mask_token``, 10% with a uniformly
  random id, 10% left unchanged;
- ``labels`` carry the ORIGINAL id at selected positions and -100
  elsewhere (``ops.softmax_cross_entropy_with_integer_labels``'s
  ``ignore_index`` contract, same as the synthetic generator).

The output batches are full-length (no padding), so the flash attention
path stays engaged (`BertConfig.attn_impl="auto"`).
"""

from __future__ import annotations

from typing import Iterable, Iterator

import numpy as np


def mlm_batches_from_tokens(batches: Iterable, vocab_size: int,
                            mask_token: int = 103,
                            mask_rate: float = 0.15,
                            seed: int = 0,
                            drop_last_column: bool = False) -> Iterator[dict]:
    """-> ``{"tokens", "labels", "segment_ids"}`` int32 [B, S] batches.

    ``drop_last_column=True`` for GPT-shape ``[B, S+1]`` sources (the
    native ``TokenLoader``) whose trailing next-token column MLM doesn't
    use."""
    if not 0 < mask_rate < 1:
        raise ValueError(f"mask_rate must be in (0, 1), got {mask_rate}")
    if not 0 <= mask_token < vocab_size:
        raise ValueError(f"mask_token {mask_token} outside vocab "
                         f"[0, {vocab_size})")
    r = np.random.RandomState(seed)
    for b in batches:
        tokens = np.asarray(b["tokens"] if isinstance(b, dict) else b)
        if tokens.ndim != 2:
            raise ValueError(f"expected [B, S] tokens, got {tokens.shape}")
        if drop_last_column:
            tokens = tokens[:, :-1]
        tokens = tokens.astype(np.int32, copy=True)
        if tokens.max(initial=0) >= vocab_size or tokens.min(initial=0) < 0:
            # Loud, not silent: out-of-range ids would otherwise hit the
            # embedding gather, where XLA clips/wraps indices quietly.
            raise ValueError(
                f"token ids outside [0, {vocab_size}) in the stream "
                f"(min {tokens.min()}, max {tokens.max()}; wrong "
                f"--data-dir for this model?)")
        sel = r.rand(*tokens.shape) < mask_rate
        labels = np.where(sel, tokens, -100).astype(np.int32)
        roll = r.rand(*tokens.shape)
        masked = sel & (roll < 0.8)
        random_sub = sel & (roll >= 0.8) & (roll < 0.9)
        tokens[masked] = mask_token
        tokens[random_sub] = r.randint(
            0, vocab_size, int(random_sub.sum()), dtype=np.int32)
        yield {"tokens": tokens, "labels": labels,
               "segment_ids": np.zeros_like(tokens)}
