"""Synthetic benchmark input pipelines.

The perf benchmarks (BASELINE.json configs 2-5) measure device throughput,
not dataset IO, and this image has no network egress — so ImageNet-shaped
image batches, GPT-2 token streams, and BERT MLM batches are generated
host-side deterministically. Real datasets drop in by replacing these
iterators; everything downstream (prefetcher, sharding, train step) is
identical.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


def synthetic_image_batches(batch_size: int, image_size: int = 224,
                            num_classes: int = 1000, seed: int = 0,
                            nchw: bool = False) -> Iterator[dict]:
    """ImageNet-shaped {"image": [B,H,W,3] f32, "label": [B] i32} batches."""
    r = np.random.RandomState(seed)
    # A small pool of pre-generated batches re-yielded forever: IO cost ~0,
    # matching how perf harnesses avoid input-bound numbers.
    pool = []
    for _ in range(4):
        shape = ((batch_size, 3, image_size, image_size) if nchw
                 else (batch_size, image_size, image_size, 3))
        pool.append({
            "image": r.rand(*shape).astype(np.float32),
            "label": r.randint(0, num_classes, size=batch_size).astype(np.int32),
        })
    i = 0
    while True:
        yield pool[i % len(pool)]
        i += 1


def synthetic_token_batches(batch_size: int, seq_len: int = 1024,
                            vocab_size: int = 50257, seed: int = 0) -> Iterator[dict]:
    """GPT-2-style LM batches: {"tokens": [B,S+1] i32}; model shifts for
    inputs/targets."""
    r = np.random.RandomState(seed)
    pool = [
        {"tokens": r.randint(0, vocab_size, size=(batch_size, seq_len + 1)).astype(np.int32)}
        for _ in range(4)
    ]
    i = 0
    while True:
        yield pool[i % len(pool)]
        i += 1


def synthetic_mlm_batches(batch_size: int, seq_len: int = 512,
                          vocab_size: int = 30522, mask_rate: float = 0.15,
                          seed: int = 0, mask_token: int = 103) -> Iterator[dict]:
    """BERT MLM batches: tokens with [MASK]s, labels -100 where unmasked."""
    r = np.random.RandomState(seed)
    pool = []
    for _ in range(4):
        tokens = r.randint(0, vocab_size, size=(batch_size, seq_len)).astype(np.int32)
        labels = np.full_like(tokens, -100)
        mask = r.rand(batch_size, seq_len) < mask_rate
        labels[mask] = tokens[mask]
        tokens = tokens.copy()
        tokens[mask] = mask_token
        # No padding_mask: these are full-length packed batches, so a mask
        # would be all-True — semantically identical to none, but its mere
        # presence forces composed-XLA attention (the flash kernel has no
        # arbitrary-mask path; see BertConfig.attn_impl).
        pool.append({
            "tokens": tokens,
            "labels": labels,
            "segment_ids": np.zeros_like(tokens),
        })
    i = 0
    while True:
        yield pool[i % len(pool)]
        i += 1
