"""MNIST loader.

Reads the standard IDX files from ``$NEZHA_DATA_DIR/mnist`` (or
``~/.cache/nezha_tpu/mnist``) if present; with no dataset on disk (this image
has no network egress) it falls back to a deterministic synthetic set with
MNIST's shapes and a learnable class structure, so the end-to-end MLP config
(BASELINE.json config 1) trains and its loss measurably drops.
"""

from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path
from typing import Iterator, Tuple

import numpy as np


def _data_dir() -> Path:
    root = os.environ.get("NEZHA_DATA_DIR")
    if root:
        return Path(root) / "mnist"
    return Path.home() / ".cache" / "nezha_tpu" / "mnist"


def _read_idx(path: Path) -> np.ndarray:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(dims)


def _find(dirpath: Path, stem: str) -> Path | None:
    for suffix in ("", ".gz"):
        p = dirpath / (stem + suffix)
        if p.exists():
            return p
    return None


def _synthetic_mnist(n_train: int = 8192, n_test: int = 1024):
    """Class-structured synthetic digits: each class is a fixed template plus
    noise. Linearly separable enough that a training MLP's accuracy climbs."""
    rng = np.random.RandomState(0)
    templates = rng.rand(10, 28, 28).astype(np.float32)

    def make(n, seed):
        r = np.random.RandomState(seed)
        labels = r.randint(0, 10, size=n).astype(np.int32)
        images = templates[labels] + 0.3 * r.randn(n, 28, 28).astype(np.float32)
        return np.clip(images, 0.0, 1.0), labels

    xtr, ytr = make(n_train, 1)
    xte, yte = make(n_test, 2)
    return (xtr, ytr), (xte, yte)


def load_mnist() -> Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]:
    """Returns ((train_x, train_y), (test_x, test_y)); images float32 in [0,1],
    shape [N, 28, 28]."""
    d = _data_dir()
    files = {
        "train_x": _find(d, "train-images-idx3-ubyte"),
        "train_y": _find(d, "train-labels-idx1-ubyte"),
        "test_x": _find(d, "t10k-images-idx3-ubyte"),
        "test_y": _find(d, "t10k-labels-idx1-ubyte"),
    }
    if all(files.values()):
        xtr = _read_idx(files["train_x"]).astype(np.float32) / 255.0
        ytr = _read_idx(files["train_y"]).astype(np.int32)
        xte = _read_idx(files["test_x"]).astype(np.float32) / 255.0
        yte = _read_idx(files["test_y"]).astype(np.int32)
        return (xtr, ytr), (xte, yte)
    return _synthetic_mnist()


def mnist_batches(batch_size: int, split: str = "train", seed: int = 0,
                  epochs: int | None = None) -> Iterator[dict]:
    """Yields {"image": [B,28,28], "label": [B]} numpy batches, reshuffled
    each epoch."""
    (xtr, ytr), (xte, yte) = load_mnist()
    x, y = (xtr, ytr) if split == "train" else (xte, yte)
    n = x.shape[0]
    if batch_size > n:
        raise ValueError(f"batch_size {batch_size} > dataset size {n}")
    rng = np.random.RandomState(seed)
    epoch = 0
    while epochs is None or epoch < epochs:
        order = rng.permutation(n) if split == "train" else np.arange(n)
        for i in range(0, n - batch_size + 1, batch_size):
            idx = order[i:i + batch_size]
            yield {"image": x[idx], "label": y[idx]}
        epoch += 1
