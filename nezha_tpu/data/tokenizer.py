"""Network-free subword tokenizers: GPT-2 byte-level BPE and BERT WordPiece.

Closes the last data-parity gap against the reference's LM configs
(SURVEY.md §2 models rows; VERDICT r4 missing item 2): the reference's
GPT-2 124M / BERT-base workloads assume real BPE / WordPiece vocabularies,
while this repo previously packed raw bytes only. These encoders load the
STANDARD on-disk formats (``vocab.json``+``merges.txt`` for GPT-2,
``vocab.txt`` for BERT) from user-supplied files or an offline Hugging
Face checkpoint directory — no network egress, no `tokenizers` Rust
dependency. Exact-match parity with the HF slow tokenizers is pinned in
``tests/test_tokenizer.py``.

TPU relevance: tokenization is host-side dataset prep (the device sees
int32 ids either way), so the design goal is correctness + zero new deps,
not throughput; `pack_text_files`-style corpus packing runs it once,
offline.
"""

from __future__ import annotations

import functools
import json
import os
import unicodedata
from typing import Dict, Iterable, List, Sequence, Tuple

__all__ = ["GPT2BPETokenizer", "WordPieceTokenizer", "load_tokenizer",
           "GPT2_PRETOKENIZE_PATTERN"]

# GPT-2's pre-tokenization regex (contractions, letter runs, digit runs,
# punctuation runs, trailing/other whitespace). ONE definition shared by
# the encoder and the offline BPE learner — they must segment identically
# or learned merges stop matching encode-time word boundaries.
GPT2_PRETOKENIZE_PATTERN = (
    r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+|"""
    r""" ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+""")


# --------------------------------------------------------------- GPT-2 BPE
@functools.lru_cache(maxsize=1)
def _bytes_to_unicode() -> Dict[int, str]:
    """The GPT-2 byte<->printable-unicode table: every byte maps to a
    character that survives a round trip through text files (control and
    whitespace bytes get remapped above U+0100)."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("\xa1"), ord("\xac") + 1))
          + list(range(ord("\xae"), ord("\xff") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


def _get_pairs(word: Tuple[str, ...]):
    return {(word[i], word[i + 1]) for i in range(len(word) - 1)}


class GPT2BPETokenizer:
    """Byte-level BPE over ``vocab.json`` / ``merges.txt`` (the GPT-2 /
    RoBERTa on-disk format). Encoding: regex pre-tokenization (GPT-2's
    pattern, via the ``regex`` module for \\p{L}/\\p{N} classes), byte ->
    unicode mapping, then lowest-rank-first merges per word."""

    def __init__(self, vocab: Dict[str, int],
                 merges: Sequence[Tuple[str, str]]):
        try:
            # \p{L}/\p{N} classes; stdlib re has no unicode categories.
            # Declared in the `prep` extra (pyproject.toml) — like PIL,
            # only dataset prep needs it, never the training path.
            import regex
        except ImportError as e:
            raise ImportError(
                "GPT-2 BPE needs the `regex` package (pip install "
                "nezha-tpu[prep] or pip install regex)") from e

        self.encoder = dict(vocab)
        self.decoder = {v: k for k, v in self.encoder.items()}
        self.bpe_ranks = {tuple(m): i for i, m in enumerate(merges)}
        self.byte_encoder = _bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        self._pat = regex.compile(GPT2_PRETOKENIZE_PATTERN)
        self._cache: Dict[str, List[str]] = {}

    # -- construction ------------------------------------------------------
    @classmethod
    def from_files(cls, vocab_json: str, merges_txt: str) -> "GPT2BPETokenizer":
        with open(vocab_json, encoding="utf-8") as f:
            vocab = json.load(f)
        merges: List[Tuple[str, str]] = []
        with open(merges_txt, encoding="utf-8") as f:
            for line in f:
                line = line.rstrip("\n")
                if not line or line.startswith("#version"):
                    continue
                a, _, b = line.partition(" ")
                merges.append((a, b))
        # Mismatched pair detection (ADVICE r5): a merge whose output is
        # not a vocab entry means vocab.json and merges.txt come from
        # different tokenizers — without this, encode() dies mid-corpus
        # with a bare KeyError on the first affected word.
        missing = [a + b for a, b in merges if a + b not in vocab]
        if missing:
            raise ValueError(
                f"{merges_txt} does not match {vocab_json}: "
                f"{len(missing)} merge output(s) missing from the vocab "
                f"(first: {missing[0]!r}) — the two files must come from "
                f"the same tokenizer")
        return cls(vocab, merges)

    @classmethod
    def from_dir(cls, path: str) -> "GPT2BPETokenizer":
        """A Hugging Face GPT-2 checkpoint/tokenizer directory."""
        return cls.from_files(os.path.join(path, "vocab.json"),
                              os.path.join(path, "merges.txt"))

    @property
    def vocab_size(self) -> int:
        return len(self.encoder)

    # -- core --------------------------------------------------------------
    def _bpe(self, token: str) -> List[str]:
        cached = self._cache.get(token)
        if cached is not None:
            return cached
        word: Tuple[str, ...] = tuple(token)
        pairs = _get_pairs(word)
        while pairs:
            best = min(pairs, key=lambda p: self.bpe_ranks.get(p, 1 << 60))
            if best not in self.bpe_ranks:
                break
            a, b = best
            merged: List[str] = []
            i = 0
            while i < len(word):
                if (word[i] == a and i < len(word) - 1
                        and word[i + 1] == b):
                    merged.append(a + b)
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = tuple(merged)
            if len(word) == 1:
                break
            pairs = _get_pairs(word)
        out = list(word)
        if len(self._cache) < 65536:
            self._cache[token] = out
        return out

    def encode(self, text: str) -> List[int]:
        ids: List[int] = []
        enc = self.encoder
        benc = self.byte_encoder
        for tok in self._pat.findall(text):
            mapped = "".join(benc[b] for b in tok.encode("utf-8"))
            ids.extend(enc[p] for p in self._bpe(mapped))
        return ids

    def decode(self, ids: Iterable[int]) -> str:
        text = "".join(self.decoder[i] for i in ids if i in self.decoder)
        return bytes(self.byte_decoder[c] for c in text).decode(
            "utf-8", errors="replace")


# ------------------------------------------------------------- WordPiece
def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    # ASCII symbol ranges count as punctuation (BERT convention: treat
    # $, +, ~ etc. as splittable even though unicode classes them S*).
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
            or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
            or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F)


class WordPieceTokenizer:
    """BERT-style tokenizer: basic (clean / CJK-space / lowercase /
    accent-strip / punct-split) + greedy longest-match WordPiece over a
    ``vocab.txt`` (one token per line, ``##`` continuation prefix)."""

    def __init__(self, vocab: Dict[str, int], lowercase: bool = True,
                 unk_token: str = "[UNK]", cls_token: str = "[CLS]",
                 sep_token: str = "[SEP]", mask_token: str = "[MASK]",
                 pad_token: str = "[PAD]",
                 max_chars_per_word: int = 100):
        self.vocab = dict(vocab)
        self.ids_to_tokens = {v: k for k, v in self.vocab.items()}
        self.lowercase = lowercase
        self.unk_token, self.cls_token = unk_token, cls_token
        self.sep_token, self.mask_token = sep_token, mask_token
        self.pad_token = pad_token
        self.max_chars_per_word = max_chars_per_word

    @classmethod
    def from_files(cls, vocab_txt: str, lowercase: bool = True,
                   **kw) -> "WordPieceTokenizer":
        vocab: Dict[str, int] = {}
        with open(vocab_txt, encoding="utf-8") as f:
            for i, line in enumerate(f):
                tok = line.rstrip("\n")
                if tok:
                    vocab[tok] = i
        self = cls(vocab, lowercase=lowercase, **kw)
        # Construction-time validation (ADVICE r5): a vocab without the
        # BERT specials (e.g. a --learn-bpe vocab pointed at by a BERT
        # flow) would otherwise surface as a bare KeyError mid-encode.
        # [MASK] is checked lazily by mask_token_id — non-MLM flows don't
        # need it.
        missing = [t for t in (self.unk_token, self.cls_token,
                               self.sep_token) if t not in vocab]
        if missing:
            raise ValueError(
                f"{vocab_txt} is not a usable WordPiece vocab: missing "
                f"special token(s) {missing} — is this really a BERT "
                f"vocab.txt?")
        return self

    @classmethod
    def from_dir(cls, path: str, **kw) -> "WordPieceTokenizer":
        return cls.from_files(os.path.join(path, "vocab.txt"), **kw)

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    @property
    def mask_token_id(self) -> int:
        try:
            return self.vocab[self.mask_token]
        except KeyError:
            raise ValueError(
                f"this WordPiece vocab has no {self.mask_token!r} token, "
                f"so it cannot drive MLM masking — re-learn/re-download a "
                f"vocab with the BERT specials or pass an explicit mask "
                f"id") from None

    # -- basic tokenization ------------------------------------------------
    def _basic(self, text: str) -> List[str]:
        cleaned: List[str] = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or unicodedata.category(ch) in (
                    "Cc", "Cf"):
                if ch not in ("\t", "\n", "\r"):
                    continue
            if _is_cjk(cp):
                cleaned.append(f" {ch} ")
            elif ch.isspace():
                cleaned.append(" ")
            else:
                cleaned.append(ch)
        words: List[str] = []
        for w in "".join(cleaned).split():
            if self.lowercase:
                w = w.lower()
                w = "".join(c for c in unicodedata.normalize("NFD", w)
                            if unicodedata.category(c) != "Mn")
            # split on punctuation, keeping each mark as its own token
            cur = ""
            for ch in w:
                if _is_punctuation(ch):
                    if cur:
                        words.append(cur)
                        cur = ""
                    words.append(ch)
                else:
                    cur += ch
            if cur:
                words.append(cur)
        return words

    def _wordpiece(self, word: str) -> List[str]:
        if len(word) > self.max_chars_per_word:
            return [self.unk_token]
        pieces: List[str] = []
        start = 0
        while start < len(word):
            end = len(word)
            piece = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    piece = sub
                    break
                end -= 1
            if piece is None:
                return [self.unk_token]
            pieces.append(piece)
            start = end
        return pieces

    def tokenize(self, text: str) -> List[str]:
        out: List[str] = []
        for w in self._basic(text):
            out.extend(self._wordpiece(w))
        return out

    def encode(self, text: str, text_pair: str | None = None,
               add_special_tokens: bool = True):
        """-> ids (and, for pairs, BERT segment ids via
        :meth:`encode_with_segments`)."""
        ids = [self.vocab[t] for t in self.tokenize(text)]
        if text_pair is None:
            if add_special_tokens:
                return ([self.vocab[self.cls_token]] + ids
                        + [self.vocab[self.sep_token]])
            return ids
        ids2 = [self.vocab[t] for t in self.tokenize(text_pair)]
        if not add_special_tokens:
            return ids + ids2
        return ([self.vocab[self.cls_token]] + ids
                + [self.vocab[self.sep_token]] + ids2
                + [self.vocab[self.sep_token]])

    def encode_with_segments(self, text: str, text_pair: str):
        """BERT NSP-style pair -> (ids, segment_ids)."""
        a = [self.vocab[t] for t in self.tokenize(text)]
        b = [self.vocab[t] for t in self.tokenize(text_pair)]
        cls_, sep = self.vocab[self.cls_token], self.vocab[self.sep_token]
        ids = [cls_] + a + [sep] + b + [sep]
        segs = [0] * (len(a) + 2) + [1] * (len(b) + 1)
        return ids, segs

    def decode(self, ids: Iterable[int],
               skip_special_tokens: bool = True) -> str:
        specials = {self.cls_token, self.sep_token, self.pad_token,
                    self.mask_token}
        toks = [self.ids_to_tokens[i] for i in ids
                if i in self.ids_to_tokens]
        if skip_special_tokens:
            toks = [t for t in toks if t not in specials]
        out: List[str] = []
        for t in toks:
            if t.startswith("##") and out:
                out[-1] += t[2:]
            else:
                out.append(t)
        return " ".join(out)


# ---------------------------------------------------------------- loader
def load_tokenizer(path: str):
    """Auto-detect the tokenizer format in ``path``: ``vocab.json`` +
    ``merges.txt`` -> GPT-2 BPE; ``vocab.txt`` -> WordPiece. The same
    directory layout a Hugging Face checkpoint ships, so
    ``nezha-generate --hf-dir D --tokenizer D`` needs one path."""
    if os.path.isfile(os.path.join(path, "vocab.json")) and \
            os.path.isfile(os.path.join(path, "merges.txt")):
        return GPT2BPETokenizer.from_dir(path)
    if os.path.isfile(os.path.join(path, "vocab.txt")):
        # Honor HF's do_lower_case if a tokenizer_config.json is present.
        lower = True
        cfgp = os.path.join(path, "tokenizer_config.json")
        if os.path.isfile(cfgp):
            try:
                with open(cfgp, encoding="utf-8") as f:
                    lower = bool(json.load(f).get("do_lower_case", True))
            except (OSError, ValueError):
                pass
        return WordPieceTokenizer.from_dir(path, lowercase=lower)
    raise FileNotFoundError(
        f"no tokenizer files in {path}: expected vocab.json+merges.txt "
        f"(GPT-2 BPE) or vocab.txt (BERT WordPiece)")


def encode_plain(tokenizer, text: str) -> List[int]:
    """Encode WITHOUT special tokens regardless of tokenizer kind — the
    packed-LM-stream / generation-prompt contract (WordPiece would
    otherwise wrap every call in [CLS]/[SEP]; BPE has no specials)."""
    try:
        return tokenizer.encode(text, add_special_tokens=False)
    except TypeError:
        return tokenizer.encode(text)


def default_eos_id(tokenizer) -> "int | None":
    """The vocabulary's end-of-sequence id, when it has one: GPT-2 BPE's
    ``<|endoftext|>``, WordPiece's ``[SEP]``. None otherwise (e.g. a
    corpus-learned vocab with no specials) — callers fall back to
    no-EOS decoding. Generation-side counterpart of the MLM mask-id
    resolution."""
    encoder = getattr(tokenizer, "encoder", None)
    if encoder is not None:                       # GPT-2 BPE
        return encoder.get("<|endoftext|>")
    vocab = getattr(tokenizer, "vocab", None)
    if vocab is not None:                         # WordPiece
        return vocab.get(getattr(tokenizer, "sep_token", "[SEP]"))
    return None
