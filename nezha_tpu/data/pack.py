"""Pack text files into flat binary token files for the native TokenLoader.

Byte-level tokenization (vocab 256): no external vocab files needed (this
image has no network egress for BPE downloads), ids are valid under any
model vocab >= 256, and real text still yields a real next-token learning
signal — the convergence evidence VERDICT round 1 item 10 asks for.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Iterable, Sequence

import numpy as np


def pack_text_files(paths: Iterable[str], out_path: str,
                    dtype=np.uint16) -> int:
    """Concatenate files as raw bytes -> ``out_path`` tokens; returns count."""
    chunks = []
    for p in sorted(str(p) for p in paths):
        chunks.append(Path(p).read_bytes())
        chunks.append(b"\n")
    data = b"".join(chunks)
    tokens = np.frombuffer(data, np.uint8).astype(dtype)
    tokens.tofile(out_path)
    return tokens.size


def pack_tree(root: str, out_path: str,
              suffixes: Sequence[str] = (".py", ".md"),
              dtype=np.uint16) -> int:
    """Pack every ``suffixes`` file under ``root`` (skipping VCS dirs)."""
    paths = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in (".git", "__pycache__", ".pytest_cache")]
        for f in filenames:
            if any(f.endswith(s) for s in suffixes):
                paths.append(os.path.join(dirpath, f))
    return pack_text_files(paths, out_path, dtype=dtype)
