"""Pack text files into flat binary token files for the native TokenLoader.

Two encodings:

- **Byte-level** (vocab 256, the zero-dependency default): ids are valid
  under any model vocab >= 256 and real text still yields a real
  next-token learning signal.
- **Subword** via :mod:`nezha_tpu.data.tokenizer` (GPT-2 byte-level BPE
  or BERT WordPiece over user-supplied vocab files — network-free): the
  reference's actual GPT-2 124M / BERT-base data parity
  (:func:`pack_text_files_tokenized`, ``nezha-pack-text --tokenizer``).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Iterable, Sequence

import numpy as np

# Directories no packer descends into (VCS/caches) — one list shared by
# pack_tree and the nezha-pack-text CLI walk.
PRUNE_DIRS = (".git", "__pycache__", ".pytest_cache")


def collect_paths(root: str, suffixes: Sequence[str]) -> list:
    """Every ``suffixes`` file under ``root``, pruning :data:`PRUNE_DIRS`."""
    paths = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in PRUNE_DIRS]
        for f in filenames:
            if any(f.endswith(s) for s in suffixes):
                paths.append(os.path.join(dirpath, f))
    return paths


def pack_text_files(paths: Iterable[str], out_path: str,
                    dtype=np.uint16) -> int:
    """Concatenate files as raw bytes -> ``out_path`` tokens; returns count."""
    total = 0
    with open(out_path, "wb") as out:
        for p in sorted(str(p) for p in paths):
            data = Path(p).read_bytes() + b"\n"
            np.frombuffer(data, np.uint8).astype(dtype).tofile(out)
            total += len(data)
    return total


def pack_tree(root: str, out_path: str,
              suffixes: Sequence[str] = (".py", ".md"),
              dtype=np.uint16) -> int:
    """Pack every ``suffixes`` file under ``root`` (skipping VCS dirs)."""
    return pack_text_files(collect_paths(root, suffixes), out_path,
                           dtype=dtype)


def token_dtype(vocab_size: int):
    """The one dtype rule for packed token files: uint16 when every id
    fits (GPT-2's 50257 and BERT's 30522 both do), else int32. Shared by
    the packers and the `nezha-pack-text` filename check so they cannot
    diverge."""
    return np.uint16 if vocab_size <= 65536 else np.int32


def pack_text_files_tokenized(paths: Iterable[str], out_path: str,
                              tokenizer, dtype=None) -> int:
    """Encode files with ``tokenizer`` (``encode(str) -> ids``; see
    ``data.tokenizer``) -> flat token file; returns the token count.

    ``dtype=None`` uses :func:`token_dtype`. Files are concatenated in
    sorted order with a document boundary between them: the tokenizer's
    ``[SEP]`` id when it has one (WordPiece — whose basic tokenizer
    would drop a bare newline), else the encoded newline (BPE).
    Streams one file at a time, so peak memory is the largest document,
    not the corpus."""
    from nezha_tpu.data.tokenizer import encode_plain

    sep_tok = getattr(tokenizer, "sep_token", None)
    if sep_tok is not None and sep_tok in getattr(tokenizer, "vocab", {}):
        boundary = [tokenizer.vocab[sep_tok]]
    else:
        boundary = encode_plain(tokenizer, "\n")
    if dtype is None:
        dtype = token_dtype(tokenizer.vocab_size)
    total = 0
    with open(out_path, "wb") as out:
        for p in sorted(str(p) for p in paths):
            ids = encode_plain(tokenizer,
                               Path(p).read_text(encoding="utf-8"))
            ids.extend(boundary)
            np.asarray(ids, dtype=dtype).tofile(out)
            total += len(ids)
    return total
