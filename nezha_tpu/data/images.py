"""Image-dataset prep: a directory of real images -> NZR1 record files.

JPEG/PNG decode happens exactly ONCE, here (csrc/dataloader.cpp keeps the
hot loader decode-free by design: "pre-decoded raw images in a flat record
file"); the C++ loader then streams fixed-size uint8 records with
crop/flip augmentation on worker threads. This closes the real-image path
of benchmark config 2 (SURVEY.md §2 data loaders): ImageFolder layout in,
`train.nzr`/`val.nzr`/`classes.txt` out, `nezha-train --data-dir` consumes
them directly.

Layouts accepted by :func:`pack_image_folder`:

* ``src/train/<class>/*.jpg`` + ``src/val/<class>/*.jpg`` — packed as-is
  (the ImageNet convention); both splits share one class list.
* ``src/<class>/*.jpg`` — a deterministic stratified val split is drawn
  per class (``val_fraction``, seeded).

Images are resized short-side to ``size`` (bilinear) and center-cropped to
``size x size`` — the stored record leaves room for the loader's random
``--crop`` at train time (store 256, crop 224 is the classic recipe).
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Sequence, Tuple

import numpy as np

from nezha_tpu.data.native import ImageRecordWriter

IMAGE_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp")


def list_image_folder(root: str) -> Tuple[List[Tuple[str, int]], List[str]]:
    """ImageFolder layout -> (sorted [(path, label)], sorted class names).

    Classes are the immediate subdirectories of ``root``, labeled in sorted
    order (the torchvision convention, so label maps line up for anyone
    migrating). Deterministic: both lists are sorted, never os.listdir
    order.
    """
    classes = sorted(
        d for d in os.listdir(root)
        if os.path.isdir(os.path.join(root, d)) and not d.startswith("."))
    if not classes:
        raise ValueError(f"no class subdirectories under {root!r}")
    samples = []
    for label, cls in enumerate(classes):
        cdir = os.path.join(root, cls)
        for dirpath, _, files in os.walk(cdir):
            for f in sorted(files):
                if f.lower().endswith(IMAGE_EXTENSIONS):
                    samples.append((os.path.join(dirpath, f), label))
    if not samples:
        raise ValueError(f"no images with extensions {IMAGE_EXTENSIONS} "
                         f"under {root!r}")
    samples.sort()
    return samples, classes


def load_image(path: str, size: int) -> np.ndarray:
    """Decode + short-side resize + center crop -> uint8 [size, size, 3].

    PIL is a prep-time-only dependency (the training path never imports
    it), matching the loader's decode-free design.
    """
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB")
        w, h = im.size
        scale = size / min(w, h)
        nw, nh = max(size, round(w * scale)), max(size, round(h * scale))
        im = im.resize((nw, nh), Image.BILINEAR)
        left, top = (nw - size) // 2, (nh - size) // 2
        im = im.crop((left, top, left + size, top + size))
        return np.asarray(im, np.uint8)


def _split_train_val(samples: Sequence[Tuple[str, int]], val_fraction: float,
                     seed: int):
    """Deterministic stratified split: per class, a seeded shuffle takes the
    first ``round(n * val_fraction)`` files for val (at least 1 when the
    class has >= 2 images and val_fraction > 0 — a val split with absent
    classes would silently skew eval accuracy)."""
    by_class: Dict[int, List[Tuple[str, int]]] = {}
    for s in samples:
        by_class.setdefault(s[1], []).append(s)
    train, val = [], []
    for label in sorted(by_class):
        rows = by_class[label]
        rng = np.random.RandomState(seed + label)
        order = rng.permutation(len(rows))
        n_val = round(len(rows) * val_fraction)
        if val_fraction > 0 and len(rows) >= 2:
            n_val = max(1, n_val)
        n_val = min(n_val, len(rows) - 1)  # never empty a class's train side
        val.extend(rows[i] for i in order[:n_val])
        train.extend(rows[i] for i in order[n_val:])
    return sorted(train), sorted(val)


def pack_split(samples: Sequence[Tuple[str, int]], out_path: str, size: int,
               workers: int = 8) -> int:
    """Decode ``samples`` on a thread pool (PIL releases the GIL during
    decode/resize) and stream them into ``out_path``. Returns the record
    count. Record order is the (sorted) sample order — the loader owns
    shuffling, so packing stays reproducible."""
    workers = max(1, workers)
    with ImageRecordWriter(out_path, size, size, 3) as wr:
        with ThreadPoolExecutor(max_workers=workers) as pool:
            # Bounded windows, not one big map: at most O(workers) decoded
            # images are ever in flight, so a lagging writer (slow disk)
            # cannot buffer the dataset into memory.
            chunk = workers * 4
            for start in range(0, len(samples), chunk):
                window = samples[start:start + chunk]
                decoded = pool.map(lambda s: load_image(s[0], size), window)
                for (_, label), img in zip(window, decoded):
                    wr.append(img, label)
        return wr.count


def pack_image_folder(src: str, out_dir: str, size: int = 256,
                      val_fraction: float = 0.1, seed: int = 0,
                      workers: int = 8) -> dict:
    """Pack an image directory into ``out_dir/{train.nzr, val.nzr,
    classes.txt}``. Returns a summary dict (counts, classes, paths)."""
    train_dir = os.path.join(src, "train")
    val_dir = os.path.join(src, "val")
    if os.path.isdir(train_dir) != os.path.isdir(val_dir):
        # A lone train/ (or val/) would otherwise be reinterpreted as the
        # flat layout — with 'train' itself becoming the single class and
        # every image mislabeled 0. Reject instead.
        present = "train" if os.path.isdir(train_dir) else "val"
        raise ValueError(
            f"{src!r} has a {present}/ subdirectory but not its "
            f"counterpart; provide both train/ and val/ (packed as-is) or "
            f"neither (flat <class>/ layout with --val-fraction split)")
    if os.path.isdir(train_dir) and os.path.isdir(val_dir):
        train, train_classes = list_image_folder(train_dir)
        val, val_classes = list_image_folder(val_dir)
        if val_classes != train_classes:
            # A val class missing from train (or vice versa) would shift
            # every later label — reject rather than mislabel the dataset.
            raise ValueError(
                f"train/ and val/ class lists differ: "
                f"{sorted(set(train_classes) ^ set(val_classes))}")
        classes = train_classes
    else:
        samples, classes = list_image_folder(src)
        train, val = _split_train_val(samples, val_fraction, seed)

    os.makedirs(out_dir, exist_ok=True)
    paths = {"train_path": os.path.join(out_dir, "train.nzr"),
             "val_path": os.path.join(out_dir, "val.nzr"),
             "classes_path": os.path.join(out_dir, "classes.txt")}
    n_train = pack_split(train, paths["train_path"], size, workers)
    n_val = pack_split(val, paths["val_path"], size, workers) if val else 0
    if not val:
        # An empty NZR1 is invalid by design (the loader rejects n=0);
        # don't leave a stale one behind from a previous pack either.
        if os.path.exists(paths["val_path"]):
            os.remove(paths["val_path"])
        paths["val_path"] = None
    with open(paths["classes_path"], "w") as f:
        f.write("\n".join(classes) + "\n")
    return {"num_train": n_train, "num_val": n_val, "num_classes":
            len(classes), "classes": classes, "size": size, **paths}
