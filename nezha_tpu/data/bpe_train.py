"""Offline byte-level BPE training — learn vocab.json/merges.txt from a
corpus, no network required.

This image (and many airgapped TPU pods) cannot download pretrained
vocabularies; the reference's LM configs assume one exists. This learner
closes the loop: `nezha-pack-text --learn-bpe N` builds a GPT-2-format
tokenizer from the corpus being packed, writes the standard files, and
the rest of the stack (pack, train, generate --tokenizer) consumes them
like any HF-shipped vocabulary.

Algorithm: the original BPE recipe over a word-frequency table —
regex pre-tokenization (GPT-2's pattern, the SAME compiled literal the
encoder uses), byte->unicode mapping, then repeatedly merge the most
frequent adjacent symbol pair. Pair counts are maintained incrementally
(only words containing the merged pair are re-counted), but each merge
still scans all pairs for the max, so per-merge cost is
O(unique_pairs) — sub-second per merge at typical corpus scales
(500 merges over ~0.4 MB measured at 0.7 s total); a lazy max-heap
would drop that to O(log n) if 30k+-merge vocabularies over GB corpora
ever matter here. Ties break by first-seen pair order, making the
output deterministic for a given ORDERED corpus (callers sort file
lists; see pack_text).

Host-side dataset prep, like everything in data/ — the device never sees
strings (SURVEY.md §2 data loaders row).
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, Iterable, List, Tuple

from nezha_tpu.data.tokenizer import _bytes_to_unicode

__all__ = ["learn_bpe", "save_bpe_files", "learn_wordpiece",
           "save_wordpiece_vocab"]


def _word_counts(texts: Iterable[str]) -> Counter:
    try:
        import regex
    except ImportError as e:
        raise ImportError(
            "BPE training needs the `regex` package (pip install "
            "nezha-tpu[prep] or pip install regex)") from e

    from nezha_tpu.data.tokenizer import GPT2_PRETOKENIZE_PATTERN

    benc = _bytes_to_unicode()
    pat = regex.compile(GPT2_PRETOKENIZE_PATTERN)
    words: Counter = Counter()
    for text in texts:
        for tok in pat.findall(text):
            words[tuple(benc[b] for b in tok.encode("utf-8"))] += 1
    return words


def learn_bpe(texts: Iterable[str], num_merges: int
              ) -> Tuple[Dict[str, int], List[Tuple[str, str]]]:
    """-> (vocab token->id, ordered merges). Vocab = the 256 byte symbols
    (sorted, matching the test/learner convention) + one entry per merge;
    ``vocab_size == 256 + num_merges`` (fewer if the corpus exhausts)."""
    words = dict(_word_counts(texts))
    # pair -> count, and pair -> set of words containing it (for
    # incremental updates); first_seen breaks count ties deterministically.
    pair_counts: Counter = Counter()
    # insertion-ORDERED dict-as-set: iteration order must not depend on
    # PYTHONHASHSEED, or first_seen tie-break ranks (assigned while
    # re-adding affected words) differ across interpreter runs.
    pair_words: Dict[Tuple[str, str], dict] = {}
    first_seen: Dict[Tuple[str, str], int] = {}

    def add_word(w: Tuple[str, ...], c: int) -> None:
        for i in range(len(w) - 1):
            p = (w[i], w[i + 1])
            pair_counts[p] += c
            pair_words.setdefault(p, {})[w] = None
            if p not in first_seen:
                first_seen[p] = len(first_seen)

    def drop_word(w: Tuple[str, ...], c: int) -> None:
        for i in range(len(w) - 1):
            p = (w[i], w[i + 1])
            pair_counts[p] -= c
            if pair_counts[p] <= 0:
                del pair_counts[p]
                pair_words.pop(p, None)
            else:
                s = pair_words.get(p)
                if s is not None:
                    s.pop(w, None)

    for w, c in words.items():
        add_word(w, c)

    merges: List[Tuple[str, str]] = []
    for _ in range(num_merges):
        if not pair_counts:
            break
        best = max(pair_counts,
                   key=lambda p: (pair_counts[p], -first_seen[p]))
        a, b = best
        merges.append(best)
        affected = list(pair_words.get(best, ()))
        for w in affected:
            c = words.pop(w, None)
            if c is None:
                continue
            drop_word(w, c)
            out: List[str] = []
            i = 0
            while i < len(w):
                if i < len(w) - 1 and w[i] == a and w[i + 1] == b:
                    out.append(a + b)
                    i += 2
                else:
                    out.append(w[i])
                    i += 1
            nw = tuple(out)
            words[nw] = words.get(nw, 0) + c
            add_word(nw, c)

    benc = _bytes_to_unicode()
    vocab = {ch: i for i, ch in enumerate(sorted(benc.values()))}
    for a, b in merges:
        vocab[a + b] = len(vocab)
    return vocab, merges


def save_bpe_files(path: str, vocab: Dict[str, int],
                   merges: List[Tuple[str, str]]) -> None:
    """Write the standard on-disk format (`load_tokenizer` reads it back)."""
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "vocab.json"), "w", encoding="utf-8") as f:
        json.dump(vocab, f, ensure_ascii=False)
    with open(os.path.join(path, "merges.txt"), "w", encoding="utf-8") as f:
        f.write("#version: 0.2\n")
        for a, b in merges:
            f.write(f"{a} {b}\n")


def learn_wordpiece(texts: Iterable[str], vocab_size: int,
                    lowercase: bool = True,
                    specials: Tuple[str, ...] = ("[PAD]", "[UNK]", "[CLS]",
                                                 "[SEP]", "[MASK]")
                    ) -> List[str]:
    """Learn a BERT-style ``vocab.txt`` (ordered token list) from a corpus.

    WordPiece scoring (the BERT recipe): merge the pair maximizing
    ``count(ab) / (count(a) * count(b))`` — likelihood gain rather than
    raw frequency — over words from the SAME basic tokenization the
    WordPiece encoder applies (clean / CJK-space / optional lowercase+
    accent-strip / punct-split), so learned pieces match encode-time word
    boundaries. Continuation pieces get the ``##`` prefix. The vocab is
    specials + every single character (guaranteeing totality: any
    in-corpus word tokenizes without [UNK]) + merged pieces, until
    ``vocab_size``; a target smaller than specials+alphabet is refused
    (truncating characters would silently [UNK] real words). Pair and
    symbol counts are maintained incrementally (same structure as
    :func:`learn_bpe`). Deterministic for an ordered corpus (score ties
    break first-seen).
    """
    from nezha_tpu.data.tokenizer import WordPieceTokenizer

    # Reuse the encoder's own basic tokenizer for word splitting.
    basic = WordPieceTokenizer({}, lowercase=lowercase)
    words: Counter = Counter()
    for text in texts:
        for w in basic._basic(text):
            words[w] += 1

    # Symbol sequences: first char bare, continuations ## -prefixed.
    seqs: Dict[Tuple[str, ...], int] = {}
    for w, c in words.items():
        seq = tuple([w[0]] + [f"##{ch}" for ch in w[1:]])
        seqs[seq] = seqs.get(seq, 0) + c

    char_vocab = sorted({s for seq in seqs for s in seq})
    floor = len(specials) + len(char_vocab)
    if vocab_size < floor:
        raise ValueError(
            f"vocab_size {vocab_size} is below specials+alphabet "
            f"({floor}); truncating characters would silently [UNK] "
            f"real words — raise the target")
    vocab: List[str] = list(specials) + char_vocab
    vocab_set = set(vocab)

    pair_counts: Counter = Counter()
    # ordered dict-as-set; see learn_bpe's note on PYTHONHASHSEED.
    pair_seqs: Dict[Tuple[str, str], dict] = {}
    first_seen: Dict[Tuple[str, str], int] = {}
    sym_counts: Counter = Counter()

    def add_seq(seq: Tuple[str, ...], c: int) -> None:
        for s_ in seq:
            sym_counts[s_] += c
        for i in range(len(seq) - 1):
            p = (seq[i], seq[i + 1])
            pair_counts[p] += c
            pair_seqs.setdefault(p, {})[seq] = None
            if p not in first_seen:
                first_seen[p] = len(first_seen)

    def drop_seq(seq: Tuple[str, ...], c: int) -> None:
        for s_ in seq:
            sym_counts[s_] -= c
        for i in range(len(seq) - 1):
            p = (seq[i], seq[i + 1])
            pair_counts[p] -= c
            if pair_counts[p] <= 0:
                del pair_counts[p]
                pair_seqs.pop(p, None)
            else:
                ss = pair_seqs.get(p)
                if ss is not None:
                    ss.pop(seq, None)

    for seq, c in seqs.items():
        add_seq(seq, c)

    while len(vocab) < vocab_size:
        if not pair_counts:
            break
        best = max(pair_counts, key=lambda p: (
            pair_counts[p] / (sym_counts[p[0]] * sym_counts[p[1]]),
            -first_seen[p]))
        a, b = best
        merged = a + b[2:]  # b is always ##-prefixed: only position 0 of
        # a word is bare, and merges preserve that invariant.
        if merged not in vocab_set:  # distinct pairs can merge to the
            vocab.append(merged)     # same string (ab+##c vs a+##bc)
            vocab_set.add(merged)
        for seq in list(pair_seqs.get(best, ())):
            c = seqs.pop(seq, None)
            if c is None:
                continue
            drop_seq(seq, c)
            out: List[str] = []
            i = 0
            while i < len(seq):
                if i < len(seq) - 1 and seq[i] == a and seq[i + 1] == b:
                    out.append(merged)
                    i += 2
                else:
                    out.append(seq[i])
                    i += 1
            nseq = tuple(out)
            seqs[nseq] = seqs.get(nseq, 0) + c
            add_seq(nseq, c)
    return vocab


def save_wordpiece_vocab(path: str, vocab: List[str]) -> None:
    """Write ``vocab.txt`` (one token per line; `load_tokenizer` reads it
    back as a WordPiece tokenizer)."""
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "vocab.txt"), "w", encoding="utf-8") as f:
        for tok in vocab:
            f.write(tok + "\n")
