"""Offline byte-level BPE training — learn vocab.json/merges.txt from a
corpus, no network required.

This image (and many airgapped TPU pods) cannot download pretrained
vocabularies; the reference's LM configs assume one exists. This learner
closes the loop: `nezha-pack-text --learn-bpe N` builds a GPT-2-format
tokenizer from the corpus being packed, writes the standard files, and
the rest of the stack (pack, train, generate --tokenizer) consumes them
like any HF-shipped vocabulary.

Algorithm: the original BPE recipe over a word-frequency table —
regex pre-tokenization (GPT-2's pattern, the SAME compiled literal the
encoder uses), byte->unicode mapping, then repeatedly merge the most
frequent adjacent symbol pair. Pair counts are maintained incrementally
(only words containing the merged pair are re-counted), but each merge
still scans all pairs for the max, so per-merge cost is
O(unique_pairs) — sub-second per merge at typical corpus scales
(500 merges over ~0.4 MB measured at 0.7 s total); a lazy max-heap
would drop that to O(log n) if 30k+-merge vocabularies over GB corpora
ever matter here. Ties break by first-seen pair order, making the
output deterministic for a given ORDERED corpus (callers sort file
lists; see pack_text).

Host-side dataset prep, like everything in data/ — the device never sees
strings (SURVEY.md §2 data loaders row).
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, Iterable, List, Tuple

from nezha_tpu.data.tokenizer import _bytes_to_unicode

__all__ = ["learn_bpe", "save_bpe_files"]


def _word_counts(texts: Iterable[str]) -> Counter:
    try:
        import regex
    except ImportError as e:
        raise ImportError(
            "BPE training needs the `regex` package (pip install "
            "nezha-tpu[prep] or pip install regex)") from e

    from nezha_tpu.data.tokenizer import GPT2_PRETOKENIZE_PATTERN

    benc = _bytes_to_unicode()
    pat = regex.compile(GPT2_PRETOKENIZE_PATTERN)
    words: Counter = Counter()
    for text in texts:
        for tok in pat.findall(text):
            words[tuple(benc[b] for b in tok.encode("utf-8"))] += 1
    return words


def learn_bpe(texts: Iterable[str], num_merges: int
              ) -> Tuple[Dict[str, int], List[Tuple[str, str]]]:
    """-> (vocab token->id, ordered merges). Vocab = the 256 byte symbols
    (sorted, matching the test/learner convention) + one entry per merge;
    ``vocab_size == 256 + num_merges`` (fewer if the corpus exhausts)."""
    words = dict(_word_counts(texts))
    # pair -> count, and pair -> set of words containing it (for
    # incremental updates); first_seen breaks count ties deterministically.
    pair_counts: Counter = Counter()
    pair_words: Dict[Tuple[str, str], set] = {}
    first_seen: Dict[Tuple[str, str], int] = {}

    def add_word(w: Tuple[str, ...], c: int) -> None:
        for i in range(len(w) - 1):
            p = (w[i], w[i + 1])
            pair_counts[p] += c
            pair_words.setdefault(p, set()).add(w)
            if p not in first_seen:
                first_seen[p] = len(first_seen)

    def drop_word(w: Tuple[str, ...], c: int) -> None:
        for i in range(len(w) - 1):
            p = (w[i], w[i + 1])
            pair_counts[p] -= c
            if pair_counts[p] <= 0:
                del pair_counts[p]
                pair_words.pop(p, None)
            else:
                s = pair_words.get(p)
                if s is not None:
                    s.discard(w)

    for w, c in words.items():
        add_word(w, c)

    merges: List[Tuple[str, str]] = []
    for _ in range(num_merges):
        if not pair_counts:
            break
        best = max(pair_counts,
                   key=lambda p: (pair_counts[p], -first_seen[p]))
        a, b = best
        merges.append(best)
        affected = list(pair_words.get(best, ()))
        for w in affected:
            c = words.pop(w, None)
            if c is None:
                continue
            drop_word(w, c)
            out: List[str] = []
            i = 0
            while i < len(w):
                if i < len(w) - 1 and w[i] == a and w[i + 1] == b:
                    out.append(a + b)
                    i += 2
                else:
                    out.append(w[i])
                    i += 1
            nw = tuple(out)
            words[nw] = words.get(nw, 0) + c
            add_word(nw, c)

    benc = _bytes_to_unicode()
    vocab = {ch: i for i, ch in enumerate(sorted(benc.values()))}
    for a, b in merges:
        vocab[a + b] = len(vocab)
    return vocab, merges


def save_bpe_files(path: str, vocab: Dict[str, int],
                   merges: List[Tuple[str, str]]) -> None:
    """Write the standard on-disk format (`load_tokenizer` reads it back)."""
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "vocab.json"), "w", encoding="utf-8") as f:
        json.dump(vocab, f, ensure_ascii=False)
    with open(os.path.join(path, "merges.txt"), "w", encoding="utf-8") as f:
        f.write("#version: 0.2\n")
        for a, b in merges:
            f.write(f"{a} {b}\n")
