"""Input pipelines — MNIST, ImageNet-format, and LM token streams
(SURVEY.md §1 "Models & data": MNIST + ImageNet + text loaders), fed through
the prefetching worker pool in `nezha_tpu.runtime`."""

from nezha_tpu.data.mnist import load_mnist, mnist_batches
from nezha_tpu.data.native import (
    ImageRecordLoader,
    MnistLoader,
    TokenLoader,
    write_image_records,
)
from nezha_tpu.data.mlm import mlm_batches_from_tokens
from nezha_tpu.data.synthetic import (
    synthetic_image_batches,
    synthetic_token_batches,
    synthetic_mlm_batches,
)

__all__ = [
    "load_mnist", "mnist_batches",
    "MnistLoader", "TokenLoader",
    "ImageRecordLoader", "write_image_records",
    "synthetic_image_batches", "synthetic_token_batches", "synthetic_mlm_batches",
    "mlm_batches_from_tokens",
]
