"""Python face of the native C++ batch loader (csrc/dataloader.cpp).

Decode + shuffle + batch assembly happen on C++ worker threads into a
bounded queue — the input-path role of the reference's goroutine worker
pool (SURVEY.md §1 "Execution runtime") — and each ``next()`` is a single
GIL-releasing copy into a numpy array. Feed the resulting iterator to
``nezha_tpu.runtime.Prefetcher`` to overlap host→device staging with the
running step.
"""

from __future__ import annotations

import ctypes
from typing import Iterator, Optional

import numpy as np

from nezha_tpu.runtime.native import load_library


class NativeLoaderError(RuntimeError):
    pass


class _Closable:
    _h = None

    def close(self) -> None:
        if self._h:
            self._lib.nz_loader_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class MnistLoader(_Closable):
    """Shuffled MNIST batches from IDX files, decoded by C++ workers.

    Yields ``{"image": float32 [B, 784] in [0,1], "label": int32 [B]}``.
    ``epochs <= 0`` streams forever (reshuffling each epoch).
    """

    def __init__(self, images_path: str, labels_path: str, batch_size: int,
                 seed: int = 0, num_workers: int = 2, queue_depth: int = 4,
                 epochs: int = 0):
        self._lib = load_library()
        n = ctypes.c_int()
        dim = ctypes.c_int()
        self._h = self._lib.nz_mnist_open(
            str(images_path).encode(), str(labels_path).encode(),
            int(batch_size), int(seed), int(num_workers), int(queue_depth),
            int(epochs), ctypes.byref(n), ctypes.byref(dim))
        if not self._h:
            raise NativeLoaderError(self._lib.nz_loader_error().decode())
        self.num_examples = n.value
        self.example_dim = dim.value
        self.batch_size = batch_size

    def __iter__(self) -> Iterator[dict]:
        while True:
            images = np.empty((self.batch_size, self.example_dim), np.float32)
            labels = np.empty((self.batch_size,), np.int32)
            got = self._lib.nz_loader_next(
                self._h,
                images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            if got <= 0:
                return
            yield {"image": images, "label": labels}


class ImageRecordLoader(_Closable):
    """ImageNet-style batches from an NZR1 record file, decoded/augmented
    by C++ workers (random crop + horizontal flip at train time, center
    crop at eval). Yields ``{"image": float32 [B, ch, cw, C] in [0,1],
    "label": int32 [B]}``. Write record files with
    :func:`write_image_records`. ``epochs <= 0`` streams forever.

    ``shard_index``/``shard_count`` (multi-host): every shard derives the
    same per-epoch shuffle and takes batches ``b % shard_count ==
    shard_index`` — each record is consumed exactly once per epoch across
    the world, with zero coordination traffic (pass the coordinator's
    rank/world_size).
    """

    def __init__(self, path: str, batch_size: int, crop: int = 0,
                 seed: int = 0, num_workers: int = 2, queue_depth: int = 4,
                 epochs: int = 0, train_augment: bool = True,
                 shard_index: int = 0, shard_count: int = 1):
        self._lib = load_library()
        n = ctypes.c_int()
        h = ctypes.c_int()
        w = ctypes.c_int()
        c = ctypes.c_int()
        self._h = self._lib.nz_records_open(
            str(path).encode(), int(batch_size), int(crop), int(crop),
            int(seed), int(num_workers), int(queue_depth), int(epochs),
            1 if train_augment else 0, int(shard_index), int(shard_count),
            ctypes.byref(n), ctypes.byref(h), ctypes.byref(w),
            ctypes.byref(c))
        if not self._h:
            raise NativeLoaderError(self._lib.nz_loader_error().decode())
        self.num_examples = n.value
        self.shape = (h.value, w.value, c.value)
        self.batch_size = batch_size

    def __iter__(self) -> Iterator[dict]:
        h, w, c = self.shape
        while True:
            images = np.empty((self.batch_size, h, w, c), np.float32)
            labels = np.empty((self.batch_size,), np.int32)
            got = self._lib.nz_loader_next(
                self._h,
                images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            if got <= 0:
                return
            yield {"image": images, "label": labels}


class ImageRecordWriter:
    """Streaming NZR1 writer: append one decoded image at a time, so packing
    a dataset never holds more than one image in memory (the prep-side
    counterpart of :class:`ImageRecordLoader`; `nezha-pack-images` uses it).

    The record count is backpatched into the header on ``close`` — a writer
    that is never closed leaves an invalid count of 0, which the loader
    rejects, so a crashed prep run cannot masquerade as a complete file.
    """

    def __init__(self, path: str, h: int, w: int, c: int = 3):
        self.shape = (int(h), int(w), int(c))
        self._n = 0
        self._f = open(path, "wb")
        self._f.write(b"NZR1")
        self._f.write(np.asarray([0, *self.shape], np.int32).tobytes())

    def append(self, image: np.ndarray, label: int) -> None:
        image = np.ascontiguousarray(image, np.uint8)
        if image.shape != self.shape:
            raise ValueError(f"image shape {image.shape} != record shape "
                             f"{self.shape}")
        self._f.write(np.int32(label).tobytes())
        self._f.write(image.tobytes())
        self._n += 1

    @property
    def count(self) -> int:
        return self._n

    def close(self) -> None:
        if self._f is not None:
            self._f.seek(4)
            self._f.write(np.int32(self._n).tobytes())
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            # Unwinding an exception: close WITHOUT backpatching, leaving
            # the header count at 0 — which the loader rejects — so the
            # crashed pack cannot masquerade as a complete file.
            if self._f is not None:
                self._f.close()
                self._f = None
        else:
            self.close()


def write_image_records(path: str, images: np.ndarray,
                        labels: np.ndarray) -> None:
    """Write an NZR1 record file: ``images`` uint8 [N,H,W,C] (pre-decoded,
    pre-resized — JPEG decode is a dataset-prep step, not a loader step),
    ``labels`` int [N]."""
    images = np.ascontiguousarray(images, np.uint8)
    labels = np.asarray(labels, np.int32)
    if images.ndim != 4 or labels.shape[0] != images.shape[0]:
        raise ValueError("images must be [N,H,W,C] with matching labels")
    n, h, w, c = images.shape
    with ImageRecordWriter(path, h, w, c) as wr:
        for i in range(n):
            wr.append(images[i], int(labels[i]))


class TokenLoader(_Closable):
    """Random ``[B, seq+1]`` windows from a flat binary token file
    (uint16 or int32), GPT-style next-token batches. Infinite stream.

    Yields ``{"tokens": int32 [B, seq+1]}``.
    """

    _DTYPES = {np.dtype(np.uint16): 2, np.dtype(np.int32): 4}

    def __init__(self, path: str, seq_len: int, batch_size: int,
                 dtype=np.uint16, seed: int = 0, num_workers: int = 2,
                 queue_depth: int = 4, shard_index: int = 0,
                 shard_count: int = 1):
        self._lib = load_library()
        code = self._DTYPES.get(np.dtype(dtype))
        if code is None:
            raise ValueError("dtype must be uint16 or int32")
        n = ctypes.c_long()
        # The stream is sampled (random windows), so sharding is a seed
        # split: each host draws a decorrelated window stream.
        self._h = self._lib.nz_tokens_open(
            str(path).encode(), code, int(seq_len), int(batch_size),
            int(seed), int(num_workers), int(queue_depth), int(shard_index),
            int(shard_count), ctypes.byref(n))
        if not self._h:
            raise NativeLoaderError(self._lib.nz_loader_error().decode())
        self.num_tokens = n.value
        self.batch_size = batch_size
        self.seq_len = seq_len

    def __iter__(self) -> Iterator[dict]:
        while True:
            out = np.empty((self.batch_size, self.seq_len + 1), np.int32)
            got = self._lib.nz_loader_next(
                self._h, None,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            if got <= 0:
                return
            yield {"tokens": out}
