"""Parameter initializers (fan-aware), mirroring what the reference's model
builders need (SURVEY.md §2 models: MLP, ResNets, GPT-2, BERT)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def zeros(rng, shape, dtype=jnp.float32):
    del rng
    return jnp.zeros(shape, dtype)


def ones(rng, shape, dtype=jnp.float32):
    del rng
    return jnp.ones(shape, dtype)


def normal(stddev: float = 0.02):
    def init(rng, shape, dtype=jnp.float32):
        return (jax.random.normal(rng, shape) * stddev).astype(dtype)
    return init


def truncated_normal(stddev: float = 0.02):
    def init(rng, shape, dtype=jnp.float32):
        return (jax.random.truncated_normal(rng, -2.0, 2.0, shape) * stddev).astype(dtype)
    return init


def _fans(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:  # (in, out) linear
        return shape[0], shape[1]
    # conv HWIO: receptive field * channels
    receptive = math.prod(shape[:-2])
    return shape[-2] * receptive, shape[-1] * receptive


def he_normal():
    """Kaiming/He normal — standard for ReLU nets (ResNets)."""
    def init(rng, shape, dtype=jnp.float32):
        fan_in, _ = _fans(shape)
        std = math.sqrt(2.0 / fan_in)
        return (jax.random.normal(rng, shape) * std).astype(dtype)
    return init


def lecun_normal():
    def init(rng, shape, dtype=jnp.float32):
        fan_in, _ = _fans(shape)
        std = math.sqrt(1.0 / fan_in)
        return (jax.random.normal(rng, shape) * std).astype(dtype)
    return init


def xavier_uniform():
    def init(rng, shape, dtype=jnp.float32):
        fan_in, fan_out = _fans(shape)
        limit = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, minval=-limit, maxval=limit).astype(dtype)
    return init
