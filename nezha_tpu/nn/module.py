"""Minimal functional module system.

This is the framework's parameter-management layer — the TPU-native
counterpart of the reference's op-graph/parameter handling (SURVEY.md §1
"Op graph & autograd"). Design principles, chosen for XLA:

- **Purely functional**: a ``Module`` holds only hyperparameters. Trainable
  parameters and mutable state (e.g. BatchNorm running stats) live in plain
  pytrees passed in and out of ``apply``. Autograd is ``jax.grad`` over the
  pure apply function; the traced jaxpr IS the op graph XLA compiles.
- **No tracing magic**: composition is explicit dicts keyed by child name, so
  parameter pytrees are stable, inspectable, and shardable with
  ``jax.sharding`` partition specs by path.
- **Static hyperparameters**: module config never enters jit, so every apply
  traces to a static-shape XLA program.

Variables layout::

    variables = {"params": <pytree>, "state": <pytree>}
    out, new_state = module.apply(variables, x, training=True, rng=rng)

Stateless modules return ``{}`` for ``new_state``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax

Params = Any
State = Any
Variables = Dict[str, Any]


def make_variables(params: Params = None, state: State = None) -> Variables:
    return {"params": {} if params is None else params,
            "state": {} if state is None else state}


def child_vars(variables: Variables, name: str) -> Variables:
    """Slice the variables of a named child module out of a parent's."""
    return {
        "params": variables.get("params", {}).get(name, {}),
        "state": variables.get("state", {}).get(name, {}),
    }


def child_rng(rng: Optional[jax.Array], name: str) -> Optional[jax.Array]:
    """Deterministically derive a child RNG from a parent's by child name."""
    if rng is None:
        return None
    return jax.random.fold_in(rng, _stable_hash(name))


def _stable_hash(name: str) -> int:
    # Python's hash() is salted per-process; use a stable FNV-1a instead so
    # RNG derivation is reproducible across runs and hosts.
    h = 0x811C9DC5
    for b in name.encode():
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return h


class Module:
    """Base class. Subclasses implement ``init`` and ``apply``.

    Composite modules get a default ``init`` for free: it collects every
    attribute that is a Module (or list/tuple of Modules, named ``attr{i}``)
    and initializes each under its attribute name. ``apply`` stays explicit —
    dataflow is the model's logic.
    """

    def _children(self) -> Dict[str, "Module"]:
        out: Dict[str, Module] = {}
        for k, v in vars(self).items():
            if isinstance(v, Module):
                out[k] = v
            elif isinstance(v, (list, tuple)):
                for i, m in enumerate(v):
                    if isinstance(m, Module):
                        out[f"{k}{i}"] = m
        return out

    def init(self, rng: jax.Array) -> Variables:
        children = self._children()
        if not children:
            raise NotImplementedError(
                f"{type(self).__name__} has no child modules; implement init()")
        params, state = {}, {}
        for name, child in children.items():
            v = child.init(child_rng(rng, name))
            if v["params"]:
                params[name] = v["params"]
            if v["state"]:
                state[name] = v["state"]
        return make_variables(params, state)

    def apply(self, variables: Variables, *args, training: bool = False,
              rng: Optional[jax.Array] = None, **kwargs):
        raise NotImplementedError

    def __call__(self, variables: Variables, *args, **kwargs):
        return self.apply(variables, *args, **kwargs)

    # -- conveniences -----------------------------------------------------

    def init_params(self, rng: jax.Array) -> Params:
        return self.init(rng)["params"]

    def param_count(self, rng_or_vars) -> int:
        if isinstance(rng_or_vars, dict):
            variables = rng_or_vars
        else:
            variables = self.init(rng_or_vars)
        return sum(x.size for x in jax.tree_util.tree_leaves(variables["params"]))


def run_child(child: Module, name: str, variables: Variables, states: Dict,
              *args, training: bool = False, rng: Optional[jax.Array] = None,
              **kwargs):
    """Apply a named child, recording its state update into ``states``."""
    out, st = child.apply(child_vars(variables, name), *args,
                          training=training, rng=child_rng(rng, name), **kwargs)
    if st:
        states[name] = st
    return out


class Sequential(Module):
    """Chain of modules applied in order. Children are named ``"0"``, ``"1"``, …"""

    def __init__(self, layers: Sequence[Module]):
        self.layers = list(layers)

    def init(self, rng: jax.Array) -> Variables:
        params, state = {}, {}
        for i, layer in enumerate(self.layers):
            v = layer.init(child_rng(rng, str(i)))
            if v["params"]:
                params[str(i)] = v["params"]
            if v["state"]:
                state[str(i)] = v["state"]
        return make_variables(params, state)

    def apply(self, variables: Variables, x, training: bool = False,
              rng: Optional[jax.Array] = None):
        new_state: Dict[str, Any] = {}
        for i, layer in enumerate(self.layers):
            name = str(i)
            x, st = layer.apply(child_vars(variables, name), x,
                                training=training, rng=child_rng(rng, name))
            if st:
                new_state[name] = st
        return x, new_state
