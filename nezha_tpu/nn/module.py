"""Minimal functional module system.

This is the framework's parameter-management layer — the TPU-native
counterpart of the reference's op-graph/parameter handling (SURVEY.md §1
"Op graph & autograd"). Design principles, chosen for XLA:

- **Purely functional**: a ``Module`` holds only hyperparameters. Trainable
  parameters and mutable state (e.g. BatchNorm running stats) live in plain
  pytrees passed in and out of ``apply``. Autograd is ``jax.grad`` over the
  pure apply function; the traced jaxpr IS the op graph XLA compiles.
- **No tracing magic**: composition is explicit dicts keyed by child name, so
  parameter pytrees are stable, inspectable, and shardable with
  ``jax.sharding`` partition specs by path.
- **Static hyperparameters**: module config never enters jit, so every apply
  traces to a static-shape XLA program.

Variables layout::

    variables = {"params": <pytree>, "state": <pytree>}
    out, new_state = module.apply(variables, x, training=True, rng=rng)

Stateless modules return ``{}`` for ``new_state``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax

Params = Any
State = Any
Variables = Dict[str, Any]


def make_variables(params: Params = None, state: State = None) -> Variables:
    return {"params": {} if params is None else params,
            "state": {} if state is None else state}


def child_vars(variables: Variables, name: str) -> Variables:
    """Slice the variables of a named child module out of a parent's."""
    return {
        "params": variables.get("params", {}).get(name, {}),
        "state": variables.get("state", {}).get(name, {}),
    }


def child_rng(rng: Optional[jax.Array], name: str) -> Optional[jax.Array]:
    """Deterministically derive a child RNG from a parent's by child name."""
    if rng is None:
        return None
    return jax.random.fold_in(rng, _stable_hash(name))


def _stable_hash(name: str) -> int:
    # Python's hash() is salted per-process; use a stable FNV-1a instead so
    # RNG derivation is reproducible across runs and hosts.
    h = 0x811C9DC5
    for b in name.encode():
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return h


class Module:
    """Base class. Subclasses implement ``init`` and ``apply``.

    Composite modules get a default ``init`` for free: it collects every
    attribute that is a Module (or list/tuple of Modules, named ``attr{i}``)
    and initializes each under its attribute name. ``apply`` stays explicit —
    dataflow is the model's logic.
    """

    def _children(self) -> Dict[str, "Module"]:
        out: Dict[str, Module] = {}
        for k, v in vars(self).items():
            if isinstance(v, Module):
                out[k] = v
            elif isinstance(v, (list, tuple)):
                for i, m in enumerate(v):
                    if isinstance(m, Module):
                        out[f"{k}{i}"] = m
        return out

    def init(self, rng: jax.Array) -> Variables:
        children = self._children()
        if not children:
            raise NotImplementedError(
                f"{type(self).__name__} has no child modules; implement init()")
        params, state = {}, {}
        for name, child in children.items():
            # _init_with_parent_rng (scan-over-layers stacks): the child
            # derives its own per-layer names from the PARENT's rng, so a
            # scan layout initializes bit-identically to the unrolled one
            # under the same seed.
            crng = (rng if getattr(child, "_init_with_parent_rng", False)
                    else child_rng(rng, name))
            v = child.init(crng)
            if v["params"]:
                params[name] = v["params"]
            if v["state"]:
                state[name] = v["state"]
        return make_variables(params, state)

    def apply(self, variables: Variables, *args, training: bool = False,
              rng: Optional[jax.Array] = None, **kwargs):
        raise NotImplementedError

    def __call__(self, variables: Variables, *args, **kwargs):
        return self.apply(variables, *args, **kwargs)

    # -- conveniences -----------------------------------------------------

    def init_params(self, rng: jax.Array) -> Params:
        return self.init(rng)["params"]

    def param_count(self, rng_or_vars) -> int:
        if isinstance(rng_or_vars, dict):
            variables = rng_or_vars
        else:
            variables = self.init(rng_or_vars)
        return sum(x.size for x in jax.tree_util.tree_leaves(variables["params"]))


def run_child(child: Module, name: str, variables: Variables, states: Dict,
              *args, training: bool = False, rng: Optional[jax.Array] = None,
              **kwargs):
    """Apply a named child, recording its state update into ``states``."""
    out, st = child.apply(child_vars(variables, name), *args,
                          training=training, rng=child_rng(rng, name), **kwargs)
    if st:
        states[name] = st
    return out


class Sequential(Module):
    """Chain of modules applied in order. Children are named ``"0"``, ``"1"``, …"""

    def __init__(self, layers: Sequence[Module]):
        self.layers = list(layers)

    def init(self, rng: jax.Array) -> Variables:
        params, state = {}, {}
        for i, layer in enumerate(self.layers):
            v = layer.init(child_rng(rng, str(i)))
            if v["params"]:
                params[str(i)] = v["params"]
            if v["state"]:
                state[str(i)] = v["state"]
        return make_variables(params, state)

    def apply(self, variables: Variables, x, training: bool = False,
              rng: Optional[jax.Array] = None):
        new_state: Dict[str, Any] = {}
        for i, layer in enumerate(self.layers):
            name = str(i)
            x, st = layer.apply(child_vars(variables, name), x,
                                training=training, rng=child_rng(rng, name))
            if st:
                new_state[name] = st
        return x, new_state


def stack_prefixed_params(params: dict, prefix: str, num_layers: int,
                          stacked_key: str) -> dict:
    """``{prefix}0 .. {prefix}{L-1}`` param subtrees -> one ``stacked_key``
    subtree with a leading [L] dim on every leaf (the lax.scan-over-layers
    layout). Non-matching entries pass through untouched."""
    import jax.numpy as jnp

    names = {f"{prefix}{i}" for i in range(num_layers)}
    out = {k: v for k, v in params.items() if k not in names}
    layers = [params[f"{prefix}{i}"] for i in range(num_layers)]
    out[stacked_key] = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *layers)
    return out


def unstack_prefixed_params(params: dict, prefix: str, num_layers: int,
                            stacked_key: str) -> dict:
    """Inverse of :func:`stack_prefixed_params`."""
    out = {k: v for k, v in params.items() if k != stacked_key}
    for i in range(num_layers):
        out[f"{prefix}{i}"] = jax.tree_util.tree_map(
            lambda x, i=i: x[i], params[stacked_key])
    return out


def scan_stack_init(template: Module, rng: jax.Array, num_layers: int,
                    prefix: str) -> Variables:
    """Init for a lax.scan-over-layers stack: ``num_layers`` independent
    inits of ``template`` (per-layer RNGs derived with the SAME
    ``{prefix}{i}`` names the unrolled trunk uses), tree-stacked along a
    new leading dim. Stateless layers only — running state would need a
    per-layer carry the scan layout doesn't model."""
    import jax.numpy as jnp

    inits = [template.init(child_rng(rng, f"{prefix}{i}"))
             for i in range(num_layers)]
    if any(v["state"] for v in inits):
        raise ValueError("scan_layers requires stateless layers")
    params = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[v["params"] for v in inits])
    return make_variables(params, {})


def scan_stack_apply(template: Module, stacked_params, x, num_layers: int,
                     prefix: str, rng: Optional[jax.Array] = None,
                     remat: bool = False, **layer_kwargs):
    """Apply a layer-stacked trunk via ``lax.scan``: one traced/compiled
    ``template`` program, params sliced per layer; ``layer_kwargs`` are
    layer-invariant broadcast inputs (masks, position offsets). Per-layer
    dropout RNGs are pre-split outside the scan with the unrolled
    ``{prefix}{i}`` derivation, so both layouts replay identical keys.
    ``remat=True`` wraps the body in ``jax.checkpoint`` (activation
    memory O(1) per layer). The template must return ``(y, {})`` —
    non-empty layer state raises."""
    import jax.numpy as jnp

    rngs = (jnp.stack([child_rng(rng, f"{prefix}{i}")
                       for i in range(num_layers)])
            if rng is not None else None)

    def body(carry, layer):
        lparams, lrng = layer
        y, st = template.apply({"params": lparams, "state": {}}, carry,
                               rng=lrng, **layer_kwargs)
        if st:
            raise ValueError(
                f"scan_layers got unexpected layer state {list(st)}")
        return y, None

    if remat:
        body = jax.checkpoint(body)
    if rngs is None:
        def body_no_rng(carry, lparams, _inner=body):
            return _inner(carry, (lparams, None))
        return jax.lax.scan(body_no_rng, x, stacked_params)[0]
    return jax.lax.scan(body, x, (stacked_params, rngs))[0]
