"""Neural-net layer library (functional, pytree-parameterized)."""

from nezha_tpu.nn.module import (
    Module,
    Sequential,
    Variables,
    make_variables,
    child_vars,
    child_rng,
    run_child,
)
from nezha_tpu.nn.layers import (
    Linear,
    Conv2d,
    BatchNorm,
    LayerNorm,
    Embedding,
    Dropout,
    max_pool,
    avg_pool,
    global_avg_pool,
)
from nezha_tpu.nn import initializers

__all__ = [
    "Module", "Sequential", "Variables", "make_variables", "child_vars",
    "child_rng", "run_child", "Linear", "Conv2d", "BatchNorm", "LayerNorm",
    "Embedding",
    "Dropout", "max_pool", "avg_pool", "global_avg_pool", "initializers",
]
