"""Core layers.

TPU-first conventions baked in:

- Convs are NHWC (feature-minor) — the layout XLA:TPU tiles best onto the
  MXU; weights are HWIO.
- Every layer takes a dtype ``Policy`` (fp32 master params, bf16 compute by
  default for the big models) so the MXU runs at full bf16 throughput while
  normalization statistics stay fp32.
- All shapes static; no data-dependent control flow, so everything fuses
  under one jit.

Reference parity: the op set nezha's graph needs for its five benchmark
workloads (SURVEY.md §2: matmul, conv, norms, embedding, dropout, pooling).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from nezha_tpu.nn import initializers as init_lib
from nezha_tpu.nn.module import Module, Variables, make_variables
from nezha_tpu.tensor.policy import DEFAULT_POLICY, Policy


class Linear(Module):
    """y = x @ W + b, weights stored (in, out)."""

    def __init__(self, in_features: int, out_features: int, use_bias: bool = True,
                 kernel_init=None, bias_init=init_lib.zeros,
                 policy: Policy = DEFAULT_POLICY, name: str = "linear"):
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = use_bias
        self.kernel_init = kernel_init or init_lib.lecun_normal()
        self.bias_init = bias_init
        self.policy = policy
        self.name = name

    def init(self, rng: jax.Array) -> Variables:
        kw, kb = jax.random.split(rng)
        p = {"w": self.kernel_init(kw, (self.in_features, self.out_features),
                                   self.policy.param_dtype)}
        if self.use_bias:
            p["b"] = self.bias_init(kb, (self.out_features,), self.policy.param_dtype)
        return make_variables(p)

    def apply(self, variables: Variables, x, training: bool = False, rng=None):
        del training, rng
        p = variables["params"]
        w = self.policy.cast_to_compute(p["w"])
        x = self.policy.cast_to_compute(x)
        y = x @ w
        if self.use_bias:
            y = y + self.policy.cast_to_compute(p["b"])
        return self.policy.cast_output(y), {}


class Conv2d(Module):
    """NHWC conv, HWIO weights, optional groups — lowers to XLA conv on MXU."""

    def __init__(self, in_channels: int, out_channels: int,
                 kernel_size: Union[int, Tuple[int, int]],
                 stride: Union[int, Tuple[int, int]] = 1,
                 padding: Union[str, int, Tuple[int, int]] = "SAME",
                 groups: int = 1, use_bias: bool = True,
                 kernel_init=None, policy: Policy = DEFAULT_POLICY):
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
        self.stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
        if isinstance(padding, int):
            padding = ((padding, padding), (padding, padding))
        elif isinstance(padding, tuple):
            padding = tuple((p, p) if isinstance(p, int) else p for p in padding)
        self.padding = padding
        self.groups = groups
        self.use_bias = use_bias
        self.kernel_init = kernel_init or init_lib.he_normal()
        self.policy = policy

    def init(self, rng: jax.Array) -> Variables:
        kw, kb = jax.random.split(rng)
        kh, kwd = self.kernel_size
        p = {"w": self.kernel_init(
            kw, (kh, kwd, self.in_channels // self.groups, self.out_channels),
            self.policy.param_dtype)}
        if self.use_bias:
            p["b"] = init_lib.zeros(kb, (self.out_channels,), self.policy.param_dtype)
        return make_variables(p)

    def apply(self, variables: Variables, x, training: bool = False, rng=None):
        del training, rng
        p = variables["params"]
        w = self.policy.cast_to_compute(p["w"])
        x = self.policy.cast_to_compute(x)
        y = lax.conv_general_dilated(
            x, w,
            window_strides=self.stride,
            padding=self.padding,
            feature_group_count=self.groups,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.use_bias:
            y = y + self.policy.cast_to_compute(p["b"])
        return self.policy.cast_output(y), {}


class BatchNorm(Module):
    """Batch norm over N,H,W (axis −1 features) with running stats in fp32.

    Running stats are framework ``state`` — updated functionally: apply in
    training mode returns the new stats, the train step threads them.
    Batch statistics themselves are per-replica under data parallelism (no
    cross-replica batch-stat sync inside the layer); the DP/ZeRO-1 train
    steps pmean the *running* stats each step (they're tiny), and
    ``nezha_tpu.parallel.sync_batch_stats`` averages pmap-style stacked
    per-replica stats for custom steps that let them drift until eval.
    """

    def __init__(self, num_features: int, momentum: float = 0.9, eps: float = 1e-5,
                 policy: Policy = DEFAULT_POLICY):
        self.num_features = num_features
        self.momentum = momentum
        self.eps = eps
        self.policy = policy

    def init(self, rng: jax.Array) -> Variables:
        del rng
        f = self.num_features
        params = {"scale": jnp.ones((f,), self.policy.param_dtype),
                  "bias": jnp.zeros((f,), self.policy.param_dtype)}
        state = {"mean": jnp.zeros((f,), jnp.float32),
                 "var": jnp.ones((f,), jnp.float32)}
        return make_variables(params, state)

    def apply(self, variables: Variables, x, training: bool = False, rng=None):
        del rng
        p, s = variables["params"], variables["state"]
        reduce_axes = tuple(range(x.ndim - 1))
        xf = jnp.asarray(x, jnp.float32)  # stats in fp32 always
        if training:
            mean = jnp.mean(xf, axis=reduce_axes)
            var = jnp.var(xf, axis=reduce_axes)
            m = self.momentum
            new_state = {"mean": m * s["mean"] + (1 - m) * mean,
                         "var": m * s["var"] + (1 - m) * var}
        else:
            mean, var = s["mean"], s["var"]
            new_state = {}
        inv = lax.rsqrt(var + self.eps)
        scale = jnp.asarray(p["scale"], jnp.float32) * inv
        shift = jnp.asarray(p["bias"], jnp.float32) - mean * scale
        # Normalize in the input's compute dtype: stats stay fp32 (above),
        # but applying them to the fp32-upcast activation would make the
        # residual saved for backward an fp32 copy of every conv output —
        # 2x the HBM traffic of the bf16 policy it runs under. scale/shift
        # are per-channel, so the bf16 multiply loses no batch statistics.
        y = x * jnp.asarray(scale, x.dtype) + jnp.asarray(shift, x.dtype)
        return self.policy.cast_output(y), new_state


class LayerNorm(Module):
    """Layer norm over the last axis; statistics in fp32.

    ``impl="pallas"`` opts into the fused Pallas kernel (fwd + custom-VJP
    bwd, `ops.pallas.fused_layer_norm`) on TPU backends; requires both
    scale and bias. Under the GSPMD auto-partitioner (which cannot
    partition Mosaic calls) the kernel still runs device-locally via a
    nested shard_map when the trace carries its mesh (rows independent,
    batch over dp); composed XLA otherwise and on non-TPU backends."""

    def __init__(self, dim: int, eps: float = 1e-5, use_bias: bool = True,
                 use_scale: bool = True, policy: Policy = DEFAULT_POLICY,
                 impl: str = "xla"):
        self.dim = dim
        self.eps = eps
        self.use_bias = use_bias
        self.use_scale = use_scale
        self.policy = policy
        if impl not in ("xla", "pallas"):
            raise ValueError(f"unknown LayerNorm impl {impl!r}")
        if impl == "pallas" and not (use_bias and use_scale):
            raise ValueError("impl='pallas' needs use_scale and use_bias")
        self.impl = impl

    def init(self, rng: jax.Array) -> Variables:
        del rng
        p = {}
        if self.use_scale:
            p["scale"] = jnp.ones((self.dim,), self.policy.param_dtype)
        if self.use_bias:
            p["bias"] = jnp.zeros((self.dim,), self.policy.param_dtype)
        return make_variables(p)

    def apply(self, variables: Variables, x, training: bool = False, rng=None):
        del training, rng
        p = variables["params"]
        force = os.environ.get("NEZHA_LN_INTERPRET")  # CPU test hook
        if self.impl == "pallas" and (jax.default_backend() == "tpu"
                                      or force):
            from nezha_tpu.parallel.gspmd import (auto_partitioner_mesh,
                                                  under_auto_partitioner)
            if not under_auto_partitioner():
                from nezha_tpu.ops.pallas import fused_layer_norm
                y = fused_layer_norm(
                    self.policy.cast_to_compute(x),
                    jnp.asarray(p["scale"], jnp.float32),
                    jnp.asarray(p["bias"], jnp.float32), eps=self.eps)
                return self.policy.cast_output(y), {}
            mesh = auto_partitioner_mesh()
            if os.environ.get("NEZHA_NO_NESTED_KERNELS"):
                mesh = None  # day-1 escape hatch; see gpt2._tp_flash_mesh
            if mesh is not None and "dp" in mesh.axis_names and x.ndim >= 2:
                # Under the GSPMD auto-partitioner (which cannot partition
                # a Mosaic call) the kernel still runs device-locally via
                # a nested shard_map: rows are independent, activations
                # between blocks are tp-replicated, batch shards over dp
                # (same pattern as models.gpt2._tp_sharded_flash).
                from jax.sharding import PartitionSpec as P

                from nezha_tpu.ops.pallas import fused_layer_norm
                from nezha_tpu.parallel._compat import shard_map
                spec = P(*(("dp",) + (None,) * (x.ndim - 1)))
                f = shard_map(
                    lambda x_, s_, b_: fused_layer_norm(x_, s_, b_,
                                                        eps=self.eps),
                    mesh=mesh, in_specs=(spec, P(), P()), out_specs=spec)
                y = f(self.policy.cast_to_compute(x),
                      jnp.asarray(p["scale"], jnp.float32),
                      jnp.asarray(p["bias"], jnp.float32))
                return self.policy.cast_output(y), {}
        xf = jnp.asarray(x, jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mean) * lax.rsqrt(var + self.eps)
        if self.use_scale:
            y = y * jnp.asarray(p["scale"], jnp.float32)
        if self.use_bias:
            y = y + jnp.asarray(p["bias"], jnp.float32)
        return self.policy.cast_output(y), {}


class Embedding(Module):
    """Token embedding table; lookup stays a gather (fast path on TPU)."""

    def __init__(self, num_embeddings: int, features: int,
                 embedding_init=None, policy: Policy = DEFAULT_POLICY):
        self.num_embeddings = num_embeddings
        self.features = features
        self.embedding_init = embedding_init or init_lib.normal(0.02)
        self.policy = policy

    def init(self, rng: jax.Array) -> Variables:
        return make_variables({
            "embedding": self.embedding_init(
                rng, (self.num_embeddings, self.features), self.policy.param_dtype)
        })

    def apply(self, variables: Variables, ids, training: bool = False, rng=None):
        del training, rng
        table = self.policy.cast_to_compute(variables["params"]["embedding"])
        return jnp.take(table, ids, axis=0), {}

    def attend(self, variables: Variables, x):
        """Tied-softmax logits: x @ E^T (GPT-2/BERT output head)."""
        table = self.policy.cast_to_compute(variables["params"]["embedding"])
        return self.policy.cast_to_compute(x) @ table.T


class Dropout(Module):
    def __init__(self, rate: float):
        self.rate = rate

    def init(self, rng: jax.Array) -> Variables:
        del rng
        return make_variables()

    def apply(self, variables: Variables, x, training: bool = False, rng=None):
        del variables
        if not training or self.rate == 0.0:
            return x, {}
        if rng is None:
            raise ValueError("Dropout in training mode needs an rng")
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, jnp.zeros_like(x)), {}


def max_pool(x, window: int, stride: int, padding: str = "SAME"):
    """NHWC max pool via reduce_window."""
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        (1, window, window, 1), (1, stride, stride, 1), padding)


def avg_pool(x, window: int, stride: int, padding: str = "VALID"):
    dims = (1, window, window, 1)
    strides = (1, stride, stride, 1)
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, padding)
    if padding == "VALID":
        return summed / (window * window)
    # SAME: edge windows overlap padding — divide by the true element count.
    counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, dims, strides,
                               padding)
    return summed / counts


def global_avg_pool(x):
    """NHWC -> NC."""
    return jnp.mean(x, axis=(1, 2))
