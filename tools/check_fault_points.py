#!/usr/bin/env python3
"""Fault-point registry validator.

The fault-injection layer (``nezha_tpu.faults``) only earns its keep if
every registered point stays discoverable, documented, and actually
exercised — an undocumented point is a chaos knob nobody can use, and an
untested one is a resilience claim nobody has proven. This validator
walks the source tree for ``faults.point("...")`` / ``faults.corrupt(
"...")`` literals and asserts each name is

1. **unique** — one call site per name, so hit counts and plan rules
   are unambiguous;
2. **documented** — the name appears in docs/RUNBOOK.md (the fault-point
   table in the "Failure modes & recovery" section);
3. **tested** — the name appears in at least one file under tests/
   (a plan rule string or a direct reference);
4. **pinned** — the discovered set matches ``EXPECTED_POINTS`` exactly,
   so a point can neither appear nor vanish without this file (and the
   RUNBOOK table) being updated deliberately.

Stdlib-only, same pattern as check_telemetry_schema.py: run from the
tier-1 suite (tests/test_faults.py) or standalone:

    python tools/check_fault_points.py [REPO_ROOT]
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List

POINT_RE = re.compile(
    r"""faults\.(?:point|corrupt)\(\s*["']([A-Za-z0-9_.]+)["']""")
# The frozen registry: every faults.point()/corrupt() call site in the
# tree, by name. Adding a fault point means adding it HERE (and to the
# RUNBOOK table + a test) in the same change.
EXPECTED_POINTS = frozenset({
    "serve.prefill", "serve.prefill.logits",
    "serve.step", "serve.step.logits",
    "checkpoint.save", "dist.join",
    # Multi-replica serving (router/supervisor front end):
    "router.route", "router.probe", "supervisor.spawn", "replica.exec",
    # Paged KV pool: armed at every block bind (admission, lazy decode
    # growth, COW) — an injected error surfaces as the same typed
    # KVBlocksExhausted backpressure genuine exhaustion produces.
    "serve.kv.bind",
})
SOURCE_DIR = "nezha_tpu"
# The faults package itself is excluded: its docstrings describe the API
# with example call patterns, which are not registered points.
EXCLUDE_PREFIX = os.path.join("nezha_tpu", "faults")
RUNBOOK = os.path.join("docs", "RUNBOOK.md")
TESTS_DIR = "tests"


def find_points(root: str) -> Dict[str, List[str]]:
    """-> {point name: [repo-relative files registering it]}."""
    points: Dict[str, List[str]] = {}
    for dirpath, _, files in os.walk(os.path.join(root, SOURCE_DIR)):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            if rel.startswith(EXCLUDE_PREFIX):
                continue
            with open(path) as f:
                for name in POINT_RE.findall(f.read()):
                    points.setdefault(name, []).append(rel)
    return points


def check(root: str) -> List[str]:
    """-> list of violations (empty = registry is clean)."""
    errors: List[str] = []
    points = find_points(root)
    if not points:
        errors.append(f"no faults.point()/faults.corrupt() call sites "
                      f"found under {SOURCE_DIR}/")
        return errors
    for name, files in sorted(points.items()):
        if len(files) > 1:
            errors.append(
                f"fault point {name!r} registered at {len(files)} call "
                f"sites ({', '.join(files)}) — names must be unique")
    for name in sorted(set(points) - EXPECTED_POINTS):
        errors.append(f"fault point {name!r} is not in EXPECTED_POINTS "
                      f"— add it to the pinned registry (and the "
                      f"RUNBOOK table) deliberately")
    for name in sorted(EXPECTED_POINTS - set(points)):
        errors.append(f"pinned fault point {name!r} has no "
                      f"faults.point()/corrupt() call site under "
                      f"{SOURCE_DIR}/ — the registry lost a point")
    with open(os.path.join(root, RUNBOOK)) as f:
        runbook = f.read()
    tests_text = []
    tests_root = os.path.join(root, TESTS_DIR)
    for dirpath, _, files in os.walk(tests_root):
        for fn in sorted(files):
            if fn.endswith(".py"):
                with open(os.path.join(dirpath, fn)) as f:
                    tests_text.append(f.read())
    tests_blob = "\n".join(tests_text)
    for name in sorted(points):
        # Boundary-anchored match: a point whose name prefixes another's
        # ("serve.step" vs "serve.step.logits") must NOT pass vacuously
        # via its sibling's mentions.
        exact = re.compile(
            rf"(?<![A-Za-z0-9_.]){re.escape(name)}(?![A-Za-z0-9_.])")
        if not exact.search(runbook):
            errors.append(f"fault point {name!r} is not documented in "
                          f"{RUNBOOK}")
        if not exact.search(tests_blob):
            errors.append(f"fault point {name!r} is not covered by any "
                          f"test under {TESTS_DIR}/")
    return errors


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    errors = check(root)
    for e in errors:
        print(e, file=sys.stderr)
    if errors:
        print(f"FAIL: {len(errors)} fault-registry violation(s)",
              file=sys.stderr)
        return 1
    points = find_points(root)
    print(f"OK: {len(points)} fault point(s) registered, documented, "
          f"and tested")
    return 0


if __name__ == "__main__":
    sys.exit(main())
