#!/usr/bin/env python3
"""Fault-point registry validator — shim over ``nezha_tpu.analysis``.

The real implementation is the ``fault-points`` lint rule
(``nezha_tpu/analysis/rules/fault_points.py``): every
``faults.point("...")`` / ``faults.corrupt("...")`` call site must be
unique, RUNBOOK-documented, test-covered, and pinned in
``EXPECTED_POINTS`` — see that module's docstring. It now walks real
AST ``Call`` nodes through the shared source index instead of
regexing, so docstring examples can never register as call sites.

This file keeps the standalone entry point and the exact API tier-1
tests import (``EXPECTED_POINTS`` / ``find_points`` / ``check``)::

    python tools/check_fault_points.py [REPO_ROOT]
"""

from __future__ import annotations

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)
try:
    import nezha_tpu  # noqa: F401 — the full package, when jax exists
except Exception:
    # Stdlib-only fallback (the checkers' original no-dependencies
    # promise): `import nezha_tpu.analysis` would execute the package
    # __init__, which imports the whole jax-backed framework. On a box
    # without jax, register a bare namespace stub instead — the
    # analysis subpackage itself is stdlib-only and loads fine alone.
    import types
    _pkg = types.ModuleType("nezha_tpu")
    _pkg.__path__ = [os.path.join(_ROOT, "nezha_tpu")]
    sys.modules["nezha_tpu"] = _pkg

from nezha_tpu.analysis.rules.fault_points import (  # noqa: E402,F401
    EXPECTED_POINTS, check, find_points)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else _ROOT
    errors = check(root)
    for e in errors:
        print(e, file=sys.stderr)
    if errors:
        print(f"FAIL: {len(errors)} fault-registry violation(s)",
              file=sys.stderr)
        return 1
    points = find_points(root)
    print(f"OK: {len(points)} fault point(s) registered, documented, "
          f"and tested")
    return 0


if __name__ == "__main__":
    sys.exit(main())
