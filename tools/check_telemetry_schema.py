#!/usr/bin/env python3
"""Frozen-schema validator for telemetry run dirs — shim over
``nezha_tpu.analysis``.

The pinned instrument/span sets and the run-dir validation live in
``nezha_tpu/analysis/telemetry_schema.py`` now, shared between this
capture-side check and the ``telemetry-schema`` lint rule (which pins
the same names at the SOURCE — every literal ``obs.*`` instrument in a
pinned namespace must be a member). See that module's docstring for
schema v1.

This file keeps the standalone entry point and the API tier-1 tests
import (``check_run_dir``)::

    python tools/check_telemetry_schema.py /tmp/run
"""

from __future__ import annotations

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)
try:
    import nezha_tpu  # noqa: F401 — the full package, when jax exists
except Exception:
    # Stdlib-only fallback (see check_fault_points.py): load the
    # analysis subpackage under a namespace stub so this checker keeps
    # working on boxes without jax.
    import types
    _pkg = types.ModuleType("nezha_tpu")
    _pkg.__path__ = [os.path.join(_ROOT, "nezha_tpu")]
    sys.modules["nezha_tpu"] = _pkg

from nezha_tpu.analysis.telemetry_schema import (  # noqa: E402,F401
    EVENT_KINDS, EVENT_SCHEMA_VERSION, EXPOSITION_PREFIX,
    EXPOSITION_WINDOW_LABELS, SCHEMA_VERSION, STATS_SCHEMA_VERSION,
    _DIST_COUNTERS, _PINNED_SPANS, _PINNED_SPAN_PREFIXES,
    _ROUTER_COUNTERS, _ROUTER_GAUGES, _ROUTER_HISTOGRAMS,
    _SERVE_COUNTERS, _SERVE_GAUGES, _SERVE_HISTOGRAMS,
    check_events_jsonl, check_metrics_exposition, check_metrics_jsonl,
    check_run_dir, check_spans_jsonl, check_stats_payload,
    check_summary_json)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print(__doc__, file=sys.stderr)
        return 2
    errors = check_run_dir(argv[0])
    for e in errors:
        print(e, file=sys.stderr)
    if errors:
        print(f"FAIL: {len(errors)} schema violation(s)", file=sys.stderr)
        return 1
    print("OK: telemetry artifacts match schema v1")
    return 0


if __name__ == "__main__":
    sys.exit(main())
