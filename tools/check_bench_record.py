#!/usr/bin/env python3
"""Sanity-check committed BENCH_*.json perf records — shim over
``nezha_tpu.analysis``.

The validation core lives in ``nezha_tpu/analysis/bench_records.py``
(whose docstring tells the BENCH_r03–r05 crash-record story), shared
between this standalone checker and the ``bench-records`` lint rule:
every committed record must be valid JSON, a real measurement, and
platform-labeled — or explicitly superseded in BENCH_NOTES.md.

This file keeps the standalone entry point and the API tier-1 tests
import (``check_dir`` / ``check_record`` / ``superseded_records``)::

    python tools/check_bench_record.py            # repo root
    python tools/check_bench_record.py /some/dir
"""

from __future__ import annotations

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)
try:
    import nezha_tpu  # noqa: F401 — the full package, when jax exists
except Exception:
    # Stdlib-only fallback (see check_fault_points.py): load the
    # analysis subpackage under a namespace stub so this checker keeps
    # working on boxes without jax.
    import types
    _pkg = types.ModuleType("nezha_tpu")
    _pkg.__path__ = [os.path.join(_ROOT, "nezha_tpu")]
    sys.modules["nezha_tpu"] = _pkg

from nezha_tpu.analysis.bench_records import (  # noqa: E402,F401
    check_dir, check_record, superseded_records)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else _ROOT
    errors = check_dir(root)
    for e in errors:
        print(e, file=sys.stderr)
    if errors:
        print(f"FAIL: {len(errors)} bench-record violation(s)",
              file=sys.stderr)
        return 1
    skip = sorted(superseded_records(root))
    note = f" ({len(skip)} superseded, skipped)" if skip else ""
    print(f"OK: committed bench records are platform-labeled and "
          f"schema-valid{note}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
