"""Flash-decode kernel vs composed masked attention, on the decode shape.

The serving decode step computes attention for ONE query token per row
against a pooled ``[B, H, L_max, D]`` KV cache. This bench measures that
op in isolation — the Pallas split-K kernel
(``ops.pallas.flash_decode_attention``, per-row lengths skip KV blocks)
against the composed path the engine used before it (dense
``dot_product_attention`` under a ``[B, 1, 1, L_max]`` ``-inf`` mask) —
sweeping batch size, pool capacity, and per-row length SKEW: the skew
sweep is the kernel's whole argument, because the dense path's cost is
flat in the lengths while the kernel's is proportional to
``sum(lengths)``.

With ``--run-dir`` each configuration is recorded through the standard
telemetry artifacts (one metrics.jsonl record per config), so runs can
be diffed like any other capture. On non-TPU backends the kernel runs in
INTERPRET mode — numerically the real kernel, wildly slower than
compiled; the record carries ``backend``/``interpreted`` so nobody reads
a CPU artifact as a perf claim (tier-1 runs it for correctness/coverage
at tiny shapes).

Usage::

    python benchmarks/decode_attention.py --batch-sizes 4 --max-lens 128 \
        --skews full,half,short,mixed --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

SKEWS = ("full", "half", "short", "mixed", "one_active")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-sizes", default="4",
                   help="comma-separated slot counts B")
    p.add_argument("--max-lens", default="128",
                   help="comma-separated KV pool capacities L_max")
    p.add_argument("--num-heads", type=int, default=12)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--skews", default="full,half,short,mixed",
                   help=f"comma-separated per-row length patterns from "
                        f"{SKEWS}: full = every row at L_max, half/short "
                        f"= L_max/2 / L_max/8, mixed = linspace(1, "
                        f"L_max), one_active = one full row + inactive "
                        f"rest")
    p.add_argument("--dtype", choices=["bf16", "f32"], default="bf16",
                   help="cache dtype (q follows)")
    p.add_argument("--block-k", type=int, default=None)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--run-dir", default=None,
                   help="write telemetry artifacts here")
    p.add_argument("--json", action="store_true")
    p.add_argument("--platform", default=None)
    return p


def _make_lengths(skew: str, b: int, L: int) -> np.ndarray:
    if skew == "full":
        lens = np.full((b,), L)
    elif skew == "half":
        lens = np.full((b,), max(1, L // 2))
    elif skew == "short":
        lens = np.full((b,), max(1, L // 8))
    elif skew == "mixed":
        lens = np.linspace(1, L, b).round()
    elif skew == "one_active":
        lens = np.zeros((b,))
        lens[0] = L
    else:
        raise SystemExit(f"unknown skew {skew!r} (choose from {SKEWS})")
    return lens.astype(np.int32)


def _time(fn, args, iters: int, warmup: int) -> float:
    """Median seconds per call, device-synchronized."""
    import jax
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        samples.append(time.perf_counter() - t0)
    return float(np.median(samples))


def run(args) -> dict:
    from nezha_tpu.cli.common import setup_jax
    setup_jax(args)

    import jax
    import jax.numpy as jnp

    from nezha_tpu import obs, ops
    from nezha_tpu.ops.pallas import flash_decode_attention

    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    backend = jax.default_backend()
    interpreted = backend != "tpu"

    @jax.jit
    def kernel(q, k, v, lens):
        return flash_decode_attention(q, k, v, lens,
                                      block_k=args.block_k)

    @jax.jit
    def composed(q, k, v, lens):
        L = k.shape[2]
        mask = jnp.where(jnp.arange(L)[None, :] < lens[:, None],
                         0.0, -jnp.inf).astype(jnp.float32)
        return ops.dot_product_attention(q, k.astype(q.dtype),
                                         v.astype(q.dtype),
                                         mask=mask[:, None, None, :])

    sink = None
    if args.run_dir:
        sink = obs.start_run(args.run_dir, meta={
            "tool": "benchmarks/decode_attention", "backend": backend,
            "dtype": args.dtype, "interpreted": interpreted})

    configs = []
    step = 0
    for b in (int(x) for x in str(args.batch_sizes).split(",")):
        for L in (int(x) for x in str(args.max_lens).split(",")):
            kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
            h, d = args.num_heads, args.head_dim
            q = jax.random.normal(kq, (b, h, 1, d), dtype)
            k = jax.random.normal(kk, (b, h, L, d), dtype)
            v = jax.random.normal(kv, (b, h, L, d), dtype)
            for skew in str(args.skews).split(","):
                lens = jnp.asarray(_make_lengths(skew, b, L))
                t_kernel = _time(kernel, (q, k, v, lens),
                                 args.iters, args.warmup)
                t_composed = _time(composed, (q, k, v, lens),
                                   args.iters, args.warmup)
                rec = {"B": b, "L_max": L, "skew": skew,
                       "kernel_ms": t_kernel * 1e3,
                       "composed_ms": t_composed * 1e3,
                       "speedup": t_composed / t_kernel if t_kernel
                       else 0.0}
                configs.append(rec)
                obs.record_metrics(step, {"bench": "decode_attention",
                                          **rec})
                step += 1
                if not args.json:
                    print(f"B={b} L={L} {skew:>10}: kernel "
                          f"{rec['kernel_ms']:8.3f} ms  composed "
                          f"{rec['composed_ms']:8.3f} ms  "
                          f"({rec['speedup']:.2f}x)")

    record = {"backend": backend, "interpreted": interpreted,
              "dtype": args.dtype, "num_heads": args.num_heads,
              "head_dim": args.head_dim, "iters": args.iters,
              "configs": configs}
    if sink is not None:
        obs.end_run()
    if args.json:
        print(json.dumps(record, indent=2, sort_keys=True))
    return record


def main(argv=None) -> int:
    run(build_parser().parse_args(argv))
    return 0


if __name__ == "__main__":
    sys.exit(main())
