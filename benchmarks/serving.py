"""Serving load generator: offered load vs TTFT/TPOT percentiles.

Drives the in-process continuous-batching stack (`nezha_tpu.serve`) the
way EQuARX-style training benchmarks drive collectives: measure the REAL
hot path (admission -> slot prefill -> batched decode) rather than a
proxy, and write the same run-dir telemetry artifacts `nezha-train`
produces, so `nezha-telemetry RUN_DIR` renders the serving report and
`tools/check_telemetry_schema.py` validates it.

Two load models:

- **closed** loop (--concurrency N): N requests always outstanding —
  measures capacity (tokens/sec at full batch occupancy).
- **open** loop (--rate R): Poisson arrivals at R req/s wall-clock —
  measures latency under offered load; queue-full arrivals are DROPPED
  and counted (that is the backpressure behaving, not an error).

With ``--replicas N`` (closed loop only) the same load drives the
multi-replica ROUTER instead of one scheduler — N thread-hosted
replicas, each its own engine behind a real HTTP socket — and
``--kill-rate R`` hard-kills live replicas on a seeded Poisson schedule
while the load runs: the record pins ``lost == 0`` (every request gets
a 200 or a typed error) next to kills / restarts / failovers and
clean-finish percentiles (docs/RUNBOOK.md §10).

Usage::

    python benchmarks/serving.py --requests 32 --concurrency 4 \
        --run-dir /tmp/serve_bench --json
    python benchmarks/serving.py --mode open --rate 20 --requests 64
    python benchmarks/serving.py --replicas 3 --kill-rate 0.5 \
        --requests 64 --concurrency 8 --json
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--mode", choices=["closed", "open"], default="closed")
    p.add_argument("--requests", type=int, default=16,
                   help="total requests to issue")
    p.add_argument("--concurrency", type=int, default=4,
                   help="closed loop: requests kept outstanding")
    p.add_argument("--rate", type=float, default=8.0,
                   help="open loop: offered arrivals per second")
    p.add_argument("--prompt-len", type=int, default=8)
    p.add_argument("--prompt-len-mix", default=None,
                   help="comma-separated prompt lengths cycled across "
                        "requests (overrides --prompt-len) — a mixed-"
                        "length load exercises the prefill buckets, and "
                        "the record reports TTFT percentiles per bucket")
    p.add_argument("--max-new-tokens", type=int, default=16)
    p.add_argument("--sample-fraction", type=float, default=0.5,
                   help="fraction of requests that sample at temperature "
                        "0.8 / top-k 40 (rest decode greedy) — a mixed "
                        "batch exercises the per-row sampling path")
    p.add_argument("--max-batch-size", type=int, default=4)
    p.add_argument("--max-len", type=int, default=64)
    p.add_argument("--max-prefill-len", type=int, default=16)
    p.add_argument("--prefill-buckets", default=None,
                   help="comma-separated static prefill pad widths "
                        "(default: powers of two up to --max-prefill-len)")
    p.add_argument("--decode-impl",
                   choices=["auto", "kernel", "xla"], default=None,
                   help="decode attention: flash-decode kernel vs the "
                        "composed masked path (the before/after knob)")
    p.add_argument("--prefill-impl",
                   choices=["auto", "kernel", "xla"], default=None,
                   help="paged prefill attention: flash-prefill kernel "
                        "(int8 pools fuse the block write into its "
                        "epilogue) vs the composed masked path (the "
                        "TTFT before/after knob)")
    p.add_argument("--prefill-mode",
                   choices=["replicated", "sequence"],
                   default="replicated",
                   help="prefill chunk parallelism: replicated = every "
                        "mesh device computes the full chunk; sequence "
                        "= shard the chunk over the 1xM mesh's "
                        "sequence axis (needs --mesh M > 1 — the "
                        "long-context before/after knob)")
    p.add_argument("--long-prefill-buckets", default=None,
                   help="comma-separated extra prefill pad widths "
                        "above --max-prefill-len (inside --max-len) so "
                        "long prompts prefill in a few wide chunks")
    p.add_argument("--seq-prefill-variant",
                   choices=["auto", "ulysses", "ring"], default="auto",
                   help="sequence-mode attention algorithm (auto = "
                        "ulysses)")
    p.add_argument("--decode-horizon", default="1",
                   help="tokens decoded per compiled step dispatch; a "
                        "comma-separated list (e.g. 1,4,8) sweeps the "
                        "horizon — one engine + fresh warmup per value, "
                        "with per-horizon sub-records (and per-horizon "
                        "run-dir subdirectories h<N>/) in the output")
    p.add_argument("--kv-layout", choices=["paged", "dense"],
                   default="paged",
                   help="KV pool layout: paged = block-paged pool with "
                        "ref-counted blocks + prefix reuse (default); "
                        "dense = classic worst-case per-slot "
                        "reservation (the before/after knob)")
    p.add_argument("--kv-block-size", type=int, default=16,
                   help="paged: tokens per KV block")
    p.add_argument("--kv-num-blocks", type=int, default=None,
                   help="paged: total pool blocks (block 0 scratch); "
                        "default = dense-equivalent capacity. Set it "
                        "BELOW the dense equivalent to measure "
                        "block-budget admission: concurrency then "
                        "tracks resident tokens, not slots")
    p.add_argument("--kv-dtype", choices=["bf16", "int8"],
                   default="bf16",
                   help="KV block storage: int8 stores blocks as int8 "
                        "+ per-block fp32 scales (paged only) — the "
                        "capacity-at-equal-memory knob; the record "
                        "reports peak resident bytes so equal-byte "
                        "budgets compare directly")
    p.add_argument("--kv-host-blocks", type=int, default=0,
                   help="paged int8: host KV spill tier budget in "
                        "blocks — evicted prefix-cache blocks demote "
                        "to host RAM and promote back on a returning "
                        "prefix hit (0 = off); the record reports "
                        "demotions/promotions and host-tier peaks")
    p.add_argument("--churn-users", type=int, default=0,
                   help="multi-tenant churn scenario (the kv_churn "
                        "suite's traffic shape): N > 0 cycles requests "
                        "over N 'users', each with a fixed block-"
                        "aligned prompt prefix + a fresh per-visit "
                        "tail — sized so device blocks CYCLE between "
                        "a user's visits, a revisit is served from "
                        "the host tier (promote) when --kv-host-blocks "
                        "is set and from a cold re-prefill when not; "
                        "the record splits TTFT by first visit vs "
                        "revisit. Run with --concurrency 1: the "
                        "scenario's eviction cadence assumes visits "
                        "issue sequentially (nothing enforces it)")
    p.add_argument("--churn-prefix-len", type=int, default=None,
                   help="churn: per-user prefix length in tokens "
                        "(default 4 KV blocks); must be block-aligned "
                        "for the full prefix to be cacheable")
    p.add_argument("--prefix-cache", choices=["on", "off"], default="on",
                   help="paged: shared-prefix prefill reuse on/off")
    p.add_argument("--shared-prefix-frac", type=float, default=0.0,
                   help="templated traffic: this fraction of requests "
                        "share one common prompt prefix — with the "
                        "paged pool + prefix cache they take block "
                        "REFERENCES instead of re-prefilling, and the "
                        "record reports prefix-hit-rate, "
                        "blocks-resident, and TTFT split by hit/miss")
    p.add_argument("--shared-prefix-len", type=int, default=None,
                   help="shared prefix length in tokens (default: 2 KV "
                        "blocks); non-shared requests are padded to "
                        "the same total length so hit/miss TTFT "
                        "compares like for like")
    p.add_argument("--speculative", action="store_true",
                   help="speculative decoding: a draft model proposes "
                        "--draft-k tokens per window, one target "
                        "forward verifies them — the record gains "
                        "spec{draft_k, accept_rate, tokens_per_verify} "
                        "and the headline tokens/sec reflects >1 token "
                        "emitted per verify dispatch")
    p.add_argument("--draft-k", type=int, default=4,
                   help="speculative: draft tokens per verify window")
    p.add_argument("--draft-layers", type=int, default=None,
                   help="speculative: early-exit self-draft depth "
                        "(default: full depth — identity draft, accept "
                        "rate ~1, the machinery-overhead measurement)")
    p.add_argument("--fault-rate", type=float, default=0.0,
                   help="probability per prefill / per decode step of an "
                        "injected fault (prefill errors + NaN logit "
                        "bursts, seeded by --seed) — measures resilience "
                        "overhead: errored requests retire with "
                        "finish_reason 'error' while the run keeps "
                        "serving, and the error/retry counters land in "
                        "the run-dir artifact next to TTFT/TPOT")
    p.add_argument("--queue-capacity", type=int, default=16)
    p.add_argument("--priority-mix", default=None,
                   help="multi-tenant storm traffic: 'class=weight,...' "
                        "over {interactive,batch,background} — each "
                        "request draws its priority class from this "
                        "seeded distribution (default: everything "
                        "interactive, the classic single-lane load). "
                        "The record gains a per-class TTFT split")
    p.add_argument("--priority-scheduling", choices=["on", "off"],
                   default="on",
                   help="'off' SUBMITS every request in the default "
                        "lane (exact pre-WFQ FIFO — the overload_storm "
                        "suite's control) while the record still "
                        "splits TTFT by each request's DRAWN class "
                        "from --priority-mix")
    p.add_argument("--preemption", choices=["on", "off"], default="off",
                   help="preempt lower-priority live decodes to the "
                        "trie/host tier when a higher-priority request "
                        "cannot get a slot or its blocks")
    p.add_argument("--preemption-budget", type=int, default=2,
                   help="max suspensions per request (anti-thrash)")
    p.add_argument("--replicas", type=int, default=1,
                   help="N > 1 drives the multi-replica router "
                        "(supervisor + N in-process replicas, each its "
                        "own engine, reached over real HTTP) instead "
                        "of one scheduler — closed loop only")
    p.add_argument("--affinity-routing", choices=["on", "off"],
                   default="on",
                   help="--replicas > 1: prefix-affinity routing — the "
                        "router scores live replicas by how much of "
                        "the prompt their advertised trie digest "
                        "covers (discounted by load) instead of pure "
                        "least-loaded, and hands near-miss picks a "
                        "peer pull_from pointer; off = the "
                        "least-loaded control")
    p.add_argument("--digest-interval", type=float, default=2.0,
                   help="--replicas > 1: seconds between replica trie-"
                        "digest rebuilds (advertised over /healthz)")
    p.add_argument("--digest-max-entries", type=int, default=256,
                   help="--replicas > 1: bound on advertised digest "
                        "entries per replica (recency-first)")
    p.add_argument("--disaggregate", action="store_true",
                   help="drive the DISAGGREGATED router: "
                        "--prefill-replicas role=prefill workers take "
                        "admissions and park prompt KV, "
                        "--decode-replicas role=decode workers pull "
                        "the migrated blocks (int8+scales wire) and "
                        "stream — the record gains migration GB/s and "
                        "the prefill-wait/decode-wait queueing split "
                        "(closed loop only)")
    p.add_argument("--prefill-replicas", type=int, default=1,
                   help="disaggregated: prefill-tier size")
    p.add_argument("--decode-replicas", type=int, default=1,
                   help="disaggregated: decode-tier size")
    p.add_argument("--kill-rate", type=float, default=0.0,
                   help="expected replica kills per second (seeded "
                        "Poisson schedule) while the measured load "
                        "runs — requires --replicas > 1 (or "
                        "--disaggregate, where kills are AIMED AT THE "
                        "PREFILL TIER: the mid-migration crash drill); "
                        "killed replicas are restarted by the "
                        "supervisor and the record reports kills / "
                        "restarts / failovers / typed errors next to "
                        "the clean-finish percentiles")
    p.add_argument("--model-preset", choices=["tiny", "full"],
                   default="tiny")
    p.add_argument("--mesh", type=int, default=1,
                   help="M > 1 runs the engine TENSOR-SHARDED over a "
                        "1xM device mesh (serve/sharded): params "
                        "Megatron-sharded, paged K/V head-sharded, "
                        "frozen program contract per mesh — requires M "
                        "visible devices and num_heads %% M == 0 "
                        "(single-replica closed/open loop only)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--run-dir", default=None,
                   help="write telemetry artifacts here")
    p.add_argument("--obs-windows", choices=["on", "off"], default="on",
                   help="install the rolling-window tap during a "
                        "--run-dir capture (off = the capture-only "
                        "baseline of nezha-bench's scrape_overhead "
                        "suite)")
    p.add_argument("--scrape-interval", type=float, default=0.0,
                   help="when > 0, a background thread renders the "
                        "Prometheus /metrics exposition from the live "
                        "registry every N seconds during the measured "
                        "load — what a 1s scraper costs the serving "
                        "path (needs --run-dir)")
    p.add_argument("--json", action="store_true",
                   help="print the result record as JSON")
    p.add_argument("--platform", default=None)
    return p


def _percentiles(values):
    from nezha_tpu.obs.registry import percentile_of
    s = sorted(values)
    return {"p50": percentile_of(s, 50), "p90": percentile_of(s, 90),
            "p99": percentile_of(s, 99)}


def _parse_priority_mix(spec: str):
    """``'class=weight,...'`` -> ``[(class, cumulative_fraction)]``
    draw table (SystemExit on malformed specs, like every knob)."""
    from nezha_tpu.serve import PRIORITIES
    weights = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        cls, eq, w = part.partition("=")
        cls = cls.strip()
        try:
            val = float(w)
        except ValueError:
            val = -1.0
        if not eq or cls not in PRIORITIES or val <= 0:
            raise SystemExit(
                f"--priority-mix entries must be 'class=weight' with "
                f"class in {PRIORITIES} and weight > 0, got {part!r}")
        weights.append((cls, val))
    if not weights:
        raise SystemExit("--priority-mix must name at least one class")
    total = sum(w for _, w in weights)
    table, cum = [], 0.0
    for cls, w in weights:
        cum += w / total
        table.append((cls, cum))
    return table


def run(args) -> dict:
    # Argv validation BEFORE the (expensive) model build + warmup.
    if not 0.0 <= args.fault_rate < 1.0:
        raise SystemExit(f"--fault-rate must be in [0, 1), got "
                         f"{args.fault_rate}")
    try:
        horizons = [int(h) for h in str(args.decode_horizon).split(",")]
        if not horizons or min(horizons) < 1:
            raise ValueError
    except ValueError:
        raise SystemExit(f"--decode-horizon must be comma-separated "
                         f"ints >= 1, got {args.decode_horizon!r}")
    if args.replicas < 1:
        raise SystemExit(f"--replicas must be >= 1, got {args.replicas}")
    if args.kill_rate < 0:
        raise SystemExit(f"--kill-rate must be >= 0, got "
                         f"{args.kill_rate}")
    if args.disaggregate:
        if args.prefill_replicas < 1 or args.decode_replicas < 1:
            raise SystemExit("--disaggregate needs --prefill-replicas "
                             "and --decode-replicas both >= 1")
    elif args.kill_rate > 0 and args.replicas < 2:
        raise SystemExit("--kill-rate needs --replicas > 1 (killing "
                         "the only replica measures a blackout, not "
                         "failover)")
    from nezha_tpu.cli.common import setup_jax
    setup_jax(args)

    if (getattr(args, "prefill_mode", "replicated") == "sequence"
            and int(getattr(args, "mesh", 1) or 1) < 2):
        raise SystemExit("--prefill-mode sequence requires --mesh M "
                         "with M > 1 (the chunk is sharded over the "
                         "mesh's sequence axis)")
    if getattr(args, "mesh", 1) > 1 and (args.replicas > 1
                                         or args.disaggregate):
        raise SystemExit("--mesh > 1 applies to the single-replica "
                         "loops (the router benches compose meshes "
                         "via nezha-serve --replicas --mesh)")
    if args.replicas > 1 or args.disaggregate:
        if len(horizons) != 1:
            raise SystemExit("--replicas > 1 takes a single "
                             "--decode-horizon value, not a sweep")
        if args.mode != "closed":
            raise SystemExit("--replicas > 1 supports closed-loop "
                             "load only (open-loop arrivals belong to "
                             "the single-replica latency study)")
        if getattr(args, "churn_users", 0) and not args.disaggregate:
            record = _run_fleet(args, horizons[0])
        else:
            record = _run_replicas(args, horizons[0])
        if args.json:
            print(json.dumps(record, indent=2, sort_keys=True))
        elif "fleet" in record:
            fl = record["fleet"]
            peer = fl.get("peer_pull") or {}
            print(f"fleet replicas={record['replicas']} "
                  f"affinity={fl['affinity_routing']}: "
                  f"{fl['users']} users x {fl['visits']} visits, "
                  f"revisit/first ttft p50 "
                  f"{fl['revisit_vs_first_ttft_p50']:.2f}, "
                  f"{fl['affinity_wins']} affinity wins, "
                  f"{fl['kv_pulls']} pulls "
                  f"({fl['kv_pull_bytes'] / 1024:.1f} KiB), hits "
                  f"{fl['fleet_hits']}, peer installed "
                  f"{peer.get('installed', 0)}")
        else:
            lat = record["latency_s"]
            mig = record.get("migration") or {}
            mig_s = (f", {mig['count']} migrations "
                     f"{mig['gb_per_s'] * 1e3:.2f} MB/s "
                     f"({mig['fallbacks']} fallbacks)"
                     if mig.get("count") is not None else "")
            print(f"replicas={record['replicas']} closed load "
                  f"{record['offered']}: "
                  f"{record['finished_clean']}/{record['requests']} "
                  f"clean ({record['answered']} answered, "
                  f"{record['lost']} lost), "
                  f"{record['kills']} kills {record['restarts']} "
                  f"restarts {record['failovers']} failovers "
                  f"{record['retries']} retries, "
                  f"latency p50 {lat['p50'] * 1e3:.1f} ms "
                  f"p99 {lat['p99'] * 1e3:.1f} ms{mig_s}")
        return record

    import jax

    if args.model_preset == "tiny":
        from nezha_tpu.cli.train import TINY_GPT2_KW
        from nezha_tpu.models.gpt2 import GPT2, GPT2Config
        model = GPT2(GPT2Config(**TINY_GPT2_KW))
    else:
        from nezha_tpu.models.gpt2 import gpt2_124m
        model = gpt2_124m()
    variables = model.init(jax.random.PRNGKey(args.seed))
    if len(horizons) == 1:
        record = _run_one(args, model, variables, horizons[0],
                          args.run_dir)
    else:
        # Horizon sweep: one engine + warmup + (optional) run-dir
        # capture per value, same offered load — the dispatch-
        # amortization record ISSUE 5 establishes.
        by_horizon = {}
        for h in horizons:
            sub = (os.path.join(args.run_dir, f"h{h}")
                   if args.run_dir else None)
            by_horizon[str(h)] = _run_one(args, model, variables, h, sub)
        record = {"sweep": "decode_horizon",
                  "horizons": horizons,
                  "mode": args.mode,
                  "by_horizon": by_horizon}
    if args.json:
        print(json.dumps(record, indent=2, sort_keys=True))
    else:
        for rec in (record["by_horizon"].values()
                    if "by_horizon" in record else [record]):
            gap = rec.get("host_gap_s") or {}
            gap_s = (f", host gap p50 {gap['p50'] * 1e3:.2f} ms"
                     if gap else "")
            sp = rec.get("spec")
            sp_s = (f", spec k={sp['draft_k']} "
                    f"{sp['tokens_per_verify']:.2f} tok/verify "
                    f"({sp['accept_rate']:.0%} accept)" if sp else "")
            print(f"h={rec['decode_horizon']} {rec['mode']} load: "
                  f"{rec['offered']} -> "
                  f"{rec['tokens_per_sec']:.1f} tok/s "
                  f"({rec['steps_per_sec']:.1f} steps/s, "
                  f"{rec['dispatches_per_token']:.3f} disp/tok), "
                  f"ttft p50 {rec['ttft_s']['p50'] * 1e3:.1f} ms, "
                  f"tpot p50 {rec['tpot_s']['p50'] * 1e3:.1f} ms, "
                  f"{rec['dropped_queue_full']} dropped{gap_s}{sp_s}")
    return record


def _run_one(args, model, variables, decode_horizon: int,
             run_dir) -> dict:
    import jax.numpy as jnp

    from nezha_tpu import obs
    from nezha_tpu.serve import (Engine, QueueFull, Request, Scheduler,
                                 ServeConfig)

    buckets = tuple(int(b) for b in args.prefill_buckets.split(",")) \
        if args.prefill_buckets else ()
    spec = None
    if getattr(args, "speculative", False):
        from nezha_tpu.serve.engine import SpeculativeConfig
        spec = SpeculativeConfig(draft_k=args.draft_k,
                                 draft_layers=args.draft_layers)
    cfg = ServeConfig(
        max_batch_size=args.max_batch_size, max_len=args.max_len,
        max_prefill_len=args.max_prefill_len, prefill_buckets=buckets,
        queue_capacity=args.queue_capacity, cache_dtype=jnp.bfloat16,
        decode_impl=args.decode_impl, decode_horizon=decode_horizon,
        prefill_impl=getattr(args, "prefill_impl", None),
        kv_layout=args.kv_layout, kv_block_size=args.kv_block_size,
        kv_num_blocks=args.kv_num_blocks,
        prefix_cache=args.prefix_cache == "on",
        kv_dtype=args.kv_dtype,
        kv_host_blocks=getattr(args, "kv_host_blocks", 0),
        prefill_mode=getattr(args, "prefill_mode", "replicated"),
        long_prefill_buckets=tuple(
            int(b) for b in
            str(args.long_prefill_buckets).split(","))
        if getattr(args, "long_prefill_buckets", None) else (),
        seq_prefill_variant=getattr(args, "seq_prefill_variant",
                                    "auto"),
        preemption=getattr(args, "preemption", "off") == "on",
        preemption_budget=getattr(args, "preemption_budget", 2),
        speculative=spec)
    mesh_m = int(getattr(args, "mesh", 1) or 1)
    if mesh_m > 1:
        from nezha_tpu.serve.sharded import ShardedEngine
        engine = ShardedEngine(model, variables, cfg,
                               mesh_devices=mesh_m)
    else:
        engine = Engine(model, variables, cfg)
    sched = Scheduler(engine)
    rng = random.Random(args.seed)
    vocab = engine.vocab

    prompt_lens = ([int(x) for x in str(args.prompt_len_mix).split(",")]
                   if args.prompt_len_mix else [args.prompt_len])
    prompt_len_of = {}                 # request_id -> prompt length
    # Templated traffic: one seeded common prefix; shared requests are
    # prefix + a short random tail, and NON-shared requests draw a fully
    # random prompt of the SAME total length, so hit-vs-miss TTFT
    # compares equal prefill spans. The cache seeder (first shared
    # arrival to actually PREFILL — a miss by construction) is
    # classified with the misses: classification reads the live trie,
    # so a would-be seeder that never ran (queue-full drop, injected
    # prefill error before registration) doesn't misfile its successor.
    # Multi-tenant churn (the kv_churn scenario): U users, each with a
    # fixed block-aligned prefix, revisited round-robin — request i is
    # user i % U on visit i // U. The pool is expected to be sized so
    # device blocks cycle between a user's visits (the bench harness
    # picks kv_num_blocks ~ 2 users' prefixes): with a host tier the
    # revisit PROMOTES its demoted blocks and prefills one tail chunk;
    # without one it re-prefills cold. TTFT splits by first visit vs
    # revisit are the record.
    churn_users = int(getattr(args, "churn_users", 0) or 0)
    churn_round = {}                   # request_id -> visit index
    churn_plen = 0
    churn_prefixes = []
    if churn_users:
        if args.shared_prefix_frac > 0:
            raise SystemExit("--churn-users and --shared-prefix-frac "
                             "are separate scenarios — pick one")
        churn_plen = args.churn_prefix_len or 4 * args.kv_block_size
        if churn_plen % args.kv_block_size:
            raise SystemExit(
                f"--churn-prefix-len {churn_plen} must be a multiple "
                f"of --kv-block-size {args.kv_block_size} (only full "
                f"blocks are cacheable/demotable)")
        if churn_plen + 2 + args.max_new_tokens > args.max_len:
            raise SystemExit(
                f"--churn-prefix-len {churn_plen} + tail 2 + "
                f"max_new_tokens {args.max_new_tokens} exceeds "
                f"--max-len {args.max_len}")
        churn_prefixes = [[rng.randrange(vocab)
                           for _ in range(churn_plen)]
                          for _ in range(churn_users)]

    shared_prefix = []
    expected_hit = {}                  # request_id -> bool
    if args.shared_prefix_frac > 0:
        plen = args.shared_prefix_len or 2 * args.kv_block_size
        if plen + 2 + args.max_new_tokens > args.max_len:
            raise SystemExit(
                f"--shared-prefix-len {plen} + tail 2 + max_new_tokens "
                f"{args.max_new_tokens} exceeds --max-len {args.max_len}")
        shared_prefix = [rng.randrange(vocab) for _ in range(plen)]

    def _prefix_cached() -> bool:
        trie = getattr(engine.pool, "trie", None)
        return bool(trie and trie.match(shared_prefix))

    # A shared request expects a hit once a prior shared request was
    # actually SUBMITTED (closed-loop bursts create several before the
    # seeder prefills) or the prefix is already in the trie (the
    # backstop that survives a dropped/errored would-be seeder).
    _shared_rids = set()
    _seeder_submitted = {"done": False}

    def note_submitted(rid: str) -> None:
        if rid in _shared_rids:
            _seeder_submitted["done"] = True

    # Multi-tenant storm traffic (the overload_storm suite): each
    # request draws its priority class from the seeded --priority-mix
    # distribution. With --priority-scheduling off the drawn class is
    # RECORDED (the per-class TTFT split still lands in the record) but
    # every submit rides the default lane — the exact pre-WFQ bounded
    # FIFO, the storm suite's head-of-line-blocking control.
    pri_mix = (_parse_priority_mix(args.priority_mix)
               if getattr(args, "priority_mix", None) else None)
    pri_of = {}                        # request_id -> drawn class
    pri_sched = getattr(args, "priority_scheduling", "on") == "on"

    def _draw_priority(rid: str) -> str:
        x = rng.random()
        cls = next((c for c, cum in pri_mix if x < cum),
                   pri_mix[-1][0])
        pri_of[rid] = cls
        return cls if pri_sched else "interactive"

    def make_request(i: int) -> Request:
        sampled = rng.random() < args.sample_fraction
        rid = f"bench-{i}"
        pri = _draw_priority(rid) if pri_mix else "interactive"
        if churn_users:
            u = i % churn_users
            prompt = churn_prefixes[u] + [rng.randrange(vocab),
                                          rng.randrange(vocab)]
            churn_round[rid] = i // churn_users
            prompt_len_of[rid] = len(prompt)
            return Request(prompt=prompt,
                           max_new_tokens=args.max_new_tokens,
                           temperature=0.8 if sampled else 0.0,
                           top_k=40 if sampled else None,
                           seed=i, request_id=rid, priority=pri)
        if shared_prefix and rng.random() < args.shared_prefix_frac:
            prompt = shared_prefix + [rng.randrange(vocab),
                                      rng.randrange(vocab)]
            expected_hit[rid] = (_seeder_submitted["done"]
                                 or _prefix_cached())
            _shared_rids.add(rid)
        elif shared_prefix:
            prompt = [rng.randrange(vocab)
                      for _ in range(len(shared_prefix) + 2)]
            expected_hit[rid] = False
        else:
            n = prompt_lens[i % len(prompt_lens)]
            prompt = [rng.randrange(vocab) for _ in range(n)]
        prompt_len_of[rid] = len(prompt)
        return Request(
            prompt=prompt,
            max_new_tokens=args.max_new_tokens,
            temperature=0.8 if sampled else 0.0,
            top_k=40 if sampled else None,
            seed=i, request_id=rid, priority=pri)

    # Warm EVERY program off the clock — serving steady state never pays
    # trace+compile, and neither should the measurement: one request per
    # prefill bucket (chunked prompts reuse the bucket programs, so this
    # covers long prompts too) plus the shared decode step. Warmup
    # prompts use DISTINCT tokens per bucket: identical prompts would
    # prefix-hit each other in the paged pool, the wider bucket would
    # prefill only its un-cached suffix through a NARROWER program, and
    # the wide program's compile would land inside the measured ttft
    # p99 — the exact spike warmup exists to keep off the clock. The
    # telemetry run starts AFTER warmup so the artifacts hold
    # steady-state percentiles only.
    for j, w in enumerate(engine.cfg.prefill_buckets):
        n = min(w, args.max_len - 1)
        sched.submit(Request(
            prompt=[(131 * j + 7 * i + 1) % vocab for i in range(n)],
            max_new_tokens=1, request_id=f"warmup-{j}"))
    sched.run_until_idle()
    if engine.paged:
        # Warmup must not leak into the measured record: drop its
        # cached blocks (and any host-demoted ones) and zero the reuse
        # counters so prefix_hit_rate, blocks-resident peaks, and the
        # demote/promote ledgers describe the measured load only.
        engine.pool.clear_prefix_cache()
        engine.pool.prefix_hits = 0
        engine.pool.cow_copies = 0
        if engine.pool.host_blocks:
            # Warm the demote/promote maintenance programs too — the
            # first eviction-demotion or promote-hit of the measured
            # load must not pay their compiles inside a TTFT window.
            engine.pool.warm_host_tier_programs()
            engine.pool.clear_host_tier()
            engine.pool.demotions = 0
            engine.pool.promotions = 0
            engine.pool.promote_failures = 0

    # Chaos mode: a seeded probabilistic plan armed AFTER warmup (a
    # faulted warmup would skip compiling a bucket program) injecting
    # the two request-isolated failure modes — prefill errors and NaN
    # logit bursts. Step crashes are excluded on purpose: their bounded
    # retry means back-to-back coin-flip failures would kill the whole
    # run, which is a different experiment than measuring overhead.
    from nezha_tpu import faults
    prev_plan = faults.active()
    plan = None
    if args.fault_rate > 0:
        plan = faults.FaultPlan.parse(
            f"serve.prefill:error%{args.fault_rate};"
            f"serve.step.logits:nan%{args.fault_rate}", seed=args.seed)
        faults.install(plan)

    sink = None
    if run_dir:
        from nezha_tpu.serve.scheduler import register_serve_instruments
        sink = obs.start_run(run_dir, meta={
            "kind": "serve_bench", "mode": args.mode,
            "requests": args.requests,
            "decode_horizon": decode_horizon,
            "offered": (args.concurrency if args.mode == "closed"
                        else args.rate)},
            windows=getattr(args, "obs_windows", "on") == "on")
        register_serve_instruments()
    # The scrape-overhead measurement (nezha-bench scrape_overhead
    # suite): a background thread rendering the full windowed /metrics
    # exposition from the live registry at --scrape-interval, exactly
    # what an external Prometheus scraper costs the serving path.
    scrape_interval = float(getattr(args, "scrape_interval", 0.0) or 0.0)
    scrape_stop = scrape_thread = None
    scrape_count = [0]
    if scrape_interval > 0 and sink is not None:
        import threading

        from nezha_tpu.obs import timeseries as _ts
        scrape_stop = threading.Event()

        def _scraper():
            while not scrape_stop.wait(scrape_interval):
                windows = (_ts.windows_payload()
                           if _ts.current_windows() is not None else None)
                _ts.render_prometheus(obs.stats_snapshot(), windows)
                scrape_count[0] += 1

        scrape_thread = threading.Thread(target=_scraper, daemon=True,
                                         name="bench-scraper")
        scrape_thread.start()
    steps_before = engine.step_calls      # exclude warmup dispatches
    spec_before = ((engine.spec_verifies, engine.spec_draft_tokens,
                    engine.spec_accepted) if spec else (0, 0, 0))

    # (Occupancy percentiles come from the scheduler itself — it folds
    # per-decode occupancy into the metric.batch_occupancy histogram.)
    t0 = time.monotonic()
    issued = finished = dropped = 0
    peak_resident = peak_blocks = peak_host_blocks = 0

    def _track_peaks():
        # The paged-pool occupancy claim: how many requests were
        # RESIDENT (decoding concurrently) and how many KV blocks that
        # took — dense reserves worst-case rows, paged only what's
        # written, so at equal device memory paged peaks strictly
        # higher on under-max_len traffic. The host-tier peak rides
        # along (0 without a tier).
        nonlocal peak_resident, peak_blocks, peak_host_blocks
        peak_resident = max(peak_resident, len(sched._live))
        peak_blocks = max(peak_blocks, engine.pool.blocks_used)
        peak_host_blocks = max(peak_host_blocks,
                               engine.pool.host_blocks_used)

    try:
        if args.mode == "closed":
            while finished < args.requests:
                # Pace by queue room: a closed-loop client waits, it does
                # not shed — hammering submit would inflate rejected_total.
                while (issued < args.requests
                       and issued - finished < args.concurrency
                       and sched.queue_depth < sched.queue_capacity):
                    req = make_request(issued)
                    sched.submit(req)
                    note_submitted(req.request_id)
                    issued += 1
                sched.step()
                _track_peaks()
                # Preempted requests hold no slot and no queue spot but
                # are NOT finished — without this term a preemption-on
                # closed loop would overfeed the queue.
                finished = (issued - sched.queue_depth
                            - len(sched._live) - sched.preempted_count)
        else:
            # Poisson arrivals: exponential inter-arrival gaps at --rate.
            # Arrivals hitting a full queue are DROPPED (open-loop clients
            # don't wait) — the genuine load-shed rejected_total measures.
            arrivals = []
            t = 0.0
            for _ in range(args.requests):
                t += rng.expovariate(args.rate)
                arrivals.append(t)
            while finished + dropped < args.requests:
                now = time.monotonic() - t0
                while issued + dropped < args.requests \
                        and arrivals[issued + dropped] <= now:
                    req = make_request(issued + dropped)
                    try:
                        sched.submit(req)
                        note_submitted(req.request_id)
                        issued += 1
                    except QueueFull:
                        dropped += 1
                if sched.has_work():
                    sched.step()
                    _track_peaks()
                else:
                    time.sleep(0.001)
                finished = (issued - sched.queue_depth
                            - len(sched._live) - sched.preempted_count)
    finally:
        faults.install(prev_plan)
        if scrape_stop is not None:
            scrape_stop.set()
            scrape_thread.join(timeout=2.0)
    wall = time.monotonic() - t0
    decode_steps = engine.step_calls - steps_before

    results = [r for rid, r in sched.results.items()
               if not rid.startswith("warmup")]
    errored = [r for r in results if r.finish_reason == "error"]
    # Error retirements carry partial decodes (or nothing): keep the
    # latency percentiles clean by computing them over CLEAN finishes,
    # while the record reports the error count alongside.
    clean = [r for r in results if r.finish_reason != "error"]
    ttfts = [r.ttft_s for r in clean if r.ttft_s is not None]
    lats = [r.latency_s for r in clean]
    total_tokens = sum(len(r.tokens) for r in results)
    tpots = [(r.latency_s - r.ttft_s) / max(len(r.tokens) - 1, 1)
             for r in clean if r.ttft_s is not None]
    # TTFT per prefill bucket: mixed-length loads show whether short
    # prompts actually get the short-bucket TTFT or queue behind wide
    # prefills (keys are the TAIL-chunk pad widths; chunked prompts
    # group under their tail bucket with chunk count in the label).
    by_bucket = {}
    for r in clean:   # same population as the headline ttft_s above
        n = prompt_len_of.get(r.request_id)
        if n is None or r.ttft_s is None:
            continue
        chunks = -(-n // args.max_prefill_len)  # ceil
        key = f"{engine.bucket_for(n)}" if chunks == 1 \
            else f"{engine.bucket_for(n)}x{chunks}"
        by_bucket.setdefault(key, []).append(r.ttft_s)
    # Host-gap percentiles straight from the live registry (it is only
    # populated while a run is active — the histogram is the same
    # serve.host_gap_s the run-dir summary carries).
    host_gap = None
    if sink is not None:
        hg = obs.histogram("serve.host_gap_s").summary()
        if hg["count"]:
            host_gap = {k: hg[k] for k in ("count", "p50", "p90", "p99")}
    record = {
        "mode": args.mode,
        "offered": (args.concurrency if args.mode == "closed"
                    else args.rate),
        "requests": args.requests, "finished": len(results),
        "dropped_queue_full": dropped,
        "wall_s": wall,
        "tokens": total_tokens,
        "tokens_per_sec": total_tokens / wall if wall else 0.0,
        # The dispatch-amortization record: compiled step dispatches
        # for the measured load (warmup excluded) — horizon H should
        # show ~1/H the dispatches per token of horizon 1.
        "decode_horizon": decode_horizon,
        "decode_steps": decode_steps,
        "steps_per_sec": decode_steps / wall if wall else 0.0,
        "dispatches_per_token": (decode_steps / total_tokens
                                 if total_tokens else 0.0),
        "host_gap_s": host_gap,
        "ttft_s": _percentiles(ttfts),
        "ttft_by_bucket": {k: _percentiles(v)
                           for k, v in sorted(by_bucket.items())},
        "tpot_s": _percentiles(tpots),
        "latency_s": _percentiles(lats),
        "prefill_buckets": list(engine.cfg.prefill_buckets),
        "decode_impl": args.decode_impl or "auto",
        "prefill_impl": getattr(args, "prefill_impl", None) or "auto",
        "mesh_devices": getattr(engine, "mesh_devices", 1),
        "compile_cache": engine.compile_stats(),
        # Paged-pool occupancy record: resident-request and
        # blocks-resident peaks are THE concurrency-at-equal-memory
        # comparison against a dense run (dense peaks at its slot
        # count; paged at what the block budget admits).
        "kv": {
            "layout": args.kv_layout,
            "dtype": args.kv_dtype,
            "block_size": args.kv_block_size,
            "num_blocks": (engine.pool.num_blocks if engine.paged
                           else None),
            "bytes_per_block": (engine.pool.bytes_per_block
                                if engine.paged else None),
            "prefix_cache": args.prefix_cache == "on",
            "prefix_hits": getattr(engine.pool, "prefix_hits", 0),
            "cow_copies": getattr(engine.pool, "cow_copies", 0),
            # Host spill tier (all 0 when --kv-host-blocks is off):
            # the demote/promote ledgers plus the tier's peak
            # occupancy — "promotions tracking demotions" is the
            # churn scenario's health signature.
            "host_blocks": engine.pool.host_blocks,
            "demotions": engine.pool.demotions,
            "promotions": engine.pool.promotions,
            "promote_failures": engine.pool.promote_failures,
            "peak_host_blocks_used": peak_host_blocks,
            "peak_resident_requests": peak_resident,
            "peak_blocks_used": peak_blocks,
            # Peak device bytes the resident KV held — the number the
            # int8-vs-bf16 equal-memory comparison is actually about
            # (blocks are not comparable across dtypes; bytes are).
            "peak_bytes_resident": (
                peak_blocks * engine.pool.bytes_per_block
                if engine.paged else peak_resident
                * engine.pool._slot_bytes),
        },
        "faults": {
            "rate": args.fault_rate,
            "injected": plan.num_injected if plan else 0,
            "by_point": plan.injected_counts if plan else {},
            "errored": len(errored),
        },
        # What the telemetry plane itself cost this record: whether the
        # rolling-window tap was installed, and how many /metrics
        # expositions the in-process scraper rendered during the load.
        "telemetry": {
            "windows": (run_dir is not None
                        and getattr(args, "obs_windows", "on") == "on"),
            "scrape_interval_s": scrape_interval,
            "scrapes": scrape_count[0],
        },
    }
    if spec:
        # The speculative headline (ISSUE 13 acceptance): tokens
        # EMITTED per verify dispatch (> 1 means the draft is paying
        # for itself) and the realized draft accept rate, measured
        # over the post-warmup load only.
        verifies = engine.spec_verifies - spec_before[0]
        drafted = engine.spec_draft_tokens - spec_before[1]
        accepted = engine.spec_accepted - spec_before[2]
        record["spec"] = {
            "draft_k": spec.draft_k,
            "draft_layers": spec.draft_layers,
            "verifies": verifies,
            "draft_tokens": drafted,
            "accepted_tokens": accepted,
            "accept_rate": accepted / drafted if drafted else 0.0,
            "tokens_per_verify": ((accepted + verifies) / verifies
                                  if verifies else 0.0),
        }
    if pri_mix:
        # TTFT split by DRAWN class over clean finishes — with
        # --priority-scheduling off this shows what FIFO head-of-line
        # blocking costs each class; with it on (+ preemption) it is
        # the overload_storm suite's gated record. Preempt/resume
        # ledgers ride along (always 0 when --preemption off).
        by_class = {}
        for cls in ("interactive", "batch", "background"):
            rs = [r for r in clean if pri_of.get(r.request_id) == cls]
            ts = [r.ttft_s for r in rs if r.ttft_s is not None]
            by_class[cls] = {
                "drawn": sum(1 for p in pri_of.values() if p == cls),
                "finished": len(rs),
                "tokens": sum(len(r.tokens) for r in rs),
                "ttft_s": _percentiles(ts or [0.0]),
                "latency_s": _percentiles(
                    [r.latency_s for r in rs] or [0.0]),
            }
        record["priorities"] = {
            "mix": args.priority_mix,
            "priority_scheduling": pri_sched,
            "preemption": getattr(args, "preemption", "off") == "on",
            "preemption_budget": getattr(args, "preemption_budget", 2),
            "preemptions": sched.preemptions,
            "resumes": sched.resumes,
            "by_class": by_class,
        }
    if churn_users:
        # TTFT by first visit vs revisit over clean finishes: a first
        # visit is a cold prefill by construction; a revisit is served
        # from whatever tier still holds the user's prefix — device
        # trie (fast), host tier via promote (the tentpole's win), or
        # nothing (cold again — the no-host-tier control). The
        # revisit/first p50 ratio is the kv_churn suite's gated
        # number, and promotions > 0 is what proves the host tier (not
        # lucky device residency) served the revisits.
        first = [r.ttft_s for r in clean
                 if churn_round.get(r.request_id) == 0
                 and r.ttft_s is not None]
        revisit = [r.ttft_s for r in clean
                   if churn_round.get(r.request_id, 0) > 0
                   and r.ttft_s is not None]
        p_first = _percentiles(first or [0.0])
        p_revisit = _percentiles(revisit or [0.0])
        record["kv_churn"] = {
            "users": churn_users,
            "visits_per_user": -(-args.requests // churn_users),
            "prefix_len": churn_plen,
            "host_blocks": engine.pool.host_blocks,
            "demotions": engine.pool.demotions,
            "promotions": engine.pool.promotions,
            "promote_failures": engine.pool.promote_failures,
            "prefix_hits": getattr(engine.pool, "prefix_hits", 0),
            "ttft_first_visit_s": p_first,
            "ttft_revisit_s": p_revisit,
            "revisit_vs_first_ttft_p50": (
                p_revisit["p50"] / max(p_first["p50"], 1e-9)),
        }
    if shared_prefix:
        # TTFT by hit/miss over clean finishes: the prefix-reuse win is
        # the GAP between these two (a hit skips the shared span's
        # prefill entirely; its TTFT is queue wait + one short tail
        # chunk + its first block slice).
        ttft_hit = [r.ttft_s for r in clean
                    if expected_hit.get(r.request_id)
                    and r.ttft_s is not None]
        ttft_miss = [r.ttft_s for r in clean
                     if not expected_hit.get(r.request_id)
                     and r.ttft_s is not None]
        record["shared_prefix"] = {
            "frac": args.shared_prefix_frac,
            "len": len(shared_prefix),
            "expected_hits": sum(expected_hit.values()),
            "prefix_hit_rate": (getattr(engine.pool, "prefix_hits", 0)
                                / len(results) if results else 0.0),
            "ttft_hit_s": _percentiles(ttft_hit or [0.0]),
            "ttft_miss_s": _percentiles(ttft_miss or [0.0]),
        }
    if sink is not None:
        obs.end_run()
        # The stitched-trace block (ISSUE 12): per-segment TTFT
        # decomposition percentiles from this run's own spans — every
        # measured request carried a trace id (the scheduler mints at
        # submit while the run is active), so nezha-bench can gate
        # each timeline segment, not just the total.
        from nezha_tpu.obs.report import trace_summary
        record["trace"] = trace_summary(run_dir)
    return record


def _run_replicas(args, decode_horizon: int) -> dict:
    """Closed-loop load against the multi-replica router, optionally
    under a seeded replica-kill schedule (``--kill-rate``): measures
    what scale-out is FOR — the service keeps answering while members
    die and restart. Every request gets exactly one answer (200 or a
    typed error object); the record pins ``lost == 0`` alongside
    kills / restarts / failovers / retries and clean-finish
    percentiles. Replicas are thread-backed (each its own engine,
    reached over real HTTP sockets, killable mid-decode) so the bench
    pays one process.

    With ``--disaggregate`` the topology is ``--prefill-replicas``
    role=prefill members + ``--decode-replicas`` role=decode members:
    admissions park prompt KV on the prefill tier, finished prompts
    migrate over the int8+scales wire, and the record adds migration
    GB/s, the prefill-wait/decode-wait queueing split, and fallback
    counts; ``--kill-rate`` then AIMS at the prefill tier — the
    SIGKILL-mid-migration chaos drill."""
    import threading

    from nezha_tpu import faults, obs
    from nezha_tpu.cli.serve import build_parser as serve_parser
    from nezha_tpu.serve.router import Router, register_router_instruments
    from nezha_tpu.serve.scheduler import register_serve_instruments
    from nezha_tpu.serve.supervisor import (RouterConfig, Supervisor,
                                            ThreadBackend)

    wargv = ["--random-init", "--model-preset", args.model_preset,
             "--max-batch-size", str(args.max_batch_size),
             "--max-len", str(args.max_len),
             "--max-prefill-len", str(args.max_prefill_len),
             "--queue-capacity", str(args.queue_capacity),
             "--decode-horizon", str(decode_horizon),
             "--max-new-tokens", str(args.max_new_tokens),
             # KV-pool shape rides into every worker (the fleet KV
             # scenarios need paged pools with pinned block geometry;
             # plain replica runs get the same defaults they always
             # did), and the digest knobs ride along so /healthz
             # advertises what the affinity scorer consumes.
             "--kv-layout", args.kv_layout,
             "--kv-block-size", str(args.kv_block_size),
             "--kv-dtype", args.kv_dtype,
             "--kv-host-blocks", str(getattr(args, "kv_host_blocks", 0)),
             "--prefix-cache", args.prefix_cache,
             "--digest-interval", str(args.digest_interval),
             "--digest-max-entries", str(args.digest_max_entries),
             "--seed", str(args.seed)]
    if args.kv_num_blocks:
        wargv += ["--kv-num-blocks", str(args.kv_num_blocks)]
    if args.prefill_buckets:
        wargv += ["--prefill-buckets", str(args.prefill_buckets)]
    if args.decode_impl:
        wargv += ["--decode-impl", args.decode_impl]
    if getattr(args, "prefill_impl", None):
        wargv += ["--prefill-impl", args.prefill_impl]
    if args.platform:
        wargv += ["--platform", args.platform]
    if getattr(args, "speculative", False):
        # Speculation rides into every replica worker, exactly as the
        # nezha-serve front end forwards it (the router is draft-blind).
        wargv += ["--speculative", "--draft-k", str(args.draft_k)]
        if args.draft_layers is not None:
            wargv += ["--draft-layers", str(args.draft_layers)]
    wargs = serve_parser().parse_args(wargv)
    roles: tuple = ()
    total = args.replicas
    if args.disaggregate:
        roles = (("prefill",) * args.prefill_replicas
                 + ("decode",) * args.decode_replicas)
        total = len(roles)
    cfg = RouterConfig(
        replicas=total, roles=roles,
        probe_interval_s=0.1, probe_misses=3,
        restart_backoff_base_s=0.05, restart_backoff_max_s=0.5,
        drain_timeout_s=5.0, seed=args.seed,
        affinity_routing=args.affinity_routing == "on",
        digest_interval_s=args.digest_interval,
        digest_max_entries=args.digest_max_entries)
    sup = Supervisor(ThreadBackend(wargs, drain_timeout_s=5.0,
                                   roles=roles), cfg)
    router = Router(sup, cfg)

    rng = random.Random(args.seed)
    vocab = 512 if args.model_preset == "tiny" else 50257
    prompt_lens = ([int(x) for x in str(args.prompt_len_mix).split(",")]
                   if args.prompt_len_mix else [args.prompt_len])
    payloads = []
    for i in range(args.requests):
        sampled = rng.random() < args.sample_fraction
        n = prompt_lens[i % len(prompt_lens)]
        p = {"id": f"bench-{i}",
             "prompt_tokens": [rng.randrange(vocab) for _ in range(n)],
             "max_new_tokens": args.max_new_tokens, "seed": i}
        if sampled:
            p.update(temperature=0.8, top_k=40)
        payloads.append(p)

    sink = plan = None
    prev_plan = faults.active()
    try:
        sup.start()
        router.start()
        if not router.wait_live(total, timeout_s=600):
            raise SystemExit(f"replicas never became live: "
                             f"{sup.describe()}")
        # Warm EVERY replica's programs off the clock — every prompt
        # length in the mix, posted DIRECTLY to each replica's port
        # (router balancing could race two warmups onto one replica
        # and leave another cold; a cold bucket or step program would
        # then compile inside the measured percentiles). Mirrors
        # _run_one's per-bucket warmup.
        import http.client

        def _warm_one(port, j, n):
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=600)
            try:
                # Distinct tokens per warmup (see _run_one): identical
                # prompts would prefix-hit in a replica's paged pool
                # and leave wider bucket programs cold.
                conn.request("POST", "/generate", body=json.dumps(
                    {"id": f"warmup-{port}-{j}",
                     "prompt_tokens": [(131 * j + 7 * i + 1) % vocab
                                       for i in range(n)],
                     "max_new_tokens": 1}).encode())
                conn.getresponse().read()
            finally:
                conn.close()

        warm = [threading.Thread(target=_warm_one, args=(r.port, j, n))
                for r in sup.live_replicas()
                for j, n in enumerate(sorted(set(prompt_lens)))]
        for t in warm:
            t.start()
        for t in warm:
            t.join()
        if args.fault_rate > 0:
            plan = faults.FaultPlan.parse(
                f"serve.prefill:error%{args.fault_rate};"
                f"serve.step.logits:nan%{args.fault_rate}",
                seed=args.seed)
            faults.install(plan)
        if args.run_dir:
            sink = obs.start_run(args.run_dir, meta={
                "kind": "serve_router_bench", "mode": "closed",
                "replicas": total, "kill_rate": args.kill_rate,
                "roles": ",".join(roles) if roles else "both",
                "requests": args.requests,
                "decode_horizon": decode_horizon,
                "offered": args.concurrency})
            register_router_instruments()
            register_serve_instruments()
        retries0, failovers0 = router.retries, router.failovers
        restarts0 = sup.restarts
        migrations0, mig_bytes0 = router.migrations, router.migration_bytes
        mig_secs0 = router.migration_seconds
        fallbacks0 = router.migrate_fallbacks

        lock = threading.Lock()
        next_idx = {"n": 0}
        results = []

        def client():
            while True:
                with lock:
                    i = next_idx["n"]
                    if i >= args.requests:
                        return
                    next_idx["n"] += 1
                t_req = time.monotonic()
                code, obj = router.route(payloads[i])
                with lock:
                    results.append(
                        (i, code, obj, time.monotonic() - t_req))

        kills = []
        stop_kill = threading.Event()

        def killer():
            # Seeded Poisson kill schedule; never kills the LAST live
            # replica (that measures a blackout, not failover). On a
            # disaggregated topology the kills are AIMED at the
            # prefill tier — the SIGKILL-mid-migration drill the
            # acceptance pins (decode members survive to prove the
            # failover; the local-decode fallback covers the window
            # where the whole prefill tier is down).
            krng = random.Random(args.seed + 1)
            while not stop_kill.is_set():
                if stop_kill.wait(min(krng.expovariate(args.kill_rate),
                                      5.0)):
                    return
                live = sup.live_replicas()
                pool = ([r for r in live if r.role == "prefill"]
                        if args.disaggregate else live)
                if len(live) >= 2 and pool:
                    victim = pool[krng.randrange(len(pool))].rid
                    sup.kill(victim)
                    kills.append(victim)

        t0 = time.monotonic()
        clients = [threading.Thread(target=client)
                   for _ in range(args.concurrency)]
        for t in clients:
            t.start()
        kt = None
        if args.kill_rate > 0:
            kt = threading.Thread(target=killer, daemon=True)
            kt.start()
        for t in clients:
            t.join()
        stop_kill.set()
        if kt is not None:
            kt.join(timeout=10)
        wall = time.monotonic() - t0
        # Recovery check: the supervisor should restart every kill;
        # give backoff a moment before reading the final live count.
        router.wait_live(total, timeout_s=120)
        recovered_live = sup.live_count()
    finally:
        faults.install(prev_plan)
        if sink is not None:
            obs.end_run()
        router.stop()
        sup.shutdown()

    ok = [(i, c, o, lat) for i, c, o, lat in results if c == 200]
    clean = [(i, c, o, lat) for i, c, o, lat in ok
             if o.get("finish_reason") in ("length", "eos")]
    errors_typed = {}
    for i, c, o, lat in results:
        if c != 200:
            kind = (o.get("error_type") if isinstance(o, dict)
                    else None) or f"http_{c}"
            errors_typed[kind] = errors_typed.get(kind, 0) + 1
    tokens = sum(len(o.get("tokens", [])) for _, _, o, _ in ok)
    # Per-token decode latency from the SERVING replica's own clock
    # (worker-reported latency_s/ttft_s pair — route latency would
    # fold admission hops and the migration transfer into "decode"
    # time): the decode-tier steady-state number the disaggregation
    # acceptance compares against the co-located baseline. Falls back
    # to the route latency for stub replicas that report none.
    tpots = [((o["latency_s"] if o.get("latency_s") is not None
               else lat) - o["ttft_s"])
             / max(len(o.get("tokens", [])) - 1, 1)
             for _, _, o, lat in clean if o.get("ttft_s") is not None]
    migs = [o["migration"] for _, _, o, _ in ok
            if isinstance(o.get("migration"), dict)]
    mig_secs = router.migration_seconds - mig_secs0
    mig_bytes = router.migration_bytes - mig_bytes0
    record_mig = None
    if args.disaggregate:
        record_mig = {
            "count": router.migrations - migrations0,
            "bytes": mig_bytes,
            "seconds": mig_secs,
            # Mean PER-PULL wire rate: total bytes over the SUM of the
            # individual pull windows (export + install + ACK each) —
            # what one migration sustains on the wire. Concurrent pulls
            # overlap, so this deliberately is NOT aggregate fleet
            # throughput; divide `bytes` by the record's `wall_s` for
            # a (load-diluted) aggregate bound.
            "gb_per_s": (mig_bytes / mig_secs / 1e9) if mig_secs else 0.0,
            "fallbacks": router.migrate_fallbacks - fallbacks0,
        }
    trace_block = None
    if args.run_dir:
        # Stitched fleet traces: with the thread backend every
        # replica's fragments land in this one capture, so the
        # decomposition covers the router hop, the migration transfer,
        # and both tiers' queue waits.
        from nezha_tpu.obs.report import trace_summary
        trace_block = trace_summary(args.run_dir)
    return {
        "mode": "closed",
        "replicas": total,
        "trace": trace_block,
        "disaggregate": bool(args.disaggregate),
        "roles": list(roles),
        "kill_rate": args.kill_rate,
        "decode_horizon": decode_horizon,
        "offered": args.concurrency,
        "requests": args.requests,
        "answered": len(results),
        # The zero-silently-lost pin: every issued request produced
        # exactly one answer — a 200 or a typed error object.
        "lost": args.requests - len(results),
        "finished_clean": len(clean),
        "clean_finish_fraction": (len(clean) / args.requests
                                  if args.requests else 0.0),
        "errors_typed": errors_typed,
        "kills": len(kills), "killed_rids": kills,
        "restarts": sup.restarts - restarts0,
        "failovers": router.failovers - failovers0,
        "retries": router.retries - retries0,
        "recovered_live": recovered_live,
        "wall_s": wall,
        "tokens": tokens,
        "tokens_per_sec": tokens / wall if wall else 0.0,
        "latency_s": _percentiles(
            [lat for _, _, _, lat in clean] or [0.0]),
        "ttft_s": _percentiles(
            [o["ttft_s"] for _, _, o, _ in clean
             if o.get("ttft_s") is not None] or [0.0]),
        "tpot_s": _percentiles(tpots or [0.0]),
        "migration": record_mig,
        # The queueing-delay split per tier (disaggregated runs only:
        # time to the parked prefill answer vs the decode replica's
        # TTFT for the migrated request).
        "prefill_wait_s": _percentiles(
            [m["prefill_wait_s"] for m in migs
             if m.get("prefill_wait_s") is not None] or [0.0]),
        "decode_wait_s": _percentiles(
            [m["decode_wait_s"] for m in migs
             if m.get("decode_wait_s") is not None] or [0.0]),
        "faults": {"rate": args.fault_rate,
                   "injected": plan.num_injected if plan else 0,
                   "errored": sum(1 for _, _, o, _ in ok
                                  if o.get("finish_reason") == "error")},
    }


def _run_fleet(args, decode_horizon: int) -> dict:
    """The fleet-wide KV reuse scenario (``--replicas N
    --churn-users U``): U users with distinct block-aligned prompt
    prefixes revisit a ROUTED fleet sequentially, against per-replica
    pools each deliberately too small to hold every user's prefix.

    With ``--affinity-routing on``, visit 0 lands by consistent-hash
    cold placement (users SPREAD across the fleet, so the aggregate
    device cache holds every prefix), trie digests propagate over the
    /healthz probes, and each revisit routes back to its owner's warm
    trie — the fleet serves from cache what no single pool could hold.
    The ``off`` control routes least-loaded: sequential traffic piles
    every user onto one replica, whose pool cycles, so revisits
    re-prefill cold. A peer-pull phase (affinity runs only) then
    saturates one owner's admission queue and routes a revisit — the
    router must place it on a sibling with a ``pull_from`` pointer to
    the full owner, and the blocks arrive over the ``/kv_export``
    int8 wire instead of being re-prefilled.

    The record splits TTFT by first visit / revisit / peer-pull hit
    and carries the affinity-win and pull ledgers; ``nezha-bench``'s
    fleet_kv suite gates it."""
    import http.client
    import threading

    from nezha_tpu import obs
    from nezha_tpu.cli.serve import build_parser as serve_parser
    from nezha_tpu.serve import fleetcache
    from nezha_tpu.serve.router import Router, register_router_instruments
    from nezha_tpu.serve.scheduler import register_serve_instruments
    from nezha_tpu.serve.supervisor import (RouterConfig, Supervisor,
                                            ThreadBackend)

    users = int(args.churn_users)
    churn_plen = args.churn_prefix_len or 4 * args.kv_block_size
    if churn_plen % args.kv_block_size:
        raise SystemExit(
            f"--churn-prefix-len {churn_plen} must be a multiple of "
            f"--kv-block-size {args.kv_block_size} (only full blocks "
            f"are cacheable/advertisable)")
    if churn_plen + 2 + args.max_new_tokens > args.max_len:
        raise SystemExit(
            f"--churn-prefix-len {churn_plen} + tail 2 + "
            f"max_new_tokens {args.max_new_tokens} exceeds "
            f"--max-len {args.max_len}")
    if args.kv_layout != "paged" or args.prefix_cache != "on":
        raise SystemExit("the fleet scenario needs --kv-layout paged "
                         "with --prefix-cache on (digests summarize "
                         "the prefix trie)")
    visits = max(2, -(-args.requests // users))
    affinity = args.affinity_routing == "on"
    blocks_per_user = churn_plen // args.kv_block_size

    wargv = ["--random-init", "--model-preset", args.model_preset,
             "--max-batch-size", str(args.max_batch_size),
             "--max-len", str(args.max_len),
             "--max-prefill-len", str(args.max_prefill_len),
             "--queue-capacity", str(args.queue_capacity),
             "--decode-horizon", str(decode_horizon),
             "--max-new-tokens", str(args.max_new_tokens),
             "--kv-layout", args.kv_layout,
             "--kv-block-size", str(args.kv_block_size),
             "--kv-dtype", args.kv_dtype,
             "--kv-host-blocks", str(getattr(args, "kv_host_blocks", 0)),
             "--prefix-cache", args.prefix_cache,
             "--digest-interval", str(args.digest_interval),
             "--digest-max-entries", str(args.digest_max_entries),
             "--seed", str(args.seed)]
    if args.kv_num_blocks:
        wargv += ["--kv-num-blocks", str(args.kv_num_blocks)]
    if args.platform:
        wargv += ["--platform", args.platform]
    wargs = serve_parser().parse_args(wargv)
    cfg = RouterConfig(
        replicas=args.replicas,
        probe_interval_s=0.1, probe_misses=3,
        restart_backoff_base_s=0.05, restart_backoff_max_s=0.5,
        drain_timeout_s=5.0, seed=args.seed,
        affinity_routing=affinity,
        digest_interval_s=args.digest_interval,
        digest_max_entries=args.digest_max_entries)
    sup = Supervisor(ThreadBackend(wargs, drain_timeout_s=5.0), cfg)
    router = Router(sup, cfg)

    rng = random.Random(args.seed)
    vocab = 512 if args.model_preset == "tiny" else 50257
    prefixes = [[rng.randrange(vocab) for _ in range(churn_plen)]
                for _ in range(users)]
    hashes = [fleetcache.prefix_hashes(p, args.kv_block_size)
              for p in prefixes]

    def payload(u: int, v, seed: int) -> dict:
        # Fixed per-user prefix + a fresh 2-token tail per visit: the
        # prefix is the reusable span, the tail forces a real (if
        # tiny) prefill on every visit so TTFT is never zero-work.
        return {"id": f"fleet-u{u}-v{v}",
                "prompt_tokens": prefixes[u] + [rng.randrange(vocab),
                                                rng.randrange(vocab)],
                "max_new_tokens": args.max_new_tokens, "seed": seed}

    def _post(port, obj, timeout=600):
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=timeout)
        try:
            conn.request("POST", "/generate",
                         body=json.dumps(obj).encode())
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read() or b"{}")
        finally:
            conn.close()

    def _owner_of(hs):
        for r in sup.replicas():
            parsed = fleetcache.digest_entries_of(r.last_health)
            if parsed and fleetcache.coverage(parsed[1], hs)[0] \
                    >= blocks_per_user:
                return r
        return None

    sink = None
    ttft_first, ttft_revisit = [], []
    peer = None
    try:
        sup.start()
        router.start()
        if not router.wait_live(args.replicas, timeout_s=600):
            raise SystemExit(f"replicas never became live: "
                             f"{sup.describe()}")
        # Warm every replica's programs off the clock: the full churn
        # prompt covers every chunk program a cold prefill runs; a
        # 2-token prompt covers the tail-only program a digest-hit
        # revisit (or a pulled prefill) runs.
        warm = [threading.Thread(target=_post, args=(
                    r.port,
                    {"id": f"warmup-{r.rid}-{j}",
                     "prompt_tokens": [(131 * j + 7 * i + 1) % vocab
                                       for i in range(n)],
                     "max_new_tokens": 1}))
                for r in sup.live_replicas()
                for j, n in enumerate(sorted({churn_plen + 2, 2}))]
        for t in warm:
            t.start()
        for t in warm:
            t.join()
        # Warmup must not leak into the measured record: every pool
        # drops its cached blocks and zeroes the reuse ledgers.
        for r in sup.replicas():
            sched = r.handle.worker._sched
            with sched._lock:
                pool = sched.engine.pool
                pool.clear_prefix_cache()
                pool.prefix_hits = 0
                pool.cow_copies = 0
                pool.fleet_hits = {"device": 0, "host": 0, "peer": 0}
                if pool.host_blocks:
                    pool.warm_host_tier_programs()
                    pool.clear_host_tier()
                    pool.demotions = 0
                    pool.promotions = 0
                    pool.promote_failures = 0
        if args.run_dir:
            sink = obs.start_run(args.run_dir, meta={
                "kind": "serve_fleet_bench", "mode": "closed",
                "replicas": args.replicas,
                "requests": users * visits, "offered": 1,
                "decode_horizon": decode_horizon,
                "affinity": args.affinity_routing})
            register_router_instruments()
            register_serve_instruments()
        wins0 = router.affinity_wins
        pulls0, pbytes0 = router.kv_pulls, router.kv_pull_bytes

        # Phase 1 — first visits, sequential: cold placement spreads
        # users across the fleet (affinity) or piles them onto the
        # least-loaded member (control).
        for u in range(users):
            code, obj = router.route(payload(u, 0, u))
            if code == 200 and obj.get("ttft_s") is not None:
                ttft_first.append(obj["ttft_s"])

        # Phase 2 — let the digests propagate. The affinity run waits
        # until the ROUTER's own probe snapshots advertise every
        # user's full prefix (that snapshot is exactly what revisits
        # route on); the control — whose single serving pool cycles,
        # so full fleet coverage never materializes — waits a fixed
        # digest+probe interval instead, equalizing cache age across
        # the two runs.
        if affinity:
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if all(_owner_of(hs) is not None for hs in hashes):
                    break
                time.sleep(0.05)
        else:
            time.sleep(2 * args.digest_interval + 0.5)

        # Phase 3 — revisits, sequential rounds.
        for v in range(1, visits):
            for u in range(users):
                code, obj = router.route(payload(u, v, v * users + u))
                if code == 200 and obj.get("ttft_s") is not None:
                    ttft_revisit.append(obj["ttft_s"])

        # Phase 4 — the peer-pull drill (affinity runs only): clamp
        # user 0's owner to a zero-capacity admission queue (the
        # deterministic stand-in for a saturated replica — the
        # ThreadBackend's workers are in-process, so the clamp is one
        # attribute write) and route a revisit.  The router forwards
        # to the owner first (best score), eats its queue-full 503,
        # re-picks the sibling, and — the whole point — hands it a
        # ``pull_from`` pointer at the still-exporting owner, so the
        # prefix arrives over the int8 wire instead of a cold
        # prefill.  ``/kv_export`` needs no admission, which is why a
        # full owner's cache keeps paying off.
        if affinity:
            owner = _owner_of(hashes[0])
            peer = {"owner_rid": owner.rid if owner else None,
                    "saturated": False, "attempts": 0,
                    "ttft_s": None, "pull_s": None, "installed": 0,
                    "bytes": 0, "degraded": None}
            if owner is not None:
                owner_sched = owner.handle.worker._sched
                cap = owner_sched.queue_capacity
                try:
                    owner_sched.queue_capacity = 0
                    code, _ = _post(owner.port,
                                    {"id": "probe-full",
                                     "prompt_tokens": [1, 2, 3],
                                     "max_new_tokens": 1})
                    peer["saturated"] = code == 503
                    attempts = 0
                    while peer["saturated"] and attempts < 5:
                        attempts += 1
                        code, obj = router.route(
                            payload(0, f"pull{attempts}",
                                    9000 + attempts))
                        fp = (obj.get("fleet_pull")
                              if code == 200 and isinstance(obj, dict)
                              else None)
                        if isinstance(fp, dict):
                            peer["degraded"] = fp.get("degraded")
                            if fp.get("installed"):
                                peer["ttft_s"] = obj.get("ttft_s")
                                peer["pull_s"] = fp.get("seconds")
                                peer["installed"] = fp.get(
                                    "installed", 0)
                                peer["bytes"] = fp.get("bytes", 0)
                                break
                    peer["attempts"] = attempts
                finally:
                    owner_sched.queue_capacity = cap

        wins = router.affinity_wins - wins0
        pulls = router.kv_pulls - pulls0
        pull_bytes = router.kv_pull_bytes - pbytes0
        fleet_hits = {"device": 0, "host": 0, "peer": 0}
        prefix_hits = 0
        for r in sup.replicas():
            w = getattr(r.handle, "worker", None)
            if w is None or w.dead.is_set():
                continue
            pool = w._sched.engine.pool
            for k in fleet_hits:
                fleet_hits[k] += pool.fleet_hits.get(k, 0)
            prefix_hits += getattr(pool, "prefix_hits", 0)
    finally:
        if sink is not None:
            obs.end_run()
        router.stop()
        sup.shutdown()

    p_first = _percentiles(ttft_first or [0.0])
    p_revisit = _percentiles(ttft_revisit or [0.0])
    return {
        "mode": "closed",
        "replicas": args.replicas,
        "decode_horizon": decode_horizon,
        "offered": 1,
        "requests": users * visits,
        "fleet": {
            "users": users, "visits": visits,
            "prefix_len": churn_plen,
            "affinity_routing": args.affinity_routing,
            "digest_interval_s": args.digest_interval,
            "digest_max_entries": args.digest_max_entries,
            "affinity_wins": wins,
            "kv_pulls": pulls,
            "kv_pull_bytes": pull_bytes,
            "fleet_hits": fleet_hits,
            "prefix_hits": prefix_hits,
            "ttft_first_visit_s": p_first,
            "ttft_revisit_s": p_revisit,
            "revisit_vs_first_ttft_p50": (
                p_revisit["p50"] / max(p_first["p50"], 1e-9)),
            "peer_pull": peer,
        },
    }


def main(argv=None) -> int:
    run(build_parser().parse_args(argv))
    return 0


if __name__ == "__main__":
    sys.exit(main())
