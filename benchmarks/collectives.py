"""Collective bus-bandwidth microbenchmark.

One of BASELINE.json's metrics of record is "all-reduce bus bw" — the
reference measured its NCCL ring (SURVEY.md §0). Here the collectives are
XLA's over ICI; this harness times them through the same `shard_map`
path the framework trains with and reports *bus* bandwidth with the
standard ring-algorithm convention, so numbers are comparable with
NCCL-style reports:

    all-reduce      busBW = bytes * 2*(n-1)/n / time   (per device)
    all-gather      busBW = bytes *   (n-1)/n / time
    reduce-scatter  busBW = bytes *   (n-1)/n / time
    ppermute        busBW = bytes             / time

Run on a real multi-chip mesh for ICI numbers, or a virtual CPU mesh
(`--xla_force_host_platform_device_count=N`) for plumbing validation.

Usage::

    python benchmarks/collectives.py [--sizes-mb 1 4 16 64] [--iters 20]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Runnable as `python benchmarks/collectives.py` from anywhere: the repo
# root (one level up) must be importable for nezha_tpu.
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


# jax imports live inside functions: forcing a virtual CPU mesh
# (--cpu-devices) must set platform/flags before the backend initializes,
# and an ambient sitecustomize may import jax at interpreter startup —
# jax.config.update after import is the reliable override (see
# tests/conftest.py).


def _force_cpu(n: int) -> None:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               f" --xla_force_host_platform_device_count={n}")
    import jax
    jax.config.update("jax_platforms", "cpu")


def _mesh():
    import jax
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), ("x",))


def _collectives(mesh):
    import jax
    from jax.sharding import PartitionSpec as P

    from nezha_tpu.parallel._compat import shard_map
    from nezha_tpu.parallel.quantized import _qar_mean

    n = mesh.devices.size
    spec = P("x")

    def wrap(f, in_spec=spec, out_spec=spec):
        return jax.jit(shard_map(f, mesh=mesh, in_specs=(in_spec,),
                                 out_specs=out_spec))

    return {
        # x: [n*k] sharded -> per-device psum of its [k] shard.
        "all_reduce": (wrap(lambda x: jax.lax.psum(x, "x")),
                       lambda b: b * 2 * (n - 1) / n),
        "all_gather": (wrap(lambda x: jax.lax.all_gather(x, "x",
                                                         tiled=True),
                            spec, P()),
                       lambda b: b * (n - 1) / n),
        "reduce_scatter": (wrap(lambda x: jax.lax.psum_scatter(
            x, "x", tiled=True)),
                           lambda b: b * (n - 1) / n),
        "ppermute": (wrap(lambda x: jax.lax.ppermute(
            x, "x", [(i, (i + 1) % n) for i in range(n)])),
                     lambda b: b),
        # int8-wire all-reduce (parallel/quantized.py). busBW is reported
        # for the fp32-equivalent payload — "effective" bandwidth, i.e. how
        # fast exact fp32 all-reduce would have to run to move the same
        # gradient; the wire itself carries ~4x less.
        "all_reduce_int8": (wrap(lambda x: _qar_mean(x, "x", 512)),
                            lambda b: b * 2 * (n - 1) / n),
    }


def run(sizes_mb, iters: int = 20) -> list:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nezha_tpu import obs

    mesh = _mesh()
    n = mesh.devices.size
    results = []
    for name, (fn, bus_bytes) in _collectives(mesh).items():
        for mb in sizes_mb:
            per_dev = int(mb * (1 << 20)) // 4  # f32 elements per device
            x = jax.device_put(
                jnp.arange(per_dev * n, dtype=jnp.float32),
                NamedSharding(mesh, P("x")))
            out = fn(x)  # compile + warm
            np.asarray(jax.tree_util.tree_leaves(out)[0][:1])  # sync
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(x)
            np.asarray(jax.tree_util.tree_leaves(out)[0][:1])  # sync
            dt = (time.perf_counter() - t0) / iters
            payload = per_dev * 4
            bus = bus_bytes(payload) / dt
            # Telemetry (with --run-dir): the MEASURED per-collective
            # bandwidth — the benchmark is the authoritative source for
            # the report's bus GB/s column (train-step call sites only
            # count payload bytes).
            obs.record_collective(name, payload, seconds=dt,
                                  bus_bytes=bus_bytes(payload))
            results.append({
                "collective": name, "devices": n, "size_mb_per_dev": mb,
                "time_ms": round(dt * 1e3, 3),
                "bus_gbps": round(bus / 1e9, 3),
            })
    return results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--sizes-mb", type=float, nargs="+",
                    default=[1, 4, 16, 64])
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--cpu-devices", type=int, default=0,
                    help="force an N-device virtual CPU mesh")
    ap.add_argument("--run-dir", default=None,
                    help="also record results as a telemetry run "
                         "(metrics.jsonl + summary.json with the "
                         "per-collective bandwidth table; read with "
                         "nezha-telemetry RUN_DIR)")
    args = ap.parse_args(argv)
    if args.cpu_devices:
        _force_cpu(args.cpu_devices)
    # After _force_cpu: importing nezha_tpu pulls in jax, which must not
    # happen before the virtual-device flags are set.
    from nezha_tpu import obs
    if args.run_dir:
        obs.start_run(args.run_dir, meta={"tool": "benchmarks/collectives",
                                          "iters": args.iters})
    try:
        for i, rec in enumerate(run(args.sizes_mb, args.iters)):
            obs.record_metrics(i, rec)  # no-op without --run-dir
            print(json.dumps(rec))
    finally:
        if args.run_dir:
            obs.end_run()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
