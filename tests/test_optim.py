"""Optimizer tests: update math vs closed-form / reference behavior."""

import jax
import jax.numpy as jnp
import numpy as np

from nezha_tpu import optim


def _quad_grads(params):
    # d/dp of 0.5*p^2 is p
    return jax.tree_util.tree_map(lambda p: p, params)


def test_sgd_step():
    opt = optim.sgd(0.1)
    params = {"w": jnp.array([1.0, -2.0])}
    state = opt.init(params)
    updates, state = opt.update(_quad_grads(params), state, params)
    new = optim.apply_updates(params, updates)
    np.testing.assert_allclose(np.asarray(new["w"]), [0.9, -1.8], rtol=1e-6)
    assert int(state["step"]) == 1


def test_momentum_accumulates_velocity():
    opt = optim.momentum(0.1, beta=0.9)
    params = {"w": jnp.array([1.0])}
    state = opt.init(params)
    u1, state = opt.update({"w": jnp.array([1.0])}, state, params)
    u2, state = opt.update({"w": jnp.array([1.0])}, state, params)
    # v1 = 1, v2 = 0.9*1 + 1 = 1.9
    np.testing.assert_allclose(np.asarray(u1["w"]), [-0.1], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(u2["w"]), [-0.19], rtol=1e-6)


def test_adamw_first_step_is_lr_sized():
    opt = optim.adamw(1e-3, weight_decay=0.0)
    params = {"w": jnp.array([10.0])}
    state = opt.init(params)
    updates, _ = opt.update({"w": jnp.array([0.5])}, state, params)
    # After bias correction the first step is ~ -lr * sign(grad).
    np.testing.assert_allclose(np.asarray(updates["w"]), [-1e-3], rtol=1e-3)


def test_adamw_weight_decay_mask():
    mask = lambda p: {"w": True, "b": False}
    opt = optim.adamw(1.0, weight_decay=0.1, mask=mask)
    params = {"w": jnp.array([1.0]), "b": jnp.array([1.0])}
    state = opt.init(params)
    zero_grads = {"w": jnp.array([0.0]), "b": jnp.array([0.0])}
    updates, _ = opt.update(zero_grads, state, params)
    assert float(updates["w"][0]) != 0.0  # decayed
    np.testing.assert_allclose(np.asarray(updates["b"]), [0.0], atol=1e-9)


def test_optimizers_minimize_quadratic():
    for make in (lambda: optim.sgd(0.2), lambda: optim.momentum(0.05),
                 lambda: optim.adamw(0.2, weight_decay=0.0)):
        opt = make()
        params = {"w": jnp.array([3.0, -4.0])}
        state = opt.init(params)
        for _ in range(100):
            updates, state = opt.update(_quad_grads(params), state, params)
            params = optim.apply_updates(params, updates)
        assert float(jnp.max(jnp.abs(params["w"]))) < 0.1, make


def test_clip_by_global_norm():
    tree = {"a": jnp.array([3.0]), "b": jnp.array([4.0])}
    clipped, norm = optim.clip_by_global_norm(tree, 1.0)
    np.testing.assert_allclose(float(norm), 5.0, rtol=1e-5)
    np.testing.assert_allclose(float(optim.global_norm(clipped)), 1.0, rtol=1e-4)


def test_schedules():
    s = optim.warmup_cosine_schedule(1.0, warmup_steps=10, total_steps=110)
    assert float(s(jnp.array(0))) < 0.2
    np.testing.assert_allclose(float(s(jnp.array(9))), 1.0, rtol=1e-6)
    assert float(s(jnp.array(110))) < 1e-6
    c = optim.cosine_decay_schedule(2.0, 100)
    np.testing.assert_allclose(float(c(jnp.array(0))), 2.0, rtol=1e-6)
