"""Optimizer tests: update math vs closed-form / reference behavior."""

import jax
import jax.numpy as jnp
import numpy as np

from nezha_tpu import optim


def _quad_grads(params):
    # d/dp of 0.5*p^2 is p
    return jax.tree_util.tree_map(lambda p: p, params)


def test_sgd_step():
    opt = optim.sgd(0.1)
    params = {"w": jnp.array([1.0, -2.0])}
    state = opt.init(params)
    updates, state = opt.update(_quad_grads(params), state, params)
    new = optim.apply_updates(params, updates)
    np.testing.assert_allclose(np.asarray(new["w"]), [0.9, -1.8], rtol=1e-6)
    assert int(state["step"]) == 1


def test_momentum_accumulates_velocity():
    opt = optim.momentum(0.1, beta=0.9)
    params = {"w": jnp.array([1.0])}
    state = opt.init(params)
    u1, state = opt.update({"w": jnp.array([1.0])}, state, params)
    u2, state = opt.update({"w": jnp.array([1.0])}, state, params)
    # v1 = 1, v2 = 0.9*1 + 1 = 1.9
    np.testing.assert_allclose(np.asarray(u1["w"]), [-0.1], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(u2["w"]), [-0.19], rtol=1e-6)


def test_adamw_first_step_is_lr_sized():
    opt = optim.adamw(1e-3, weight_decay=0.0)
    params = {"w": jnp.array([10.0])}
    state = opt.init(params)
    updates, _ = opt.update({"w": jnp.array([0.5])}, state, params)
    # After bias correction the first step is ~ -lr * sign(grad).
    np.testing.assert_allclose(np.asarray(updates["w"]), [-1e-3], rtol=1e-3)


def test_adamw_weight_decay_mask():
    mask = lambda p: {"w": True, "b": False}
    opt = optim.adamw(1.0, weight_decay=0.1, mask=mask)
    params = {"w": jnp.array([1.0]), "b": jnp.array([1.0])}
    state = opt.init(params)
    zero_grads = {"w": jnp.array([0.0]), "b": jnp.array([0.0])}
    updates, _ = opt.update(zero_grads, state, params)
    assert float(updates["w"][0]) != 0.0  # decayed
    np.testing.assert_allclose(np.asarray(updates["b"]), [0.0], atol=1e-9)


def test_optimizers_minimize_quadratic():
    for make in (lambda: optim.sgd(0.2), lambda: optim.momentum(0.05),
                 lambda: optim.adamw(0.2, weight_decay=0.0)):
        opt = make()
        params = {"w": jnp.array([3.0, -4.0])}
        state = opt.init(params)
        for _ in range(100):
            updates, state = opt.update(_quad_grads(params), state, params)
            params = optim.apply_updates(params, updates)
        assert float(jnp.max(jnp.abs(params["w"]))) < 0.1, make


def test_clip_by_global_norm():
    tree = {"a": jnp.array([3.0]), "b": jnp.array([4.0])}
    clipped, norm = optim.clip_by_global_norm(tree, 1.0)
    np.testing.assert_allclose(float(norm), 5.0, rtol=1e-5)
    np.testing.assert_allclose(float(optim.global_norm(clipped)), 1.0, rtol=1e-4)


def test_schedules():
    s = optim.warmup_cosine_schedule(1.0, warmup_steps=10, total_steps=110)
    assert float(s(jnp.array(0))) < 0.2
    np.testing.assert_allclose(float(s(jnp.array(9))), 1.0, rtol=1e-6)
    assert float(s(jnp.array(110))) < 1e-6
    c = optim.cosine_decay_schedule(2.0, 100)
    np.testing.assert_allclose(float(c(jnp.array(0))), 2.0, rtol=1e-6)


class TestLargeBatchOptimizers:
    def _quadratic_converges(self, opt, steps=200, tol=0.15):
        """Minimize |Wx - y|^2; the optimizer must make steady progress."""
        import jax

        W = jnp.asarray(np.random.RandomState(0).randn(8, 8), jnp.float32)
        x = jnp.asarray(np.random.RandomState(1).randn(8), jnp.float32)
        y = W @ x
        params = {"w": jnp.zeros((8, 8), jnp.float32),
                  "b": jnp.zeros((8,), jnp.float32)}

        def loss(p):
            return jnp.mean((p["w"] @ x + p["b"] - y) ** 2)

        state = opt.init(params)

        @jax.jit
        def step(params, state):
            g = jax.grad(loss)(params)
            updates, state = opt.update(g, state, params)
            return optim.apply_updates(params, updates), state

        l0 = float(loss(params))
        for _ in range(steps):
            params, state = step(params, state)
        assert float(loss(params)) < tol * l0, float(loss(params))

    def test_lars_converges(self):
        # Small trust coefficient means small effective steps; give the
        # tiny quadratic a matching LR and enough steps.
        self._quadratic_converges(optim.lars(2.0, trust_coefficient=0.1),
                                  steps=400)

    def test_lamb_converges(self):
        self._quadratic_converges(optim.lamb(0.1))

    def test_adafactor_converges(self):
        self._quadratic_converges(optim.adafactor(0.1), steps=500)

    def test_adafactor_memory_is_factored(self):
        params = {"w": jnp.zeros((64, 32), jnp.float32)}
        state = optim.adafactor(1e-2).init(params)
        slot = state["slots"]["w"]
        assert slot["vr"].shape == (64,) and slot["vc"].shape == (32,)

    def test_grad_clipping_wrapper_bounds_update(self):
        opt = optim.with_grad_clipping(optim.sgd(1.0), max_norm=1.0)
        params = {"w": jnp.zeros(4, jnp.float32)}
        state = opt.init(params)
        huge = {"w": jnp.full(4, 1e6, jnp.float32)}
        updates, _ = opt.update(huge, state, params)
        assert float(optim.global_norm(updates)) <= 1.0 + 1e-4

    def test_accumulation_matches_big_batch(self):
        """k micro-steps with accumulation == one step on the mean grad."""
        import jax

        base = optim.adamw(1e-2)
        acc = optim.accumulate_gradients(optim.adamw(1e-2), every=4)
        params = {"w": jnp.ones(6, jnp.float32)}
        micro = [{"w": jnp.asarray(np.random.RandomState(i).randn(6),
                                   jnp.float32)} for i in range(4)]
        mean = {"w": sum(m["w"] for m in micro) / 4}

        s_base = base.init(params)
        u_ref, _ = base.update(mean, s_base, params)

        s_acc = acc.init(params)
        p = params
        for m in micro:
            u, s_acc = acc.update(m, s_acc, p)
            p = optim.apply_updates(p, u)
        # First 3 updates are zero; the 4th equals the big-batch update.
        np.testing.assert_allclose(np.asarray(p["w"]),
                                   np.asarray(optim.apply_updates(
                                       params, u_ref)["w"]), rtol=1e-6)
        # Counter reset: a second cycle flushes again at step 8.
        for m in micro:
            u, s_acc = acc.update(m, s_acc, p)
        assert int(s_acc["count"]) == 0

    def test_adafactor_handles_qkv_named_params(self):
        """Param dicts with a 'v' key must not be mistaken for slots."""
        import jax

        params = {"attn": {"q": jnp.ones((4, 4)), "k": jnp.ones((4, 4)),
                           "v": jnp.ones((4, 4))}}
        opt = optim.adafactor(1e-2)
        state = opt.init(params)
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        updates, state = opt.update(grads, state, params)
        assert updates["attn"]["v"].shape == (4, 4)

    def test_lars_skip_fn_excludes_weight_decay(self):
        """Skip-listed leaves get neither trust scaling nor weight decay."""
        opt = optim.lars(1.0, beta=0.0, weight_decay=0.5,
                         skip_fn=lambda p: {"w": False, "b": True})
        params = {"w": jnp.ones((2, 2)), "b": jnp.ones((2,))}
        state = opt.init(params)
        zero_g = {"w": jnp.zeros((2, 2)), "b": jnp.zeros((2,))}
        updates, _ = opt.update(zero_g, state, params)
        # Bias: no wd -> zero update. Weight: wd decays it.
        np.testing.assert_allclose(np.asarray(updates["b"]), 0.0)
        assert float(jnp.abs(updates["w"]).sum()) > 0


def test_matrix_decay_mask_scan_layout():
    """The decay mask is layout-aware: a stacked trunk's [L, H] norm
    scale is still excluded (threshold ndim>=3 under h_scan/layers_scan),
    while a real stacked kernel [L, H, 4H] is decayed."""
    import jax
    params = {
        "wte": {"embedding": np.zeros((8, 4))},
        "ln_f": {"scale": np.zeros((4,))},
        "h_scan": {"mlp": {"fc": {"w": np.zeros((2, 4, 16)),
                                  "b": np.zeros((2, 16))}},
                   "ln_1": {"scale": np.zeros((2, 4))}},
    }
    m = optim.matrix_decay_mask(params)
    assert m["wte"]["embedding"] is True or m["wte"]["embedding"] == True
    assert not m["ln_f"]["scale"]
    assert m["h_scan"]["mlp"]["fc"]["w"]
    assert not m["h_scan"]["mlp"]["fc"]["b"]
    assert not m["h_scan"]["ln_1"]["scale"]
