"""CLI tests: the `nezha-train` entry point runs configs end-to-end
(SURVEY.md §1 `cmd/nezha-train`)."""

import json

import numpy as np

from nezha_tpu.cli.train import build_parser, run


def _run(argv):
    return run(build_parser().parse_args(argv))


def test_cli_mlp_mnist(tmp_path):
    metrics = _run(["--config", "mlp_mnist", "--steps", "30",
                    "--batch-size", "64", "--log-every", "10",
                    "--metrics-file", str(tmp_path / "m.jsonl")])
    assert np.isfinite(metrics["loss"])
    lines = (tmp_path / "m.jsonl").read_text().strip().splitlines()
    assert len(lines) == 3
    assert "examples_per_sec" in json.loads(lines[-1])


def test_cli_resume(tmp_path):
    ck = str(tmp_path / "ck")
    _run(["--config", "mlp_mnist", "--steps", "10", "--batch-size", "64",
          "--ckpt-dir", ck])
    m = _run(["--config", "mlp_mnist", "--steps", "5", "--batch-size", "64",
              "--ckpt-dir", ck, "--log-every", "5"])
    # Resumed from 10 -> logged step numbers continue past it.
    assert m["step"] == 15


def test_cli_dp_mesh(devices8, capsys):
    """ResNet (tiny preset) actually trains data-parallel over the 8-device
    mesh through the CLI — no degrade warning, finite loss."""
    metrics = _run(["--config", "resnet50_imagenet", "--model-preset", "tiny",
                    "--steps", "4", "--batch-size", "16", "--mesh", "dp=8",
                    "--log-every", "2"])
    assert np.isfinite(metrics["loss"])
    assert "only 1 device" not in capsys.readouterr().err  # DP really ran


def test_cli_dp_int8_allreduce(devices8, capsys):
    """--grad-allreduce int8 trains DP with the quantized wire collective;
    non-dp modes reject the flag instead of ignoring it."""
    import pytest
    metrics = _run(["--config", "resnet50_imagenet", "--model-preset", "tiny",
                    "--steps", "4", "--batch-size", "16", "--mesh", "dp=8",
                    "--grad-allreduce", "int8", "--log-every", "2"])
    assert np.isfinite(metrics["loss"])
    assert "only 1 device" not in capsys.readouterr().err
    # ZeRO-1 consumes it too (both wire phases quantized).
    metrics = _run(["--config", "bert_base_zero1", "--model-preset", "tiny",
                    "--steps", "2", "--batch-size", "16", "--mesh", "dp=8",
                    "--grad-allreduce", "int8", "--log-every", "2"])
    assert np.isfinite(metrics["loss"])
    with pytest.raises(SystemExit, match="grad-allreduce"):
        _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "1", "--batch-size", "8", "--parallel", "sp",
              "--mesh", "dp=4,sp=2", "--grad-allreduce", "int8"])


def test_cli_label_smoothing():
    """--label-smoothing trains the CE configs; non-CE configs reject."""
    import pytest
    metrics = _run(["--config", "mlp_mnist", "--steps", "4",
                    "--batch-size", "64", "--label-smoothing", "0.1",
                    "--log-every", "2"])
    assert np.isfinite(metrics["loss"])
    with pytest.raises(SystemExit, match="label-smoothing"):
        _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "1", "--batch-size", "4",
              "--label-smoothing", "0.1"])


def test_mesh_parsing():
    from nezha_tpu.cli.train import _parse_mesh
    assert _parse_mesh("dp=4,sp=2") == {"dp": 4, "sp": 2}
    assert _parse_mesh(None) is None


def test_cli_rejects_unusable_mesh_axes(devices8):
    """A mesh axis the chosen parallel mode cannot consume is an error, not
    silently ignored (VERDICT r2 missing #1)."""
    import pytest
    with pytest.raises(SystemExit, match="cannot use mesh axis"):
        _run(["--config", "resnet50_imagenet", "--model-preset", "tiny",
              "--steps", "1", "--batch-size", "8", "--parallel", "dp",
              "--mesh", "dp=4,tp=2"])
    with pytest.raises(SystemExit, match=r"needs mesh axis\(es\) \['tp'\]"):
        _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "1", "--batch-size", "8", "--parallel", "gspmd",
              "--mesh", "dp=8"])
    with pytest.raises(SystemExit, match=r"needs mesh axis\(es\) \['dp'\]"):
        _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "1", "--batch-size", "8", "--parallel", "sp",
              "--mesh", "sp=8"])
    with pytest.raises(SystemExit, match="no effect in single-device"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
              "--parallel", "single", "--mesh", "dp=8"])
    with pytest.raises(SystemExit, match="no tensor-parallel rule table"):
        _run(["--config", "resnet50_imagenet", "--model-preset", "tiny",
              "--steps", "1", "--batch-size", "8", "--parallel", "gspmd",
              "--mesh", "dp=2,tp=4"])


def _final_losses(config, steps, batch, extra):
    """Per-step losses from a metrics file for mode-vs-mode comparison."""
    import tempfile, pathlib, os
    with tempfile.TemporaryDirectory() as d:
        mf = os.path.join(d, "m.jsonl")
        _run(["--config", config, "--model-preset", "tiny",
              "--steps", str(steps), "--batch-size", str(batch),
              "--log-every", "1", "--metrics-file", mf] + extra)
        return [json.loads(l)["loss"]
                for l in pathlib.Path(mf).read_text().strip().splitlines()]


def test_cli_gspmd_matches_single(devices8):
    """--parallel gspmd (dp x tp, Megatron rules) launches from the CLI and
    matches single-device numerics step-for-step."""
    ref = _final_losses("gpt2_124m", 3, 8, ["--parallel", "single"])
    tp = _final_losses("gpt2_124m", 3, 8,
                       ["--parallel", "gspmd", "--mesh", "dp=2,tp=4"])
    np.testing.assert_allclose(tp, ref, rtol=1e-3)


def test_cli_pp_matches_single(devices8):
    """--parallel pp (dp x pp GPipe) launches from the CLI and matches
    single-device numerics step-for-step."""
    ref = _final_losses("gpt2_124m", 3, 8, ["--parallel", "single"])
    pp = _final_losses("gpt2_124m", 3, 8,
                       ["--parallel", "pp", "--mesh", "dp=2,pp=4",
                        "--microbatches", "2"])
    np.testing.assert_allclose(pp, ref, rtol=1e-3)


def test_cli_sp_matches_single(devices8):
    """--parallel sp (dp x sp ring attention) launches from the CLI and
    matches single-device numerics step-for-step."""
    ref = _final_losses("gpt2_124m", 3, 8, ["--parallel", "single"])
    sp = _final_losses("gpt2_124m", 3, 8,
                       ["--parallel", "sp", "--mesh", "dp=2,sp=4",
                        "--attn-impl", "ring"])
    np.testing.assert_allclose(sp, ref, rtol=1e-3)


def test_cli_moe_gpt2(devices8):
    """--moe-experts turns config 3 into a routed-MoE transformer and
    trains it data-parallel through the CLI."""
    m = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--moe-experts", "4", "--parallel", "dp", "--mesh", "dp=8",
              "--steps", "3", "--batch-size", "16", "--log-every", "1"])
    assert np.isfinite(m["loss"])


def test_cli_moe_ep_gspmd_matches_single(devices8):
    """--moe-experts with --parallel gspmd shards experts over an ep mesh
    axis (dp x tp x ep) from the CLI and matches single-device numerics."""
    ref = _final_losses("gpt2_124m", 3, 8,
                        ["--parallel", "single", "--moe-experts", "4"])
    ep = _final_losses("gpt2_124m", 3, 8,
                       ["--parallel", "gspmd", "--mesh", "dp=2,tp=2,ep=2",
                        "--moe-experts", "4"])
    np.testing.assert_allclose(ep, ref, rtol=1e-3)
    # An ep axis that does not divide the expert count is a friendly error,
    # not a raw device_put traceback (e.g. the dp=1,tp=1,ep=8 default mesh).
    import pytest
    with pytest.raises(SystemExit, match="not divisible by"):
        _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "1", "--batch-size", "8", "--moe-experts", "4",
              "--parallel", "gspmd", "--mesh", "dp=1,tp=1,ep=8"])


def test_cli_sp_flash_matches_single(devices8):
    """--sp-flash on forces the flash-ring path from the CLI (interpret
    mode on CPU) and still matches single-device numerics; the flag is
    rejected where no sp kernels run."""
    import pytest
    ref = _final_losses("gpt2_124m", 3, 8, ["--parallel", "single"])
    spf = _final_losses("gpt2_124m", 3, 8,
                        ["--parallel", "sp", "--mesh", "dp=2,sp=4",
                         "--attn-impl", "ring", "--sp-flash", "on"])
    np.testing.assert_allclose(spf, ref, rtol=1e-3)
    with pytest.raises(SystemExit, match="does not consume it"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
              "--sp-flash", "off"])
    with pytest.raises(SystemExit, match="needs --parallel sp"):
        _run(["--config", "mlp_mnist", "--engine", "graph", "--steps", "1",
              "--batch-size", "8", "--sp-flash", "off"])


def test_cli_sp_one_chip_smoke(devices8):
    """The 1-chip sp smoke BENCH_NOTES prescribes: an EXPLICIT all-ones
    mesh (--mesh dp=1,sp=1) must RUN the sp mode on a single visible
    device (no degrade — it is the kernel/wiring smoke), with --sp-flash
    working in both positions."""
    import sys

    from conftest import run_worker_processes
    base = [sys.executable, "-m", "nezha_tpu.cli.train",
            "--config", "gpt2_124m", "--model-preset", "tiny",
            "--parallel", "sp", "--mesh", "dp=1,sp=1",
            "--platform", "cpu", "--steps", "2", "--batch-size", "4",
            "--log-every", "1"]
    results = run_worker_processes([base + ["--sp-flash", "on"],
                                    base + ["--sp-flash", "off"]])
    for rc, out, err in results:
        assert rc == 0, err[-3000:]
        assert "only 1 device" not in err  # ran sp, not the degrade
        assert json.loads(out.strip().splitlines()[-1])["final"]["loss"] > 0


def test_cli_sp_ulysses(devices8):
    """--attn-impl ulysses: the all-to-all sequence-parallel path from the
    CLI (heads 4 divisible by sp=4)."""
    m = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--parallel", "sp", "--mesh", "dp=2,sp=4",
              "--attn-impl", "ulysses", "--steps", "2", "--batch-size", "8",
              "--log-every", "1"])
    assert np.isfinite(m["loss"])


def test_cli_sp_long_context(devices8):
    """--seq-len stretches model + data together; with --parallel sp the
    sequence shards over sp, the long-context path of the brief — composed
    here with --remat (jax.checkpoint per block), the other long-context
    memory knob."""
    m = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--parallel", "sp", "--mesh", "dp=1,sp=8", "--seq-len", "256",
              "--attn-impl", "ring", "--remat", "--steps", "2",
              "--batch-size", "4", "--log-every", "1"])
    assert np.isfinite(m["loss"])


def test_cli_remat_matches_and_rejects(devices8):
    """--remat must not change training numerics, and configs/engines that
    cannot honor it reject instead of silently ignoring."""
    import pytest
    ref = _final_losses("gpt2_124m", 2, 8, ["--parallel", "single"])
    rm = _final_losses("gpt2_124m", 2, 8, ["--parallel", "single",
                                           "--remat"])
    np.testing.assert_allclose(rm, ref, rtol=1e-5)
    with pytest.raises(SystemExit, match="applies to gpt2_124m"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
              "--remat"])
    # pp honors --remat too (per-tick stage checkpointing): numerics match
    # the plain pp run exactly.
    pp_ref = _final_losses("gpt2_124m", 2, 8,
                           ["--parallel", "pp", "--mesh", "dp=2,pp=4",
                            "--microbatches", "2"])
    pp_rm = _final_losses("gpt2_124m", 2, 8,
                          ["--parallel", "pp", "--mesh", "dp=2,pp=4",
                           "--microbatches", "2", "--remat"])
    np.testing.assert_allclose(pp_rm, pp_ref, rtol=1e-5)


def test_cli_gspmd_sharded_checkpoint_resume(devices8, tmp_path):
    """GSPMD CLI checkpoints in the per-shard format and resumes from it."""
    ck = str(tmp_path / "ck")
    base = ["--config", "gpt2_124m", "--model-preset", "tiny",
            "--batch-size", "8", "--parallel", "gspmd",
            "--mesh", "dp=2,tp=4", "--ckpt-dir", ck, "--log-every", "1"]
    _run(base + ["--steps", "2"])
    import pathlib
    assert list(pathlib.Path(ck).glob("step_*.sharded"))
    m = _run(base + ["--steps", "1", "--eval", "--eval-batches", "2"])
    assert m["step"] == 3  # resumed at 2, trained 1 more
    assert any(k.startswith("eval_") for k in m)  # eval over sharded params


def test_cli_moe_ep_sharded_checkpoint_resume(devices8, tmp_path):
    """The ep-sharded expert layout round-trips the per-shard checkpoint
    format (reshard-on-restore must rebuild [E,.,.] leaves split over ep)."""
    ck = str(tmp_path / "ck")
    base = ["--config", "gpt2_124m", "--model-preset", "tiny",
            "--batch-size", "8", "--moe-experts", "4", "--parallel", "gspmd",
            "--mesh", "dp=2,tp=2,ep=2", "--ckpt-dir", ck, "--log-every", "1"]
    _run(base + ["--steps", "2"])
    import pathlib
    assert list(pathlib.Path(ck).glob("step_*.sharded"))
    m = _run(base + ["--steps", "1"])
    assert m["step"] == 3  # resumed at 2, trained 1 more
    assert np.isfinite(m["loss"])


def test_cli_pp_sharded_checkpoint_resume_and_eval(devices8, tmp_path):
    """Pipeline CLI checkpoints stacked stage slabs and resumes; eval runs
    off the merged (native-layout) params."""
    ck = str(tmp_path / "ck")
    base = ["--config", "gpt2_124m", "--model-preset", "tiny",
            "--batch-size", "8", "--parallel", "pp", "--mesh", "dp=2,pp=4",
            "--microbatches", "2", "--ckpt-dir", ck, "--log-every", "1"]
    _run(base + ["--steps", "2"])
    import pathlib
    assert list(pathlib.Path(ck).glob("step_*.sharded"))
    m = _run(base + ["--steps", "1", "--eval", "--eval-batches", "2"])
    assert m["step"] == 3
    assert any(k.startswith("eval_") for k in m)


def test_cli_graph_engine_trains_and_evals(tmp_path):
    """Config 1 through the Graph IR -> StableHLO -> Executor path; metrics
    improve and eval runs off the same params."""
    metrics = _run(["--config", "mlp_mnist", "--engine", "graph",
                    "--steps", "40", "--batch-size", "64",
                    "--log-every", "10", "--eval", "--eval-batches", "4",
                    "--metrics-file", str(tmp_path / "m.jsonl")])
    lines = [json.loads(l) for l in
             (tmp_path / "m.jsonl").read_text().strip().splitlines()]
    assert lines[-1]["loss"] < lines[0]["loss"]
    assert any(k.startswith("eval_") for k in metrics)


def test_cli_graph_engine_dp(devices8, tmp_path, capsys):
    """--engine graph --parallel dp: the IR's all_reduce path runs from the
    CLI over the 8-device mesh (no degrade warning, loss drops); invalid
    combos reject loudly."""
    import pytest
    metrics = _run(["--config", "mlp_mnist", "--engine", "graph",
                    "--parallel", "dp", "--steps", "30",
                    "--batch-size", "64", "--log-every", "10",
                    "--metrics-file", str(tmp_path / "m.jsonl")])
    assert np.isfinite(metrics["loss"])
    err = capsys.readouterr().err
    assert "running single-device" not in err  # the graph-dp degrade path
    assert "only 1 device" not in err
    lines = [json.loads(l) for l in
             (tmp_path / "m.jsonl").read_text().strip().splitlines()]
    assert lines[-1]["loss"] < lines[0]["loss"]
    with pytest.raises(SystemExit, match="not divisible by mesh axis"):
        _run(["--config", "mlp_mnist", "--engine", "graph", "--parallel",
              "dp", "--steps", "1", "--batch-size", "60"])
    # The conv path: graph-dp ResNet (tiny) trains over the mesh too.
    metrics = _run(["--config", "resnet50_imagenet", "--model-preset",
                    "tiny", "--engine", "graph", "--parallel", "dp",
                    "--steps", "4", "--batch-size", "16",
                    "--log-every", "2"])
    assert np.isfinite(metrics["loss"])
    # And the AdamW path (dp_adamw_update_graph): graph-dp GPT-2 + BERT
    # (BERT is the riskiest wiring: 5 feed arrays incl. a 4-d attn_mask
    # sharded over dp; per-shard masked-mean loss is the documented dp
    # semantics, so finite-and-runs is the contract here — exact dp math
    # is pinned by test_graph.py's GPT-2 parity).
    for config in ("gpt2_124m", "bert_base_zero1"):
        metrics = _run(["--config", config, "--model-preset", "tiny",
                        "--engine", "graph", "--parallel", "dp",
                        "--steps", "4", "--batch-size", "16",
                        "--log-every", "2"])
        assert np.isfinite(metrics["loss"]), config
    with pytest.raises(SystemExit, match="supports --parallel dp"):
        _run(["--config", "mlp_mnist", "--engine", "graph", "--parallel",
              "pp", "--steps", "1", "--batch-size", "8"])
    with pytest.raises(SystemExit, match="mesh axis 'dp'"):
        _run(["--config", "mlp_mnist", "--engine", "graph", "--parallel",
              "dp", "--mesh", "dp=4,tp=2", "--steps", "1",
              "--batch-size", "8"])


def test_cli_graph_engine_zero1(devices8, tmp_path, capsys):
    """--engine graph --parallel zero1: the IR's reduce_scatter/all_gather
    path trains over the 8-device mesh from the CLI (loss drops, no
    degrade), resumes from its flat-chunk checkpoint, and invalid combos
    reject loudly."""
    import pytest
    ck = str(tmp_path / "ck")
    metrics = _run(["--config", "mlp_mnist", "--engine", "graph",
                    "--parallel", "zero1", "--steps", "30",
                    "--batch-size", "64", "--log-every", "10",
                    "--ckpt-dir", ck, "--eval", "--eval-batches", "4",
                    "--metrics-file", str(tmp_path / "m.jsonl")])
    assert np.isfinite(metrics["loss"])
    # Eval runs off params materialized from the flat sharded state.
    assert any(k.startswith("eval_") for k in metrics)
    assert "running single-device" not in capsys.readouterr().err
    lines = [json.loads(l) for l in
             (tmp_path / "m.jsonl").read_text().strip().splitlines()]
    assert lines[-1]["loss"] < lines[0]["loss"]
    m = _run(["--config", "mlp_mnist", "--engine", "graph", "--parallel",
              "zero1", "--steps", "5", "--batch-size", "64",
              "--ckpt-dir", ck, "--log-every", "5"])
    assert m["step"] == 35  # resumed at 30, trained 5 more
    with pytest.raises(SystemExit, match="graph-engine zero1 is authored"):
        _run(["--config", "gpt2_124m", "--model-preset", "tiny", "--engine",
              "graph", "--parallel", "zero1", "--steps", "1",
              "--batch-size", "8"])


def test_cli_graph_engine_resnet(tmp_path):
    """Config 2 through the Graph IR engine (tiny preset): runs from the
    CLI with finite loss (descent is asserted on a fixed batch in
    test_graph.py); --eval is rejected (no running BN stats)."""
    import pytest
    _run(["--config", "resnet50_imagenet", "--model-preset",
                    "tiny", "--engine", "graph", "--steps", "6",
                    "--batch-size", "8", "--log-every", "2",
                    "--metrics-file", str(tmp_path / "m.jsonl")])
    # Rotating random-label batches at fixed lr don't descend this fast;
    # descent is asserted on a fixed batch in test_graph.py. Here: the IR
    # program runs through the CLI and stays finite.
    lines = [json.loads(l) for l in
             (tmp_path / "m.jsonl").read_text().strip().splitlines()]
    assert all(np.isfinite(l["loss"]) for l in lines)
    with pytest.raises(SystemExit, match="running BN stats"):
        _run(["--config", "resnet50_imagenet", "--model-preset", "tiny",
              "--engine", "graph", "--steps", "1", "--batch-size", "8",
              "--eval"])


def test_cli_graph_engine_bert(tmp_path):
    """Config 4's model through the Graph IR engine: the IR-authored BERT
    encoder + AdamW graphs train from the CLI and the loss drops."""
    metrics = _run(["--config", "bert_base_zero1", "--model-preset", "tiny",
                    "--engine", "graph", "--steps", "20",
                    "--batch-size", "8", "--log-every", "5",
                    "--metrics-file", str(tmp_path / "m.jsonl")])
    lines = [json.loads(l) for l in
             (tmp_path / "m.jsonl").read_text().strip().splitlines()]
    assert lines[-1]["loss"] < lines[0]["loss"]


def test_cli_graph_engine_gpt2(tmp_path):
    """Config 3 through the Graph IR engine: the IR-authored transformer +
    AdamW update graphs train from the CLI and the loss drops."""
    metrics = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
                    "--engine", "graph", "--steps", "30",
                    "--batch-size", "8", "--log-every", "10",
                    "--metrics-file", str(tmp_path / "m.jsonl")])
    lines = [json.loads(l) for l in
             (tmp_path / "m.jsonl").read_text().strip().splitlines()]
    assert lines[-1]["loss"] < lines[0]["loss"]


def test_cli_degrade_warning_is_loud(monkeypatch, capsys):
    """A multi-device config on a 1-device host must warn, not silently
    shrink to 1/Nth scale (VERDICT round 1, weak #5)."""
    import jax
    one = jax.devices()[:1]
    monkeypatch.setattr(jax, "devices", lambda *a, **k: one)
    _run(["--config", "resnet50_imagenet", "--steps", "0",
          "--batch-size", "8"])
    err = capsys.readouterr().err
    assert "WARNING" in err and "only 1 device" in err


def test_cli_trains_rn50_from_image_records(devices8, tmp_path):
    """E2E: write NZR1 records, train ResNet-50 DP through the CLI from
    them (the real-data input path of benchmark config 2)."""
    from nezha_tpu.data.native import write_image_records
    from nezha_tpu.runtime.native import native_available
    if not native_available():
        import pytest
        pytest.skip("native runtime not available")
    rng = np.random.RandomState(0)
    write_image_records(
        tmp_path / "train.nzr",
        rng.randint(0, 256, (64, 40, 40, 3), dtype=np.uint8).astype(np.uint8),
        rng.randint(0, 100, 64))  # tiny preset has 100 classes
    # 20 val records with batch 8 forces the divisor adjustment (-> 5) and
    # full coverage; count pins the val.nzr path (synthetic fallback would
    # differ).
    write_image_records(
        tmp_path / "val.nzr",
        rng.randint(0, 256, (20, 40, 40, 3), dtype=np.uint8),
        rng.randint(0, 100, 20))
    # tiny preset: the test pins the records->loader->train->eval plumbing,
    # not model depth — the full 50-layer compile added ~45s of nothing.
    metrics = _run(["--config", "resnet50_imagenet", "--model-preset", "tiny",
                    "--steps", "2", "--batch-size", "8", "--log-every", "1",
                    "--data-dir", str(tmp_path), "--crop", "32",
                    "--eval"])
    assert np.isfinite(metrics["loss"])
    assert metrics["eval_count"] == 20  # every val record, exactly once


def test_cli_zero1_sharded_checkpoint_resume(devices8, tmp_path):
    """ZeRO-1 CLI runs checkpoint in the per-shard format and resume from it."""
    ck = str(tmp_path / "ck")
    _run(["--config", "bert_base_zero1", "--model-preset", "tiny",
          "--steps", "2", "--batch-size", "8",
          "--ckpt-dir", ck, "--log-every", "1"])
    import pathlib
    assert list(pathlib.Path(ck).glob("step_*.sharded"))
    m = _run(["--config", "bert_base_zero1", "--model-preset", "tiny",
              "--steps", "1",
              "--batch-size", "8", "--ckpt-dir", ck, "--log-every", "1"])
    assert m["step"] == 3  # resumed at 2, trained 1 more


def test_cli_ckpt_keep_retention(devices8, tmp_path):
    """--ckpt-keep N prunes old checkpoints in both formats (npz via the
    Trainer default path, per-shard via the wrapped async save_fn)."""
    import pathlib
    ck = str(tmp_path / "npz")
    _run(["--config", "mlp_mnist", "--steps", "6", "--batch-size", "16",
          "--ckpt-dir", ck, "--ckpt-every", "2", "--ckpt-keep", "1"])
    names = sorted(p.name for p in pathlib.Path(ck).glob("step_*.npz"))
    assert names == ["step_00000006.npz"]  # 2 and 4 pruned, final kept

    ck = str(tmp_path / "sharded")
    _run(["--config", "bert_base_zero1", "--model-preset", "tiny",
          "--steps", "4", "--batch-size", "16", "--mesh", "dp=8",
          "--ckpt-dir", ck, "--ckpt-every", "1", "--ckpt-keep", "2"])
    names = sorted(p.name for p in pathlib.Path(ck).glob("step_*.sharded"))
    assert names == ["step_00000003.sharded", "step_00000004.sharded"]


def test_cli_failure_detection_checkpoints_then_raises(tmp_path):
    """Kill a peer rank mid-run: the CLI loop (via Trainer) must detect the
    failure, checkpoint, and raise — the elastic machinery live from the
    CLI (VERDICT round 1, weak #6)."""
    import threading

    import pytest

    from nezha_tpu.runtime.native import native_available
    if not native_available():
        pytest.skip("native runtime not available")
    from nezha_tpu import dist
    from nezha_tpu.cli.train import build_parser, run

    with dist.Coordinator(world_size=2, heartbeat_timeout_s=1.0) as coord:
        g1 = dist.join("127.0.0.1", coord.port, rank_hint=1,
                       heartbeat_interval_s=0.1)
        killer = threading.Timer(1.0, g1.close)  # abrupt: no LEAVE
        killer.start()
        ck = str(tmp_path / "ck")
        args = build_parser().parse_args([
            "--config", "mlp_mnist", "--steps", "100000",
            "--batch-size", "16", "--log-every", "100000",
            "--failure-check-every", "5", "--ckpt-dir", ck,
            "--coordinator", f"127.0.0.1:{coord.port}",
            "--no-jax-distributed"])
        with pytest.raises(RuntimeError, match=r"peer rank\(s\) \[1\]"):
            run(args)
        import pathlib
        assert list(pathlib.Path(ck).glob("step_*.npz"))  # saved before raise


def test_cli_elastic_rejoin_continues(tmp_path):
    """The FULL elastic cycle (VERDICT r3 weak #7), automatically: rank 1
    is SIGKILLed mid-run; rank 0 detects it, commits a rescue checkpoint,
    and (--on-failure rejoin) WAITS; rank 1 is relaunched with --rank-hint
    1, resumes from the rescue checkpoint; rank 0 sees the world heal,
    reloads the same checkpoint, and training CONTINUES in-process to
    completion on both ranks."""
    import pathlib

    import pytest

    from nezha_tpu.runtime.native import native_available
    if not native_available():
        pytest.skip("native runtime not available")

    from conftest import TwoRankElastic
    cluster = TwoRankElastic(tmp_path)
    try:
        r0 = cluster.launch("r0", ["--steps", "2000", "--serve-coordinator",
                                   "--world-size", "2"])
        r1 = cluster.launch("r1", ["--steps", "2000", "--rank-hint", "1"])
        # Kill rank 1 only once it is demonstrably mid-training (has
        # logged a metrics line), so the failure lands between steps.
        cluster.wait_for("r1", '"step"', r1)
        r1.kill()
        r1.wait()

        # Rank 0 must detect, checkpoint, and announce the wait.
        cluster.wait_for("r0", "waiting for rejoin", r0)
        assert list(pathlib.Path(cluster.ck).glob("step_*.npz"))  # rescue

        # Relaunch the dead rank into its old slot; both must finish.
        r1b = cluster.launch("r1b", ["--steps", "200", "--rank-hint", "1"])
        assert r0.wait(timeout=240) == 0, cluster.err("r0")
        assert r1b.wait(timeout=240) == 0, cluster.err("r1b")
    finally:
        cluster.cleanup()

    e0 = cluster.err("r0")
    assert "world healed; resumed from step" in e0
    assert "resumed from step" in cluster.err("r1b")  # restored rescue ckpt
    # The loss stream continued: rank 0's logged steps are strictly
    # increasing through the failure and reach the full horizon.
    steps = [json.loads(l)["step"] for l in e0.splitlines()
             if l.startswith("{") and '"step"' in l]
    assert steps[-1] == 2000
    assert all(a < b for a, b in zip(steps, steps[1:]))  # no re-logged steps


def test_cli_rejoin_timeout_gives_up_loudly(tmp_path):
    """--on-failure rejoin with NO replacement: the survivor must not wait
    forever — after --rejoin-timeout it raises (checkpoint already
    committed), exiting nonzero with the timeout message."""
    import pathlib

    import pytest

    from nezha_tpu.runtime.native import native_available
    if not native_available():
        pytest.skip("native runtime not available")

    from conftest import TwoRankElastic
    cluster = TwoRankElastic(tmp_path, rejoin_timeout="3")
    try:
        r0 = cluster.launch("r0", ["--steps", "2000", "--serve-coordinator",
                                   "--world-size", "2"])
        r1 = cluster.launch("r1", ["--steps", "2000", "--rank-hint", "1"])
        cluster.wait_for("r1", '"step"', r1)
        r1.kill()
        r1.wait()
        assert r0.wait(timeout=180) != 0  # gave up, loudly
    finally:
        cluster.cleanup()
    assert "no replacement rejoined within 3s" in cluster.err("r0")
    assert list(pathlib.Path(cluster.ck).glob("step_*.npz"))  # rescue saved


def test_cli_on_failure_rejoin_validation():
    """--on-failure rejoin rejects combos its recovery path cannot honor."""
    import pytest
    with pytest.raises(SystemExit, match="needs --coordinator"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
              "--on-failure", "rejoin"])
    with pytest.raises(SystemExit, match="needs --ckpt-dir"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
              "--on-failure", "rejoin", "--coordinator", "127.0.0.1:1",
              "--no-jax-distributed"])
    with pytest.raises(SystemExit, match="no-jax-distributed"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
              "--on-failure", "rejoin", "--coordinator", "127.0.0.1:1",
              "--ckpt-dir", "/tmp/x"])


def test_cli_with_coordinator(tmp_path):
    """Single-process world through the real coordinator dial-in path."""
    from nezha_tpu.runtime.native import native_available
    if not native_available():
        import pytest
        pytest.skip("native runtime not available")
    from nezha_tpu import dist
    from nezha_tpu.cli.train import build_parser, run

    with dist.Coordinator(world_size=1) as coord:
        args = build_parser().parse_args([
            "--config", "mlp_mnist", "--steps", "4", "--batch-size", "16",
            "--platform", "cpu", "--log-every", "2",
            "--coordinator", f"127.0.0.1:{coord.port}",
        ])
        last = run(args)
    assert "loss" in last


def test_cli_two_process_dp_sharded_data(devices8, tmp_path):
    """The pod launch path end-to-end on one box: two OS processes
    rendezvous via --coordinator, enter jax.distributed, shard the record
    file by rank (disjoint halves of each epoch), assemble global batches
    from process-local rows, and train DP over the 2-device global mesh —
    replicated metrics must agree bit-for-bit across ranks."""
    import socket
    import sys

    from conftest import run_worker_processes
    from nezha_tpu.data.native import write_image_records
    from nezha_tpu.runtime.native import native_available
    if not native_available():
        import pytest
        pytest.skip("native runtime not available")

    rng = np.random.RandomState(0)
    write_image_records(
        tmp_path / "train.nzr",
        rng.randint(0, 256, (32, 36, 36, 3), dtype=np.uint8),
        rng.randint(0, 100, 32))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    base = [sys.executable, "-m", "nezha_tpu.cli.train",
            "--config", "resnet50_imagenet", "--model-preset", "tiny",
            "--steps", "2", "--batch-size", "8", "--mesh", "dp=2",
            "--crop", "32", "--data-dir", str(tmp_path),
            "--platform", "cpu", "--log-every", "1",
            "--coordinator", f"127.0.0.1:{port}"]
    results = run_worker_processes([
        base + (["--serve-coordinator", "--world-size", "2"] if i == 0
                else [])
        for i in range(2)])
    for rc, _, err in results:
        assert rc == 0, err[-3000:]
    shards = {s for _, _, err in results
              for s in ("(shard 0/2)", "(shard 1/2)") if s in err}
    assert shards == {"(shard 0/2)", "(shard 1/2)"}, \
        [e[-500:] for _, _, e in results]
    finals = [json.loads(out.strip().splitlines()[-1])["final"]["loss"]
              for _, out, _ in results]
    assert np.isfinite(finals[0])
    assert finals[0] == finals[1]  # replicated metrics agree across ranks


def test_cli_two_process_graph_dp(devices8):
    """Graph-engine dp across two OS processes: the IR all_reduce path
    composes with the multi-process launch (process-local rows assembled
    into the global batch) — replicated metrics must agree across ranks."""
    import socket
    import sys

    from conftest import run_worker_processes
    from nezha_tpu.runtime.native import native_available
    if not native_available():
        import pytest
        pytest.skip("native runtime not available")

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    base = [sys.executable, "-m", "nezha_tpu.cli.train",
            "--config", "mlp_mnist", "--engine", "graph",
            "--parallel", "dp", "--steps", "3", "--batch-size", "16",
            "--platform", "cpu", "--log-every", "1",
            "--coordinator", f"127.0.0.1:{port}"]
    results = run_worker_processes([
        base + (["--serve-coordinator", "--world-size", "2"] if i == 0
                else [])
        for i in range(2)])
    for rc, _, err in results:
        assert rc == 0, err[-3000:]
        # jax.distributed forms the 2-device global world — the degrade
        # path must NOT fire, or the IR all_reduce never runs.
        assert "running single-device" not in err, err[-2000:]
    finals = [json.loads(out.strip().splitlines()[-1])["final"]["loss"]
              for _, out, _ in results]
    assert np.isfinite(finals[0])
    assert finals[0] == finals[1]  # replicated metrics agree across ranks


def test_cli_dropout_pipelines(devices8):
    """--dropout works in pp mode (per-layer/microbatch keys through the
    GPipe schedule) and is rejected where it cannot apply."""
    import pytest
    m = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--parallel", "pp", "--mesh", "dp=2,pp=4",
              "--microbatches", "2", "--dropout", "0.2", "--steps", "2",
              "--batch-size", "8", "--log-every", "1"])
    assert np.isfinite(m["loss"])
    with pytest.raises(SystemExit, match="applies to gpt2_124m"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
              "--dropout", "0.1"])
    with pytest.raises(SystemExit, match="no.*dropout path|dropout path"):
        _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--engine", "graph", "--steps", "1", "--batch-size", "8",
              "--dropout", "0.1"])
    with pytest.raises(SystemExit, match=r"in \[0, 1\)"):
        _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "1", "--batch-size", "8", "--dropout", "1.5"])


def test_cli_grad_accum(devices8):
    """--grad-accum N holds updates for N micro-steps: params change only
    every Nth step, and the graph engine rejects the wrapper."""
    import pytest
    losses = _final_losses("gpt2_124m", 4, 8,
                           ["--parallel", "single", "--grad-accum", "2"])
    # Steps 1 and 2 see the same params (update flushes at step 2's end):
    # identical batch stream per step is not guaranteed, so instead pin the
    # mechanism by comparing against no-accum: first-step losses match
    # (same init params), later steps diverge.
    plain = _final_losses("gpt2_124m", 4, 8, ["--parallel", "single"])
    np.testing.assert_allclose(losses[0], plain[0], rtol=1e-6)
    assert not np.allclose(losses[-1], plain[-1], rtol=1e-6)
    with pytest.raises(SystemExit, match="graph engine"):
        _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--engine", "graph", "--steps", "1", "--batch-size", "8",
              "--grad-accum", "2"])
    with pytest.raises(SystemExit, match="grad-accum must be"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
              "--grad-accum", "0"])


def test_cli_clip_norm(devices8):
    """--clip-norm bounds the update: a near-zero clip freezes training
    (losses stay ~constant) where the unclipped run moves; invalid values
    reject."""
    import pytest
    # mlp_mnist trains with momentum SGD, whose update scales with the
    # gradient (AdamW's does not — it normalizes scale away), so a
    # near-zero clip visibly freezes it.
    clipped = _final_losses("mlp_mnist", 8, 64,
                            ["--parallel", "single", "--clip-norm", "1e-9"])
    plain = _final_losses("mlp_mnist", 8, 64, ["--parallel", "single"])
    # Frozen params still see per-batch loss noise (~0.05); the real run's
    # drop must dwarf the clipped run's drift.
    assert plain[0] - plain[-1] > 5 * abs(clipped[0] - clipped[-1]), \
        (plain, clipped)
    with pytest.raises(SystemExit, match="clip-norm must be"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
              "--clip-norm", "-1"])
    # The graph engine authors the clip in the IR (clip_scale_graph): a
    # near-zero clip freezes it exactly like the module engine.
    gclip = _final_losses("mlp_mnist", 8, 64,
                          ["--engine", "graph", "--clip-norm", "1e-9"])
    gplain = _final_losses("mlp_mnist", 8, 64, ["--engine", "graph"])
    assert gplain[0] - gplain[-1] > 5 * abs(gclip[0] - gclip[-1]), \
        (gplain, gclip)
    # Graph-dp cannot clip (the all_reduce lives inside the update graphs).
    with pytest.raises(SystemExit, match="REDUCED gradients"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
              "--engine", "graph", "--parallel", "dp", "--clip-norm", "1.0"])


def test_cli_ckpt_keep_rejects_nonpositive():
    import pytest
    with pytest.raises(SystemExit, match="ckpt-keep must be >= 1"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
              "--ckpt-keep", "0"])


def test_cli_optimizer_override(devices8):
    """--optimizer swaps the config's optimizer (with --lr + warmup/cosine);
    invalid combinations reject loudly."""
    import pytest
    m = _run(["--config", "resnet50_imagenet", "--model-preset", "tiny",
              "--steps", "3", "--batch-size", "16", "--mesh", "dp=8",
              "--optimizer", "lars", "--lr", "0.5", "--log-every", "1"])
    assert np.isfinite(m["loss"])
    m = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "2", "--batch-size", "8", "--parallel", "single",
              "--optimizer", "adafactor", "--lr", "1e-2"])
    assert np.isfinite(m["loss"])
    with pytest.raises(SystemExit, match="needs --lr"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
              "--optimizer", "adamw"])
    with pytest.raises(SystemExit, match="only applies with --optimizer"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
              "--lr", "0.1"])
    with pytest.raises(SystemExit, match="layerwise trust ratios"):
        _run(["--config", "bert_base_zero1", "--model-preset", "tiny",
              "--steps", "1", "--batch-size", "8", "--optimizer", "lamb",
              "--lr", "1e-3"])
    with pytest.raises(SystemExit, match="graph engine"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
              "--engine", "graph", "--optimizer", "adamw", "--lr", "1e-3"])


def test_cli_lr_rejects_nonpositive():
    import pytest
    with pytest.raises(SystemExit, match="lr must be"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
              "--optimizer", "sgd", "--lr", "nan"])


def test_cli_eval_every(devices8, tmp_path):
    """--eval-every N interleaves full eval passes with training: the
    metrics stream carries eval_* entries at each boundary plus the final
    pass, and eval accuracy reflects the current (training) params."""
    import pytest
    mf = tmp_path / "m.jsonl"
    m = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--parallel", "single", "--steps", "4", "--batch-size", "8",
              "--eval-every", "2", "--eval-batches", "2",
              "--log-every", "4", "--metrics-file", str(mf)])
    assert any(k.startswith("eval_") for k in m)  # final pass in result
    recs = [json.loads(l) for l in mf.read_text().strip().splitlines()]
    evals = [r for r in recs if any(k.startswith("eval_") for k in r)]
    assert len(evals) == 1 and evals[0]["step"] == 2  # midpoint pass logged
    with pytest.raises(SystemExit, match="eval-every must be"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
              "--eval-every", "0"])


def test_cli_knob_composition(devices8, tmp_path):
    """The whole knob stack composes in one run: gspmd (dp x tp) + remat +
    dropout + global clip + grad accumulation + periodic eval + retention,
    end to end with finite losses."""
    m = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--parallel", "gspmd", "--mesh", "dp=2,tp=4",
              "--remat", "--dropout", "0.1", "--clip-norm", "1.0",
              "--grad-accum", "2", "--eval-every", "2",
              "--eval-batches", "2", "--steps", "4", "--batch-size", "8",
              "--ckpt-dir", str(tmp_path), "--ckpt-every", "2",
              "--ckpt-keep", "1", "--log-every", "2"])
    assert np.isfinite(m["loss"])
    assert any(k.startswith("eval_") for k in m)
    kept = list(tmp_path.glob("step_*.sharded"))
    assert len(kept) == 1  # retention pruned to the newest


def test_cli_bert_real_token_data(devices8, tmp_path):
    """Config 4 on real data: packed tokens -> native TokenLoader ->
    dynamic MLM masking -> ZeRO-1 training (the same .tokens.u16 format
    GPT-2 consumes)."""
    import pytest
    try:
        from nezha_tpu.data.native import load_library
        load_library()
    except Exception:
        pytest.skip("native runtime not available")
    rng = np.random.RandomState(0)
    (tmp_path / "train.tokens.u16").write_bytes(
        rng.randint(0, 512, 8192).astype(np.uint16).tobytes())
    metrics = _run(["--config", "bert_base_zero1", "--model-preset", "tiny",
                    "--steps", "2", "--batch-size", "8", "--log-every", "1",
                    "--data-dir", str(tmp_path)])
    assert np.isfinite(metrics["loss"])


def test_cli_scan_layers(devices8):
    """--scan-layers trains the stacked trunk (single + dp), and the
    incompatible engines/modes reject loudly."""
    import pytest
    metrics = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
                    "--steps", "2", "--batch-size", "2", "--scan-layers",
                    "--parallel", "single", "--log-every", "1"])
    assert np.isfinite(metrics["loss"])
    metrics = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
                    "--steps", "2", "--batch-size", "8", "--scan-layers",
                    "--mesh", "dp=8", "--log-every", "1"])
    assert np.isfinite(metrics["loss"])
    with pytest.raises(SystemExit, match="scan-layers"):
        _run(["--config", "resnet50_imagenet", "--model-preset", "tiny",
              "--steps", "1", "--batch-size", "2", "--scan-layers"])
    with pytest.raises(SystemExit, match="scan-layers"):
        _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "1", "--batch-size", "4", "--scan-layers",
              "--parallel", "pp", "--mesh", "dp=4,pp=2",
              "--microbatches", "2"])
    with pytest.raises(SystemExit, match="graph"):
        _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "1", "--batch-size", "2", "--scan-layers",
              "--engine", "graph"])


def test_cli_bert_byte_corpus_requires_explicit_mask_token(tmp_path):
    """A byte-packed corpus (all sampled ids < 256) with a defaulted MLM
    mask token is refused — the default 103 is a real byte value there
    (ADVICE r4); an explicit --mlm-mask-token proceeds."""
    import pytest
    try:
        from nezha_tpu.data.native import load_library
        load_library()
    except Exception:
        pytest.skip("native runtime not available")
    rng = np.random.RandomState(0)
    (tmp_path / "train.tokens.u16").write_bytes(
        rng.randint(0, 256, 8192).astype(np.uint16).tobytes())
    with pytest.raises(SystemExit, match="byte-packed"):
        _run(["--config", "bert_base_zero1", "--model-preset", "tiny",
              "--steps", "1", "--batch-size", "8",
              "--data-dir", str(tmp_path)])
    metrics = _run(["--config", "bert_base_zero1", "--model-preset", "tiny",
                    "--steps", "2", "--batch-size", "8", "--log-every", "1",
                    "--mlm-mask-token", "300",
                    "--data-dir", str(tmp_path)])
    assert np.isfinite(metrics["loss"])


def test_cli_bert_scan_layers(devices8):
    """--scan-layers trains BERT's stacked encoder under zero1."""
    metrics = _run(["--config", "bert_base_zero1", "--model-preset", "tiny",
                    "--steps", "2", "--batch-size", "16", "--scan-layers",
                    "--mesh", "dp=8", "--log-every", "1"])
    assert np.isfinite(metrics["loss"])


def test_cli_scan_layers_full_preset_builders():
    """Both full-preset builders accept the scan_layers override (the
    tiny-only CLI tests would miss a zero-arg full-preset lambda)."""
    from nezha_tpu.cli.train import _configs
    cfgs = _configs()
    for name in ("gpt2_124m", "bert_base_zero1"):
        m = cfgs[name].build_model(scan_layers=True)
        assert m.cfg.scan_layers


def test_cli_resnet_remat(devices8):
    """--remat now covers the image configs (per-bottleneck checkpoint)."""
    metrics = _run(["--config", "resnet50_imagenet", "--model-preset", "tiny",
                    "--steps", "2", "--batch-size", "16", "--remat",
                    "--mesh", "dp=8", "--log-every", "1"])
    assert np.isfinite(metrics["loss"])


def test_cli_scan_layers_gspmd_matches_single(devices8):
    """--scan-layers composes with GSPMD tensor parallel: the stacked
    trunk shards via the SAME Megatron rule table (leading layer dim
    prepended) and matches single-device numerics step-for-step."""
    ref = _final_losses("gpt2_124m", 3, 8,
                        ["--parallel", "single", "--scan-layers"])
    tp = _final_losses("gpt2_124m", 3, 8,
                       ["--parallel", "gspmd", "--mesh", "dp=2,tp=4",
                        "--scan-layers"])
    np.testing.assert_allclose(tp, ref, rtol=1e-3)
    # And the unrolled single matches the scan single (layout-invariant).
    ref_unrolled = _final_losses("gpt2_124m", 3, 8, ["--parallel", "single"])
    np.testing.assert_allclose(ref, ref_unrolled, rtol=1e-4)


def test_cli_wd_exclude_1d(devices8):
    """--wd-exclude-1d masks weight decay off 1-D leaves; invalid combos
    reject loudly."""
    import pytest
    m = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "2", "--batch-size", "8", "--wd-exclude-1d",
              "--mesh", "dp=8", "--log-every", "1"])
    assert np.isfinite(m["loss"])
    # Composes with the stacked trunk (the mask is layout-aware).
    m = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "2", "--batch-size", "8", "--wd-exclude-1d",
              "--scan-layers", "--mesh", "dp=8", "--log-every", "1"])
    assert np.isfinite(m["loss"])
    with pytest.raises(SystemExit, match="wd-exclude-1d"):
        _run(["--config", "bert_base_zero1", "--model-preset", "tiny",
              "--steps", "1", "--batch-size", "8", "--wd-exclude-1d",
              "--parallel", "zero1", "--mesh", "dp=8"])
    with pytest.raises(SystemExit, match="wd-exclude-1d"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
              "--wd-exclude-1d"])
    with pytest.raises(SystemExit, match="graph"):
        _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "1", "--batch-size", "4", "--wd-exclude-1d",
              "--engine", "graph"])


def test_cli_wd_exclude_1d_changes_decay_not_masked_leaves():
    """The mask really turns decay off for 1-D leaves: with lr frozen and
    zero gradients, decayed leaves shrink and masked leaves don't."""
    import jax
    from nezha_tpu import optim
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config

    model = GPT2(GPT2Config(vocab_size=64, max_positions=16, num_layers=1,
                            num_heads=2, hidden_size=16))
    params = model.init(jax.random.PRNGKey(0))["params"]
    opt = optim.adamw(1e-2, weight_decay=0.5,
                      mask=optim.matrix_decay_mask)
    state = opt.init(params)
    zeros = jax.tree_util.tree_map(lambda p: np.zeros_like(p), params)
    upd, _ = opt.update(zeros, state, params)
    flat = dict(jax.tree_util.tree_leaves_with_path(upd))
    for path, u in flat.items():
        nd = np.asarray(u).ndim
        if nd >= 2:
            assert np.any(np.asarray(u) != 0.0), path  # decay applied
        else:
            np.testing.assert_array_equal(np.asarray(u), 0.0, err_msg=str(path))


def test_cli_gpt2_rejects_out_of_vocab_corpus(tmp_path):
    """Token files with ids >= the model vocab are refused up front (they
    would NaN the CE via out-of-range target gathers, silently)."""
    import pytest
    (tmp_path / "train.tokens.u16").write_bytes(
        np.random.RandomState(0).randint(0, 700, 8192)
        .astype(np.uint16).tobytes())
    with pytest.raises(SystemExit, match="vocab"):
        _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "1", "--batch-size", "4", "--seq-len", "64",
              "--data-dir", str(tmp_path)])


def test_cli_scan_layers_sp_matches_single(devices8):
    """--scan-layers composes with ring-attention sequence parallelism:
    the per-hop collectives run inside the lax.scan body under shard_map,
    matching single-device numerics step-for-step."""
    ref = _final_losses("gpt2_124m", 3, 8,
                        ["--parallel", "single", "--scan-layers"])
    sp = _final_losses("gpt2_124m", 3, 8,
                       ["--parallel", "sp", "--mesh", "dp=2,sp=4",
                        "--attn-impl", "ring", "--scan-layers"])
    np.testing.assert_allclose(sp, ref, rtol=1e-3)
    # Ulysses all-to-all + scan + remat compose too (memory-knob stack).
    uly = _final_losses("gpt2_124m", 3, 8,
                        ["--parallel", "sp", "--mesh", "dp=2,sp=4",
                         "--attn-impl", "ulysses", "--scan-layers",
                         "--remat"])
    np.testing.assert_allclose(uly, ref, rtol=1e-3)


def test_cli_bert_eval_and_lm_heldout_eval(tmp_path):
    """--eval works for BERT (masked perplexity over synthetic MLM) and
    both LM configs evaluate held-out val.tokens files deterministically."""
    m = _run(["--config", "bert_base_zero1", "--model-preset", "tiny",
              "--steps", "2", "--batch-size", "8", "--parallel", "single",
              "--eval", "--log-every", "1"])
    assert "eval_perplexity" in m or any("perplexity" in k for k in m), m

    rng = np.random.RandomState(0)
    (tmp_path / "train.tokens.u16").write_bytes(
        rng.randint(0, 512, 40000).astype(np.uint16).tobytes())
    (tmp_path / "val.tokens.u16").write_bytes(
        rng.randint(0, 512, 4000).astype(np.uint16).tobytes())
    m1 = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
               "--steps", "2", "--batch-size", "4", "--seq-len", "64",
               "--parallel", "single",
               "--data-dir", str(tmp_path), "--eval", "--log-every", "1"])
    m2 = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
               "--steps", "2", "--batch-size", "4", "--seq-len", "64",
               "--parallel", "single",
               "--data-dir", str(tmp_path), "--eval", "--log-every", "1"])
    k = [x for x in m1 if "perplexity" in x][0]
    assert np.isfinite(m1[k])
    np.testing.assert_allclose(m1[k], m2[k], rtol=1e-5)  # deterministic
    # BERT over the same held-out tokens (explicit mask id: byte-ish vocab)
    m3 = _run(["--config", "bert_base_zero1", "--model-preset", "tiny",
               "--steps", "2", "--batch-size", "8", "--parallel", "single",
               "--mlm-mask-token", "300", "--data-dir", str(tmp_path),
               "--eval", "--log-every", "1"])
    k3 = [x for x in m3 if "perplexity" in x][0]
    assert np.isfinite(m3[k3])


def test_cli_graph_bf16(devices8):
    """--graph-bf16 trains the IR-authored bf16 policy through the CLI
    (single and graph-dp); non-graph engines reject."""
    import pytest
    m = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "2", "--batch-size", "4", "--engine", "graph",
              "--parallel", "single", "--graph-bf16", "--log-every", "1"])
    assert np.isfinite(m["loss"])
    m = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "2", "--batch-size", "8", "--engine", "graph",
              "--parallel", "dp", "--mesh", "dp=8", "--graph-bf16",
              "--log-every", "1"])
    assert np.isfinite(m["loss"])
    with pytest.raises(SystemExit, match="graph-bf16"):
        _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "1", "--batch-size", "4", "--graph-bf16"])
    with pytest.raises(SystemExit, match="graph-bf16"):
        _run(["--config", "mlp_mnist", "--steps", "1", "--batch-size", "4",
              "--engine", "graph", "--graph-bf16"])


def test_cli_scan_layers_resume_and_knob_compositions(tmp_path, devices8):
    """scan-layers composes with checkpoint resume, --grad-accum,
    --clip-norm, and --wd-exclude-1d; MoE composes with the decay mask."""
    ck = str(tmp_path / "ck")
    _run(["--config", "gpt2_124m", "--model-preset", "tiny", "--steps", "3",
          "--batch-size", "8", "--scan-layers", "--mesh", "dp=8",
          "--ckpt-dir", ck])
    m = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "2", "--batch-size", "8", "--scan-layers",
              "--mesh", "dp=8", "--ckpt-dir", ck, "--log-every", "1"])
    assert m["step"] == 5  # resumed 3 -> 5
    m = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "2", "--batch-size", "8", "--scan-layers",
              "--grad-accum", "2", "--clip-norm", "1.0", "--wd-exclude-1d",
              "--mesh", "dp=8", "--log-every", "1"])
    assert np.isfinite(m["loss"])
    m = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "2", "--batch-size", "8", "--moe-experts", "4",
              "--wd-exclude-1d", "--mesh", "dp=8", "--log-every", "1"])
    assert np.isfinite(m["loss"])
    # BERT stacked encoder under GSPMD TP; scan + int8 gradient wire.
    m = _run(["--config", "bert_base_zero1", "--model-preset", "tiny",
              "--steps", "2", "--batch-size", "8", "--parallel", "gspmd",
              "--mesh", "dp=4,tp=2", "--scan-layers", "--log-every", "1"])
    assert np.isfinite(m["loss"])
    m = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "2", "--batch-size", "8", "--parallel", "dp",
              "--mesh", "dp=8", "--scan-layers", "--grad-allreduce",
              "int8", "--log-every", "1"])
    assert np.isfinite(m["loss"])
    # Sharded (gspmd) checkpoint resume with the stacked trunk.
    ck2 = str(tmp_path / "ck2")
    _run(["--config", "gpt2_124m", "--model-preset", "tiny", "--steps", "2",
          "--batch-size", "8", "--parallel", "gspmd", "--mesh", "dp=4,tp=2",
          "--scan-layers", "--ckpt-dir", ck2])
    m = _run(["--config", "gpt2_124m", "--model-preset", "tiny",
              "--steps", "2", "--batch-size", "8", "--parallel", "gspmd",
              "--mesh", "dp=4,tp=2", "--scan-layers", "--ckpt-dir", ck2,
              "--log-every", "1"])
    assert m["step"] == 4 and np.isfinite(m["loss"])


def test_cli_run_dir_telemetry(devices8, tmp_path):
    """--run-dir captures the run: metrics.jsonl with step rates,
    spans.jsonl, and a summary.json carrying per-collective payload bytes
    and compile-cache counts — all matching the frozen telemetry schema —
    and nezha-telemetry renders a report from it. Telemetry is OFF again
    after the run (the disabled fast path is the default state)."""
    import os
    import sys

    from nezha_tpu import obs
    from nezha_tpu.cli.telemetry import main as telemetry_main

    run_dir = str(tmp_path / "run")
    metrics = _run(["--config", "mlp_mnist", "--steps", "6",
                    "--batch-size", "16", "--parallel", "dp",
                    "--mesh", "dp=8", "--log-every", "2",
                    "--run-dir", run_dir])
    assert np.isfinite(metrics["loss"])
    assert not obs.enabled()  # run scope closed on exit

    recs = obs.read_metrics(os.path.join(run_dir, "metrics.jsonl"))
    assert recs and all("steps_per_sec" in r for r in recs)
    assert recs[-1]["step"] == 6
    spans = obs.read_metrics(os.path.join(run_dir, "spans.jsonl"))
    assert any(s["name"] == "train.first_step" for s in spans)
    with open(os.path.join(run_dir, "summary.json")) as f:
        summary = json.load(f)
    # The dp gradient collective was accounted (trace-time payload bytes).
    ar = summary["collectives"]["all_reduce"]
    assert ar["calls"] >= 1 and ar["payload_bytes"] > 0
    assert summary["compile_cache"]["hits"] >= 0  # section always present
    assert summary["histograms"]["metric.steps_per_sec"]["count"] == 3

    # Frozen schema (tools/check_telemetry_schema.py): drift fails here.
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "tools"))
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []

    # The report CLI renders the capture.
    from contextlib import redirect_stdout
    import io
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert telemetry_main([run_dir]) == 0
    out = buf.getvalue()
    assert "step rate" in out and "all_reduce" in out
    assert "compile cache" in out


def test_cli_bert_mask_token_resolved_from_corpus_tokenizer(devices8,
                                                            tmp_path,
                                                            capsys):
    """No --mlm-mask-token and a non-byte-level corpus: the TRUE [MASK]
    id comes from the tokenizer metadata next to the tokens file — the
    vocab.txt layout and the nezha-pack-text meta sidecar — instead of
    silently defaulting to 103 (ADVICE r5: a learned WordPiece vocab puts
    [MASK] at id 4, where 103 is a real subword)."""
    import pytest
    try:
        from nezha_tpu.data.native import load_library
        load_library()
    except Exception:
        pytest.skip("native runtime not available")
    rng = np.random.RandomState(0)
    (tmp_path / "train.tokens.u16").write_bytes(
        rng.randint(5, 200, 8192).astype(np.uint16).tobytes())
    # Layout 1: the packing tokenizer's vocab.txt sits next to the tokens
    # (--save-tokenizer into the data dir): [MASK] at id 4.
    (tmp_path / "vocab.txt").write_text(
        "[PAD]\n[UNK]\n[CLS]\n[SEP]\n[MASK]\n" +
        "\n".join(f"tok{i}" for i in range(500)) + "\n", encoding="utf-8")
    m = _run(["--config", "bert_base_zero1", "--model-preset", "tiny",
              "--steps", "2", "--batch-size", "8", "--log-every", "1",
              "--data-dir", str(tmp_path)])
    assert np.isfinite(m["loss"])
    assert "[MASK] id 4 resolved" in capsys.readouterr().err
    # Layout 2: the meta sidecar wins even without an adjacent vocab.
    (tmp_path / "vocab.txt").unlink()
    (tmp_path / "train.tokens.u16.meta.json").write_text(
        json.dumps({"tokenizer_kind": "WordPieceTokenizer",
                    "vocab_size": 505, "mask_token_id": 7}),
        encoding="utf-8")
    m = _run(["--config", "bert_base_zero1", "--model-preset", "tiny",
              "--steps", "2", "--batch-size", "8", "--log-every", "1",
              "--data-dir", str(tmp_path)])
    assert np.isfinite(m["loss"])
    assert "[MASK] id 7 resolved" in capsys.readouterr().err
