"""CLI tests: the `nezha-train` entry point runs configs end-to-end
(SURVEY.md §1 `cmd/nezha-train`)."""

import json

import numpy as np

from nezha_tpu.cli.train import build_parser, run


def _run(argv):
    return run(build_parser().parse_args(argv))


def test_cli_mlp_mnist(tmp_path):
    metrics = _run(["--config", "mlp_mnist", "--steps", "30",
                    "--batch-size", "64", "--log-every", "10",
                    "--metrics-file", str(tmp_path / "m.jsonl")])
    assert np.isfinite(metrics["loss"])
    lines = (tmp_path / "m.jsonl").read_text().strip().splitlines()
    assert len(lines) == 3
    assert "examples_per_sec" in json.loads(lines[-1])


def test_cli_resume(tmp_path):
    ck = str(tmp_path / "ck")
    _run(["--config", "mlp_mnist", "--steps", "10", "--batch-size", "64",
          "--ckpt-dir", ck])
    m = _run(["--config", "mlp_mnist", "--steps", "5", "--batch-size", "64",
              "--ckpt-dir", ck, "--log-every", "5"])
    # Resumed from 10 -> logged step numbers continue past it.
    assert m["step"] == 15


def test_cli_dp_mesh(devices8, tmp_path):
    # tiny ResNet stand-in is too slow; use mlp in DP mode via gpt2-like path:
    # mlp_mnist is single-mode by design, so exercise DP through the mesh
    # parse + resnet tiny steps instead.
    metrics = _run(["--config", "mlp_mnist", "--steps", "4",
                    "--batch-size", "64", "--log-every", "2"])
    assert np.isfinite(metrics["loss"])


def test_mesh_parsing():
    from nezha_tpu.cli.train import _parse_mesh
    assert _parse_mesh("dp=4,sp=2") == {"dp": 4, "sp": 2}
    assert _parse_mesh(None) is None


def test_cli_with_coordinator(tmp_path):
    """Single-process world through the real coordinator dial-in path."""
    from nezha_tpu.runtime.native import native_available
    if not native_available():
        import pytest
        pytest.skip("native runtime not available")
    from nezha_tpu import dist
    from nezha_tpu.cli.train import build_parser, run

    with dist.Coordinator(world_size=1) as coord:
        args = build_parser().parse_args([
            "--config", "mlp_mnist", "--steps", "4", "--batch-size", "16",
            "--platform", "cpu", "--log-every", "2",
            "--coordinator", f"127.0.0.1:{coord.port}",
        ])
        last = run(args)
    assert "loss" in last
