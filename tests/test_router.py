"""Multi-replica serving: supervised router with health-checked
failover, rolling drain, and replica-kill chaos (the scale-out PR's
acceptance suite).

The replica-kill chaos acceptance drives 3 REAL replicas (each its own
tiny-GPT-2 engine behind a real HTTP socket, thread-hosted so a kill
severs sockets like a SIGKILL) under a seeded kill plan and pins the
contract: every submitted request completes or retires with a TYPED
error — zero silently-lost requests — killed replicas restart, and the
router's telemetry record is schema-valid. Edge cases get deterministic
tests: all-replicas-full 503, K-miss ejection + readmission, committed
streams are never retried, the restart circuit breaker, and the rolling
drain's never-zero capacity ladder. One process-backend test proves the
subprocess worker path (`cli/serve.run_worker`) end to end.

Tests share a module-scoped 3-replica cluster where state allows (the
chaos kills are healed by the supervisor itself; the rolling-drain test
runs LAST because it consumes the cluster)."""

import json
import os
import socket
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from nezha_tpu import faults, obs
from nezha_tpu.faults import FaultPlan
from nezha_tpu.serve.router import Router, register_router_instruments
from nezha_tpu.serve.supervisor import (
    FAILED,
    STOPPED,
    ProcessBackend,
    RouterConfig,
    Supervisor,
    ThreadBackend,
)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))


def _worker_args(extra=()):
    from nezha_tpu.cli.serve import build_parser
    return build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny", "--max-batch-size",
         "2", "--max-len", "48", "--max-prefill-len", "8",
         "--queue-capacity", "4", "--platform", "cpu", *extra])


def _cfg(**kw):
    base = dict(replicas=3, probe_interval_s=0.1, probe_misses=3,
                route_retries=2, retry_backoff_base_s=0.01,
                retry_backoff_max_s=0.05, restart_backoff_base_s=0.05,
                restart_backoff_max_s=0.5, drain_timeout_s=20.0, seed=0)
    base.update(kw)
    return RouterConfig(**base)


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def cluster3():
    """3 thread-hosted replicas + router. Killed members are healed by
    the supervisor between tests; the rolling-drain test (which runs
    last in this file) is the one consumer that ends it."""
    cfg = _cfg(replicas=3)
    sup = Supervisor(ThreadBackend(_worker_args(), drain_timeout_s=20.0),
                     cfg)
    router = Router(sup, cfg)
    sup.start()
    assert router.wait_live(3, timeout_s=300), sup.describe()
    yield sup, router
    router.stop()
    sup.shutdown()


# ------------------------------------------------------------ stub layer
class _StubReplicaServer:
    """A replica that speaks only the wire protocol (no engine): healthz
    answers ok; /generate behavior switches by ``mode`` — "ok" returns a
    canned result, "partial" begins the response then severs the socket
    mid-body (the died-after-commit case)."""

    def __init__(self):
        self.mode = "ok"
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._send(200, {"status": "ok", "active": 0,
                                 "capacity": 1, "queued": 0,
                                 "occupancy": 0.0})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                if stub.mode == "full":
                    return self._send(503, {
                        "error": "admission queue at capacity 1"})
                if stub.mode == "partial":
                    # The response BEGINS (status + headers + a few
                    # body bytes), then the replica dies: the router
                    # must treat the stream as committed — typed
                    # error, never a retry.
                    self.send_response(200)
                    self.send_header("Content-Length", "1000")
                    self.end_headers()
                    self.wfile.write(b'{"partial":')
                    self.wfile.flush()
                    self.connection.shutdown(socket.SHUT_RDWR)
                    self.connection.close()
                    return
                self._send(200, {"id": "stub", "tokens": [1, 2],
                                 "finish_reason": "length", "text": "",
                                 "ttft_s": 0.0, "latency_s": 0.0})

        class Server(ThreadingHTTPServer):
            daemon_threads = True

            def handle_error(self, request, client_address):
                pass

        self.server = Server(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        self._alive = True
        threading.Thread(target=self.server.serve_forever,
                         kwargs={"poll_interval": 0.05},
                         daemon=True).start()

    def stop(self):
        self._alive = False
        self.server.shutdown()
        self.server.server_close()


class _StubHandle:
    def __init__(self, stub):
        self.stub = stub
        self.port = stub.port

    def alive(self):
        return self.stub._alive

    def terminate(self):
        self.stub.stop()

    def kill(self):
        self.stub.stop()

    def wait(self, timeout):
        return True


class _StubBackend:
    def __init__(self):
        self.stubs = []

    def spawn(self, rid, port):
        stub = _StubReplicaServer()
        self.stubs.append(stub)
        return _StubHandle(stub)


@pytest.fixture()
def stub_cluster():
    backend = _StubBackend()
    cfg = _cfg(replicas=1, probe_misses=2)
    sup = Supervisor(backend, cfg)
    router = Router(sup, cfg)
    sup.start()
    router.probe_all()
    assert sup.live_count() == 1
    yield sup, router, backend
    router.stop()
    sup.shutdown()


# --------------------------------------------------------------- config
def test_router_config_validation():
    with pytest.raises(ValueError):
        RouterConfig(replicas=0)
    with pytest.raises(ValueError):
        RouterConfig(probe_misses=0)
    with pytest.raises(ValueError):
        RouterConfig(route_retries=-1)
    with pytest.raises(ValueError):
        RouterConfig(max_restart_failures=0)


# --------------------------------------------------------------- routing
def test_route_basic(cluster3):
    sup, router = cluster3
    assert router.wait_live(3, timeout_s=300)
    for i in range(4):
        code, obj = router.route(
            {"id": f"basic-{i}", "prompt_tokens": [5, 17, 3],
             "max_new_tokens": 5})
        assert code == 200, obj
        assert obj["finish_reason"] == "length"
        assert len(obj["tokens"]) == 5
    # a replica's own 4xx passes through untouched (bad on every
    # replica — retrying elsewhere would be wasted dispatches)
    code, obj = router.route({"id": "bad", "prompt_tokens": [],
                              "max_new_tokens": 2})
    assert code == 400 and "error" in obj


def test_mid_decode_kill_fails_over(cluster3):
    """A replica killed mid-decode (response not yet begun) provably
    delivered nothing: the router re-dispatches to another replica and
    the request still finishes 200 — one retry, one failover."""
    sup, router = cluster3
    assert router.wait_live(3, timeout_s=300)
    faults.install(FaultPlan.parse("serve.step:delay=0.05x*"))
    retries0, failovers0 = router.retries, router.failovers
    out = {}
    t = threading.Thread(target=lambda: out.update(dict(zip(
        ("code", "obj"),
        router.route({"id": "slowkill", "prompt_tokens": [5, 17, 3],
                      "max_new_tokens": 30})))))
    t.start()
    victim = None
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        busy = [r.rid for r in sup.replicas() if r.in_flight]
        if busy:
            victim = busy[0]
            break
        time.sleep(0.01)
    assert victim is not None
    time.sleep(0.2)            # let a few tokens decode first
    sup.kill(victim)
    t.join(timeout=120)
    faults.clear()
    assert out["code"] == 200, out
    assert out["obj"]["finish_reason"] == "length"
    assert router.retries == retries0 + 1
    assert router.failovers == failovers0 + 1
    # the supervisor heals the kill
    assert router.wait_live(3, timeout_s=300), sup.describe()


def test_chaos_acceptance_replicas3_seeded_kills(cluster3, tmp_path):
    """THE acceptance scenario: 3 replicas, 24 concurrent requests, a
    seeded kill plan firing twice mid-load. Every request gets exactly
    one answer — 200 or a typed error object (zero silently lost) —
    killed replicas are restarted, and the run-dir record carrying
    router.failovers_total / router.replica_restarts_total is
    schema-valid."""
    import random

    sup, router = cluster3
    assert router.wait_live(3, timeout_s=300)
    run_dir = str(tmp_path / "chaos")
    obs.start_run(run_dir, meta={"kind": "router_chaos_test"})
    register_router_instruments()
    from nezha_tpu.serve.scheduler import register_serve_instruments
    register_serve_instruments()
    restarts0 = sup.restarts
    # Slow decode a little so the seeded kills land mid-flight.
    faults.install(FaultPlan.parse("serve.step:delay=0.005x*"))
    try:
        N = 24
        results = []
        lock = threading.Lock()
        next_idx = {"n": 0}

        def client():
            while True:
                with lock:
                    i = next_idx["n"]
                    if i >= N:
                        return
                    next_idx["n"] += 1
                code, obj = router.route(
                    {"id": f"chaos-{i}",
                     "prompt_tokens": [(5 + 3 * i) % 97, 17, 3],
                     "max_new_tokens": 6, "seed": i})
                with lock:
                    results.append((i, code, obj))

        threads = [threading.Thread(target=client) for _ in range(4)]
        for t in threads:
            t.start()
        # The seeded kill plan: one kill when a third of the load has
        # answered, another at two thirds — both mid-serving.
        krng = random.Random(7)
        for milestone in (N // 3, 2 * N // 3):
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                with lock:
                    if len(results) >= milestone:
                        break
                time.sleep(0.005)
            live = sup.live_replicas()
            if live:
                sup.kill(live[krng.randrange(len(live))].rid)
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)

        # Zero silently-lost requests: one answer per request, each a
        # 200 or a TYPED error.
        assert len(results) == N
        assert sorted(i for i, _, _ in results) == list(range(N))
        typed = {"no_live_replicas", "queue_full", "replica_lost",
                 "replica_timeout", "injected_fault"}
        for i, code, obj in results:
            if code == 200:
                assert obj["finish_reason"] in ("length", "eos"), obj
            else:
                assert obj.get("error_type") in typed, (code, obj)
        # kills hit live replicas, so they were restarted
        assert sup.restarts >= restarts0 + 1
        assert router.wait_live(3, timeout_s=300), sup.describe()
    finally:
        faults.clear()
        obs.end_run()
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []
    with open(os.path.join(run_dir, "summary.json")) as f:
        summary = json.load(f)
    for name in ("router.replica_restarts_total", "router.failovers_total",
                 "router.retries_total"):
        assert name in summary["counters"]
    assert summary["counters"]["router.replica_restarts_total"] >= 1
    assert "router.replicas_live" in summary["gauges"]
    assert "router.route_s" in summary["histograms"]
    from nezha_tpu.obs.report import render_report
    report = render_report(run_dir)
    assert "replicas:" in report and "restarts" in report


# ------------------------------------------------------ probing / health
def test_probe_fault_ejects_then_readmits(stub_cluster):
    """K consecutive missed probes eject a replica from routing; the
    first successful probe readmits it."""
    sup, router, backend = stub_cluster
    assert sup.live_count() == 1
    # cfg.probe_misses == 2: two injected probe failures eject it
    faults.install(FaultPlan.parse("router.probe:error@1x2"))
    router.probe_all()
    assert sup.live_count() == 1     # one miss: still routable
    router.probe_all()
    assert sup.live_count() == 0     # ejected after K misses
    code, obj = router.route({"id": "e", "prompt_tokens": [1],
                              "max_new_tokens": 1})
    assert code == 503 and obj["error_type"] == "no_live_replicas"
    faults.clear()
    router.probe_all()               # recovery: readmitted
    assert sup.live_count() == 1
    code, obj = router.route({"id": "r", "prompt_tokens": [1],
                              "max_new_tokens": 1})
    assert code == 200


def test_route_injected_fault_is_typed(stub_cluster):
    """The router.route fault point surfaces as a typed error object —
    chaos at the router itself never silently drops a request."""
    sup, router, backend = stub_cluster
    faults.install(FaultPlan.parse("router.route:error@1"))
    code, obj = router.route({"id": "x", "prompt_tokens": [1],
                              "max_new_tokens": 1})
    assert code == 500 and obj["error_type"] == "injected_fault"
    code, obj = router.route({"id": "y", "prompt_tokens": [1],
                              "max_new_tokens": 1})
    assert code == 200               # rule window closed


def test_committed_stream_is_not_retried(stub_cluster):
    """A replica that dies AFTER its response began: the stream is
    committed, so the router returns the typed replica_lost error and
    attempts NO retry (a re-dispatch could double-serve)."""
    sup, router, backend = stub_cluster
    backend.stubs[0].mode = "partial"
    retries0, failovers0 = router.retries, router.failovers
    code, obj = router.route({"id": "c", "prompt_tokens": [1],
                              "max_new_tokens": 1})
    assert code == 502 and obj["error_type"] == "replica_lost"
    assert "began" in obj["error"]
    assert router.retries == retries0        # no retry attempted
    assert router.failovers == failovers0
    backend.stubs[0].mode = "ok"


# ------------------------------------------------------- backpressure
def test_all_replicas_full_503():
    """Queue-full 503 surfaces to the client only when EVERY live
    replica refused — one replica with room absorbs the request even
    when its neighbors are saturated. Stub replicas make both states
    deterministic (a real engine's queue frees on its own schedule;
    the worker-side QueueFull -> 503 half of the contract is covered by
    test_serve/test_faults)."""
    backend = _StubBackend()
    cfg = _cfg(replicas=2)
    sup = Supervisor(backend, cfg)
    router = Router(sup, cfg)
    try:
        sup.start()
        router.probe_all()
        assert sup.live_count() == 2
        for stub in backend.stubs:
            stub.mode = "full"
        retries0 = router.retries
        code, obj = router.route({"id": "x", "prompt_tokens": [1],
                                  "max_new_tokens": 2})
        assert code == 503, obj
        assert obj["error_type"] == "queue_full"
        assert "2 live replica" in obj["error"]   # both were swept
        assert router.retries == retries0   # full != dead: no retries
        # both replicas are still LIVE (full is backpressure, not
        # death — a 503 must never eject)
        assert sup.live_count() == 2
        # one replica finds room again: the sweep lands there
        backend.stubs[1].mode = "ok"
        code, obj = router.route({"id": "y", "prompt_tokens": [1],
                                  "max_new_tokens": 2})
        assert code == 200, obj
    finally:
        router.stop()
        sup.shutdown()


# ------------------------------------------------- restarts and breaker
def test_replica_exec_crash_is_restarted():
    """A worker that crashes at startup (the replica.exec drill) is
    respawned with backoff; the retry comes up healthy and the restart
    is counted."""
    faults.install(FaultPlan.parse("replica.exec:error@1"))
    cfg = _cfg(replicas=1)
    sup = Supervisor(ThreadBackend(_worker_args(), drain_timeout_s=20.0),
                     cfg)
    router = Router(sup, cfg)
    try:
        sup.start()
        assert router.wait_live(1, timeout_s=300), sup.describe()
        assert sup.restarts == 1
        assert faults.active().injected_counts == {"replica.exec": 1}
        code, obj = router.route({"id": "after", "prompt_tokens": [5],
                                  "max_new_tokens": 2})
        assert code == 200
    finally:
        router.stop()
        sup.shutdown()


def test_circuit_breaker_opens_after_m_failures():
    """M consecutive spawn failures open the replica's circuit breaker:
    the supervisor stops restarting it (no more supervisor.spawn hits)
    and the replica parks in state "failed"."""

    class _NeverBackend:
        def spawn(self, rid, port):     # pragma: no cover — the
            raise AssertionError("unreachable")   # fault fires first

    faults.install(FaultPlan.parse("supervisor.spawn:error@1x*"))
    cfg = _cfg(replicas=1, max_restart_failures=3,
               restart_backoff_base_s=0.01, restart_backoff_max_s=0.02)
    sup = Supervisor(_NeverBackend(), cfg)
    try:
        sup.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if sup.replicas()[0].state == FAILED:
                break
            time.sleep(0.01)
        r = sup.replicas()[0]
        assert r.state == FAILED, r
        assert r.restart_failures == 3
        assert faults.active().hit_counts == {"supervisor.spawn": 3}
        assert sup.restarts == 0
        # breaker is OPEN: no further spawn attempts accumulate
        time.sleep(0.2)
        assert faults.active().hit_counts == {"supervisor.spawn": 3}
        assert sup.live_count() == 0
    finally:
        sup.shutdown()


# --------------------------------------------------------- CLI front end
def test_cli_replicas_requires_http():
    from nezha_tpu.cli.serve import build_parser, run
    args = build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny", "--replicas", "2",
         "--replica-backend", "thread", "--platform", "cpu"])
    with pytest.raises(SystemExit, match="--http"):
        run(args)


def test_cli_multi_replica_front_end_and_drain(tmp_path):
    """nezha-serve --replicas 2 end to end through run(): the router
    front end answers /healthz and routes POST /generate across the
    replicas; the drain event (the signal handlers' path) performs the
    rolling drain and exits 0 with a schema-valid run-dir record."""
    from nezha_tpu.cli.serve import build_parser, run

    run_dir = str(tmp_path / "router_run")
    args = build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny", "--max-batch-size",
         "2", "--max-len", "48", "--max-prefill-len", "8", "--platform",
         "cpu", "--replicas", "2", "--replica-backend", "thread",
         "--http", "0", "--probe-interval", "0.1", "--drain-timeout",
         "20", "--run-dir", run_dir])
    ready, rc = {}, {}
    ready_evt, drain = threading.Event(), threading.Event()

    def ready_cb(server):
        ready["port"] = server.server_address[1]
        ready_evt.set()

    t = threading.Thread(
        target=lambda: rc.update(rc=run(args, ready_cb=ready_cb,
                                        drain_event=drain)),
        daemon=True)
    t.start()
    assert ready_evt.wait(timeout=300)
    base = f"http://127.0.0.1:{ready['port']}"
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(f"{base}/healthz",
                                        timeout=5) as r:
                if json.loads(r.read())["replicas_live"] == 2:
                    break
        except Exception:
            pass
        time.sleep(0.05)
    else:
        pytest.fail("replicas never became live")
    req = urllib.request.Request(
        f"{base}/generate",
        data=json.dumps({"id": "cli", "prompt_tokens": [5, 17, 3],
                         "max_new_tokens": 5}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        obj = json.loads(r.read())
    assert obj["finish_reason"] == "length" and len(obj["tokens"]) == 5
    drain.set()
    t.join(timeout=300)
    assert not t.is_alive() and rc["rc"] == 0
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []
    with open(os.path.join(run_dir, "summary.json")) as f:
        summary = json.load(f)
    assert "router.replica_restarts_total" in summary["counters"]
    # the rolling drain is span-recorded
    with open(os.path.join(run_dir, "spans.jsonl")) as f:
        spans = [json.loads(ln) for ln in f if ln.strip()]
    assert any(sp.get("name") == "router.drain" for sp in spans)


# -------------------------------------------------------- process backend
@pytest.mark.slow
def test_process_backend_kill_and_restart(tmp_path):
    """The production backend: a real nezha-serve subprocess worker
    (cli/serve.run_worker — the same code path --replicas 1 runs),
    probed live, killed with SIGKILL, restarted by the supervisor, then
    drained gracefully via SIGTERM. Marked slow (subprocess spawns +
    full restarts): tier-1 covers the identical router/supervisor logic
    through the thread backend; this test proves the OS-process layer
    (SIGKILL severs sockets, SIGTERM drains) on the full runs."""
    from conftest import worker_env

    from nezha_tpu.cli.serve import _worker_argv, build_parser

    args = build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny", "--max-batch-size",
         "2", "--max-len", "48", "--max-prefill-len", "8", "--platform",
         "cpu", "--drain-timeout", "20"])
    cfg = _cfg(replicas=1, probe_timeout_s=10.0)
    backend = ProcessBackend(
        lambda rid, port: _worker_argv(args, rid, port),
        env=worker_env(), log_dir=str(tmp_path / "logs"))
    sup = Supervisor(backend, cfg)
    router = Router(sup, cfg)
    try:
        sup.start()
        assert router.wait_live(1, timeout_s=600), sup.describe()
        code, obj = router.route({"id": "p", "prompt_tokens": [5, 17],
                                  "max_new_tokens": 3})
        assert code == 200 and len(obj["tokens"]) == 3
        sup.kill(0)
        # wait for the monitor to register the death and respawn (the
        # old record stays nominally "live" until probes/monitor catch
        # up, so poll the restart ledger, not live_count)
        deadline = time.monotonic() + 600
        while time.monotonic() < deadline and sup.restarts < 1:
            time.sleep(0.02)
        assert sup.restarts == 1, sup.describe()
        assert router.wait_live(1, timeout_s=600), sup.describe()
        code, obj = router.route({"id": "q", "prompt_tokens": [7],
                                  "max_new_tokens": 2})
        assert code == 200
        progress = []
        sup.rolling_drain(timeout_s=20.0, progress=progress.append)
        assert progress == [0]
        assert sup.replicas()[0].state == STOPPED
    finally:
        router.stop()
        sup.shutdown()


# -------------------------------------------------- benchmark + rolling
def test_serving_benchmark_replicas_kill_rate(tmp_path):
    sys.path.insert(0, os.path.join(_ROOT, "benchmarks"))
    import serving as bench

    # A pre-installed delay plan slows decode so the seeded kill
    # schedule provably fires mid-load (the bench restores it).
    faults.install(FaultPlan.parse("serve.step:delay=0.01x*"))
    run_dir = str(tmp_path / "repbench")
    rec = bench.run(bench.build_parser().parse_args(
        ["--replicas", "2", "--kill-rate", "20", "--requests", "16",
         "--concurrency", "4", "--prompt-len", "4", "--max-new-tokens",
         "12", "--max-batch-size", "2", "--max-len", "32",
         "--max-prefill-len", "8", "--seed", "3", "--run-dir", run_dir]))
    assert rec["replicas"] == 2 and rec["kill_rate"] == 20.0
    # the zero-silently-lost pin, under kills
    assert rec["answered"] == 16 and rec["lost"] == 0
    assert rec["kills"] >= 1
    assert rec["restarts"] >= 1
    assert rec["recovered_live"] == 2
    assert rec["finished_clean"] + sum(rec["errors_typed"].values()) \
        + rec["faults"]["errored"] == 16
    assert rec["latency_s"]["p50"] > 0
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []
    with open(os.path.join(run_dir, "summary.json")) as f:
        counters = json.load(f)["counters"]
    assert counters["router.replica_restarts_total"] == rec["restarts"]


def test_rolling_drain_never_drops_capacity_to_zero(cluster3):
    """Runs LAST on the shared cluster (it consumes it): with one slow
    request in flight on EACH replica, the rolling drain finishes them
    one replica at a time — live capacity steps 2, 1, 0 and every
    request completes; nothing is cut off."""
    sup, router = cluster3
    assert router.wait_live(3, timeout_s=300)
    faults.install(FaultPlan.parse("serve.step:delay=0.02x*"))
    results = []
    lock = threading.Lock()

    def client(i):
        code, obj = router.route(
            {"id": f"drain-{i}", "prompt_tokens": [5, 17, 3],
             "max_new_tokens": 15})
        with lock:
            results.append((i, code, obj))

    threads = []
    for i in range(3):
        t = threading.Thread(target=client, args=(i,))
        t.start()
        threads.append(t)
        time.sleep(0.15)     # stagger so least-loaded spreads them
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if all(r.in_flight >= 1 for r in sup.replicas()):
            break
        time.sleep(0.01)
    assert all(r.in_flight >= 1 for r in sup.replicas()), sup.describe()
    progress = []
    sup.rolling_drain(timeout_s=20.0, progress=progress.append)
    faults.clear()
    # one replica at a time: capacity never hit zero before the last
    assert progress == [2, 1, 0]
    for t in threads:
        t.join(timeout=120)
    assert len(results) == 3
    for i, code, obj in sorted(results):
        assert code == 200, obj
        assert obj["finish_reason"] == "length"
        assert len(obj["tokens"]) == 15    # the drain let it FINISH
    assert all(r.state == STOPPED for r in sup.replicas())
