"""Race detection for the native runtime: build and run the C++ stress
harness under ThreadSanitizer (the reference's `go test -race` role,
SURVEY.md §5). TSAN reports abort the binary via halt_on_error."""

import os
import subprocess

import pytest

CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "csrc")


@pytest.mark.slow
def test_stress_under_tsan(tmp_path):
    build = subprocess.run(["make", "-s", "stress-tsan"], cwd=CSRC,
                           capture_output=True, text=True)
    if build.returncode != 0:
        pytest.skip(f"tsan build unavailable: {build.stderr[-300:]}")
    proc = subprocess.run(
        [os.path.join(CSRC, "build", "stress_test_tsan"), str(tmp_path)],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "TSAN_OPTIONS": "halt_on_error=1"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "stress OK" in proc.stdout
    assert "ThreadSanitizer" not in proc.stderr


def test_stress_plain(tmp_path):
    build = subprocess.run(["make", "-s", "stress"], cwd=CSRC,
                           capture_output=True, text=True)
    if build.returncode != 0:
        pytest.skip(f"native build unavailable: {build.stderr[-300:]}")
    proc = subprocess.run(
        [os.path.join(CSRC, "build", "stress_test"), str(tmp_path)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "stress OK" in proc.stdout
