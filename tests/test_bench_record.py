"""bench.py self-healing + per-platform baselines (ROADMAP item 5):
backend-init failure falls back to CPU instead of producing a crash
record, the JSON line is platform-labeled, and vs_baseline is tracked
PER PLATFORM FAMILY — a CPU fallback run can neither regress nor
overwrite the TPU anchor. The e2e test runs the real main() with the
config benches stubbed out (their numerics are covered elsewhere; this
file pins the record/baseline plumbing)."""

import json

import pytest

import bench


# ------------------------------------------------------- backend init
def test_init_backend_falls_back_to_cpu(monkeypatch, capsys):
    import jax

    calls = {"n": 0}
    real_devices = jax.devices

    def flaky_devices():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("Unable to initialize backend 'axon'")
        return real_devices()

    monkeypatch.setattr(jax, "devices", flaky_devices)
    assert bench._init_backend() == "cpu"
    assert calls["n"] == 2
    assert "retrying on cpu" in capsys.readouterr().err


def test_init_backend_env_escape_hatch(monkeypatch):
    monkeypatch.setenv("NEZHA_BENCH_CPU", "1")
    assert bench._init_backend() == "cpu"


# -------------------------------------------------- baseline plumbing
def test_family_baseline_legacy_flat_record_is_tpu():
    legacy = {"gpt2_124m_tokens_per_sec_per_chip": 87564.0,
              "platform": "tpu",
              "resnet50_images_per_sec_per_chip": 2373.7}
    # the tunneled TPU ('axon') and 'tpu' share one family
    assert bench._platform_family("axon") == "tpu"
    tpu = bench._family_baseline(legacy, "tpu")
    assert tpu["gpt2_124m_tokens_per_sec_per_chip"] == 87564.0
    # a CPU run sees NO anchors in a legacy tpu record
    assert bench._family_baseline(legacy, "cpu") == {}


def test_family_baseline_by_platform_overlays_flat():
    rec = {"gpt2_124m_tokens_per_sec_per_chip": 100.0, "platform": "tpu",
           "by_platform": {
               "tpu": {"gpt2_124m_tokens_per_sec_per_chip": 200.0},
               "cpu": {"gpt2_124m_tokens_per_sec_per_chip": 5.0}}}
    assert bench._family_baseline(rec, "tpu")[
        "gpt2_124m_tokens_per_sec_per_chip"] == 200.0
    assert bench._family_baseline(rec, "cpu")[
        "gpt2_124m_tokens_per_sec_per_chip"] == 5.0


def test_load_baseline_corruption_is_sticky(tmp_path):
    path = tmp_path / "b.json"
    path.write_text("{not json")
    rec, corrupt = bench._load_baseline(str(path))
    assert rec == {} and corrupt
    path.write_text("[1, 2]")       # parseable but not a record
    rec, corrupt = bench._load_baseline(str(path))
    assert rec == {} and corrupt
    rec, corrupt = bench._load_baseline(str(tmp_path / "missing.json"))
    assert rec == {} and not corrupt


# --------------------------------------------------------- e2e record
@pytest.fixture()
def stubbed_bench(monkeypatch):
    """main() with the config benches stubbed to constants — the run
    exercises backend init, the dispatch-ping loop, and the whole
    baseline/record path, without minutes of CPU training."""
    monkeypatch.setattr(bench, "bench_gpt2",
                        lambda on_tpu, peak, **kw: (1000.0, None, 0.01))
    monkeypatch.setattr(bench, "bench_resnet50",
                        lambda on_tpu, peak: (50.0, None, 0.02))
    monkeypatch.setattr(bench, "bench_bert",
                        lambda on_tpu, peak: (800.0, None, 0.01))
    monkeypatch.setattr(bench, "bench_wrn101",
                        lambda on_tpu, peak: (20.0, None, 0.01))
    monkeypatch.setattr(bench, "bench_mlp", lambda on_tpu: 5.0)
    return bench


def _run_main(capsys) -> dict:
    assert bench.main() == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    return json.loads(out)


def test_bench_writes_platform_labeled_record(stubbed_bench, tmp_path,
                                              monkeypatch, capsys):
    """The acceptance path: on a machine with no TPU backend, bench.py
    completes, labels the record with its platform, seeds the CPU
    baseline slot, and tracks vs_baseline against it on the next run —
    all without touching a pre-existing TPU anchor."""
    path = tmp_path / "baseline.json"
    # a legacy TPU record is already there — the CPU run must not read
    # or clobber it
    path.write_text(json.dumps(
        {"gpt2_124m_tokens_per_sec_per_chip": 87564.0,
         "platform": "tpu"}))
    monkeypatch.setenv("NEZHA_BENCH_BASELINE", str(path))

    rec = _run_main(capsys)
    assert rec["platform"] == "cpu"
    assert rec["value"] == 1000.0
    assert rec["vs_baseline"] == 1.0      # first CPU measurement
    saved = json.loads(path.read_text())
    # TPU anchor untouched; CPU anchors seeded in their own slot
    assert saved["gpt2_124m_tokens_per_sec_per_chip"] == 87564.0
    assert saved["by_platform"]["cpu"][
        "gpt2_124m_tokens_per_sec_per_chip"] == 1000.0
    assert saved["by_platform"]["cpu"][
        "resnet50_images_per_sec_per_chip"] == 50.0

    # second run: vs_baseline is CPU-vs-CPU, anchors not overwritten
    monkeypatch.setattr(bench, "bench_gpt2",
                        lambda on_tpu, peak, **kw: (1500.0, None, 0.01))
    rec2 = _run_main(capsys)
    assert rec2["vs_baseline"] == 1.5
    assert rec2["extras"]["resnet50_vs_baseline"] == 1.0
    saved2 = json.loads(path.read_text())
    assert saved2["by_platform"]["cpu"][
        "gpt2_124m_tokens_per_sec_per_chip"] == 1000.0


def test_bench_corrupt_baseline_never_overwritten(stubbed_bench,
                                                  tmp_path, monkeypatch,
                                                  capsys):
    path = tmp_path / "baseline.json"
    path.write_text("{torn write")
    monkeypatch.setenv("NEZHA_BENCH_BASELINE", str(path))
    rec = _run_main(capsys)
    assert rec["vs_baseline"] == 1.0
    # the corrupt file was left for a human, not reset to this run
    assert path.read_text() == "{torn write"
