"""bench.py self-healing + per-platform baselines (ROADMAP item 5):
backend-init failure falls back to CPU instead of producing a crash
record, the JSON line is platform-labeled, and vs_baseline is tracked
PER PLATFORM FAMILY — a CPU fallback run can neither regress nor
overwrite the TPU anchor. The e2e test runs the real main() with the
config benches stubbed out (their numerics are covered elsewhere; this
file pins the record/baseline plumbing)."""

import json
import os
import sys

import pytest

import bench

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)


# ------------------------------------------------------- backend init
def test_init_backend_falls_back_to_cpu(monkeypatch, capsys):
    import jax

    calls = {"n": 0}
    real_devices = jax.devices

    def flaky_devices():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("Unable to initialize backend 'axon'")
        return real_devices()

    monkeypatch.setattr(jax, "devices", flaky_devices)
    assert bench._init_backend() == "cpu"
    assert calls["n"] == 2
    assert "retrying on cpu" in capsys.readouterr().err


def test_init_backend_env_escape_hatch(monkeypatch):
    monkeypatch.setenv("NEZHA_BENCH_CPU", "1")
    assert bench._init_backend() == "cpu"


# -------------------------------------------------- baseline plumbing
def test_family_baseline_legacy_flat_record_is_tpu():
    legacy = {"gpt2_124m_tokens_per_sec_per_chip": 87564.0,
              "platform": "tpu",
              "resnet50_images_per_sec_per_chip": 2373.7}
    # the tunneled TPU ('axon') and 'tpu' share one family
    assert bench._platform_family("axon") == "tpu"
    tpu = bench._family_baseline(legacy, "tpu")
    assert tpu["gpt2_124m_tokens_per_sec_per_chip"] == 87564.0
    # a CPU run sees NO anchors in a legacy tpu record
    assert bench._family_baseline(legacy, "cpu") == {}


def test_family_baseline_by_platform_overlays_flat():
    rec = {"gpt2_124m_tokens_per_sec_per_chip": 100.0, "platform": "tpu",
           "by_platform": {
               "tpu": {"gpt2_124m_tokens_per_sec_per_chip": 200.0},
               "cpu": {"gpt2_124m_tokens_per_sec_per_chip": 5.0}}}
    assert bench._family_baseline(rec, "tpu")[
        "gpt2_124m_tokens_per_sec_per_chip"] == 200.0
    assert bench._family_baseline(rec, "cpu")[
        "gpt2_124m_tokens_per_sec_per_chip"] == 5.0


def test_load_baseline_corruption_is_sticky(tmp_path):
    path = tmp_path / "b.json"
    path.write_text("{not json")
    rec, corrupt = bench._load_baseline(str(path))
    assert rec == {} and corrupt
    path.write_text("[1, 2]")       # parseable but not a record
    rec, corrupt = bench._load_baseline(str(path))
    assert rec == {} and corrupt
    rec, corrupt = bench._load_baseline(str(tmp_path / "missing.json"))
    assert rec == {} and not corrupt


# --------------------------------------------------------- e2e record
@pytest.fixture()
def stubbed_bench(monkeypatch):
    """main() with the config benches stubbed to constants — the run
    exercises backend init, the dispatch-ping loop, and the whole
    baseline/record path, without minutes of CPU training."""
    monkeypatch.setattr(bench, "bench_gpt2",
                        lambda on_tpu, peak, **kw: (1000.0, None, 0.01))
    monkeypatch.setattr(bench, "bench_resnet50",
                        lambda on_tpu, peak: (50.0, None, 0.02))
    monkeypatch.setattr(bench, "bench_bert",
                        lambda on_tpu, peak: (800.0, None, 0.01))
    monkeypatch.setattr(bench, "bench_wrn101",
                        lambda on_tpu, peak: (20.0, None, 0.01))
    monkeypatch.setattr(bench, "bench_mlp", lambda on_tpu: 5.0)
    return bench


def _run_main(capsys) -> dict:
    assert bench.main() == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    return json.loads(out)


def test_bench_writes_platform_labeled_record(stubbed_bench, tmp_path,
                                              monkeypatch, capsys):
    """The acceptance path: on a machine with no TPU backend, bench.py
    completes, labels the record with its platform, seeds the CPU
    baseline slot, and tracks vs_baseline against it on the next run —
    all without touching a pre-existing TPU anchor."""
    path = tmp_path / "baseline.json"
    # a legacy TPU record is already there — the CPU run must not read
    # or clobber it
    path.write_text(json.dumps(
        {"gpt2_124m_tokens_per_sec_per_chip": 87564.0,
         "platform": "tpu"}))
    monkeypatch.setenv("NEZHA_BENCH_BASELINE", str(path))

    rec = _run_main(capsys)
    assert rec["platform"] == "cpu"
    assert rec["value"] == 1000.0
    assert rec["vs_baseline"] == 1.0      # first CPU measurement
    saved = json.loads(path.read_text())
    # TPU anchor untouched; CPU anchors seeded in their own slot
    assert saved["gpt2_124m_tokens_per_sec_per_chip"] == 87564.0
    assert saved["by_platform"]["cpu"][
        "gpt2_124m_tokens_per_sec_per_chip"] == 1000.0
    assert saved["by_platform"]["cpu"][
        "resnet50_images_per_sec_per_chip"] == 50.0

    # second run: vs_baseline is CPU-vs-CPU, anchors not overwritten
    monkeypatch.setattr(bench, "bench_gpt2",
                        lambda on_tpu, peak, **kw: (1500.0, None, 0.01))
    rec2 = _run_main(capsys)
    assert rec2["vs_baseline"] == 1.5
    assert rec2["extras"]["resnet50_vs_baseline"] == 1.0
    saved2 = json.loads(path.read_text())
    assert saved2["by_platform"]["cpu"][
        "gpt2_124m_tokens_per_sec_per_chip"] == 1000.0


def test_bench_corrupt_baseline_never_overwritten(stubbed_bench,
                                                  tmp_path, monkeypatch,
                                                  capsys):
    path = tmp_path / "baseline.json"
    path.write_text("{torn write")
    monkeypatch.setenv("NEZHA_BENCH_BASELINE", str(path))
    rec = _run_main(capsys)
    assert rec["vs_baseline"] == 1.0
    # the corrupt file was left for a human, not reset to this run
    assert path.read_text() == "{torn write"


# ------------------------------------------- committed-record hygiene
def test_committed_bench_records_pass_hygiene_check():
    """THE tier-1 wire for tools/check_bench_record.py: every committed
    BENCH_*.json in the repo root must be a platform-labeled, schema-
    valid measurement — or be explicitly superseded in BENCH_NOTES.md
    (the r03–r05 crash records). A future crash record fails here."""
    import os

    from check_bench_record import check_dir, superseded_records
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert check_dir(root) == []
    # The known crash records are superseded, not silently valid.
    assert {"BENCH_r03.json", "BENCH_r04.json",
            "BENCH_r05.json"} <= superseded_records(root)


def test_bench_record_checker_flags_crash_and_unlabeled(tmp_path):
    """A crash record (rc != 0), an rc=0 run with no parsed metric, and
    an unlabeled measurement all fail; listing the crash under the
    notes' Superseded heading exempts exactly that file."""
    from check_bench_record import check_dir, check_record
    crash = tmp_path / "BENCH_r99.json"
    crash.write_text(json.dumps(
        {"n": 99, "cmd": "python bench.py", "rc": 1,
         "tail": "RuntimeError: Unable to initialize backend 'axon'",
         "parsed": None}))
    assert any("CRASH RECORD" in e for e in check_record(str(crash)))

    silent = tmp_path / "BENCH_s.json"
    silent.write_text(json.dumps(
        {"n": 1, "cmd": "x", "rc": 0, "tail": "", "parsed": None}))
    assert any("no parsed metric" in e for e in check_record(str(silent)))

    unlabeled = tmp_path / "BENCH_u.json"
    unlabeled.write_text(json.dumps(
        {"n": 1, "cmd": "x", "rc": 0, "tail": "",
         "parsed": {"metric": "m", "value": 1.0}}))
    assert any("no platform label" in e
               for e in check_record(str(unlabeled)))

    not_json = tmp_path / "BENCH_torn.json"
    not_json.write_text("{torn")
    assert any("not valid JSON" in e for e in check_record(str(not_json)))

    good = tmp_path / "BENCH_ok.json"
    good.write_text(json.dumps(
        {"n": 1, "cmd": "x", "rc": 0, "tail": "",
         "parsed": {"metric": "m", "value": 1.0}, "platform": "cpu"}))
    assert check_record(str(good)) == []

    # Directory sweep: everything flagged until the notes supersede the
    # bad ones — and ONLY the listed files are exempted.
    assert check_dir(str(tmp_path)) != []
    (tmp_path / "BENCH_NOTES.md").write_text(
        "# notes\n\n## Superseded records\n\n"
        "- BENCH_r99.json — crash record\n"
        "- BENCH_s.json — printed nothing\n"
        "- BENCH_u.json — unlabeled legacy\n"
        "- BENCH_torn.json — torn write\n")
    assert check_dir(str(tmp_path)) == []
