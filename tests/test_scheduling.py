"""SLO-aware multi-tenant scheduling (PR 19): WFQ lane arithmetic
(weight conservation, starvation-freedom, tenant round-robin, the
single-lane-is-exact-FIFO compatibility pin), typed per-tenant queue
caps, preemption to the host KV tier (bit-identical preempt -> resume
on BOTH kv layouts, deadline-while-preempted, the preemption-budget
anti-thrash pin, the scheduler.preempt failed-demotion drill, the
SLO-burn quota widening), the elastic supervisor (autoscale ladder
with two-sided hysteresis, cooldown, bounds, the supervisor.scale
drill, config validation), and the 16-request seeded acceptance under
preemption churn: zero slot/block/host leaks and the frozen
``1 + len(prefill_buckets)`` program contract.

Serving tests run the tiny CPU GPT-2 from test_serve.py's config;
autoscale tests drive ``Supervisor.autoscale_tick(now=...)`` directly
against a fake backend — no sockets, no threads, no timing games."""

import dataclasses
import os
import sys
import time
import types

import jax
import jax.numpy as jnp
import pytest

from nezha_tpu import faults, obs
from nezha_tpu.faults import FaultPlan
from nezha_tpu.models.gpt2 import GPT2, GPT2Config
from nezha_tpu.serve import (
    Engine,
    FinishReason,
    PRIORITIES,
    QueueFull,
    Request,
    Scheduler,
    ServeConfig,
    TenantOverLimit,
)
from nezha_tpu.serve.scheduler import _Live
from nezha_tpu.serve.supervisor import (
    LIVE,
    STARTING,
    STOPPED,
    RouterConfig,
    Supervisor,
)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))

CFG = dict(vocab_size=97, max_positions=64, num_layers=2, num_heads=4,
           hidden_size=64)
# Two slots on purpose: one background decode + one free slot means the
# SECOND interactive arrival is exactly the preemption trigger.
PCFG = ServeConfig(max_batch_size=2, max_len=48, max_prefill_len=8,
                   prefill_buckets=(4, 8), k_max=16, queue_capacity=8,
                   cache_dtype=jnp.float32, kv_block_size=4,
                   preemption=True, preemption_budget=2)
DCFG = dataclasses.replace(PCFG, kv_layout="dense")


@pytest.fixture(scope="module")
def model_and_vars():
    model = GPT2(GPT2Config(**CFG))
    return model, model.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def paged_engine(model_and_vars):
    model, variables = model_and_vars
    return Engine(model, variables, PCFG)


@pytest.fixture(scope="module")
def dense_engine(model_and_vars):
    model, variables = model_and_vars
    return Engine(model, variables, DCFG)


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.clear()
    yield
    faults.clear()


def _drain(sched, max_iters=300):
    iters = sched.run_until_idle(max_iters=max_iters)
    assert not sched.has_work(), "scheduler did not drain"
    return iters


def _submit(sched, rid, prompt, priority="interactive", tenant="default",
            max_new=4, deadline_s=None):
    return sched.submit(Request(
        prompt=prompt, max_new_tokens=max_new, priority=priority,
        tenant_id=tenant, deadline_s=deadline_s, request_id=rid))


# ------------------------------------------------------------------ WFQ
def test_wfq_weight_conservation(paged_engine):
    """Under a full backlog in every lane the default 4:2:1 weights
    grant exactly 4 interactive / 2 batch / 1 background per 7 — and
    the exact virtual-time order is deterministic. Background is
    granted within the first 7: starvation-freedom, not priority
    masking."""
    sched = Scheduler(paged_engine)
    # Adversarial submit order: lowest class first.
    _submit(sched, "g0", [1, 2, 3], priority="background")
    for i in range(2):
        _submit(sched, f"b{i}", [1, 2, 3], priority="batch")
    for i in range(4):
        _submit(sched, f"i{i}", [1, 2, 3], priority="interactive")
    with sched._lock:
        order = [sched._pop_next().req.priority for _ in range(7)]
    assert order == ["interactive", "batch", "background",
                     "interactive", "interactive", "batch",
                     "interactive"]
    assert sched.queue_depth == 0


def test_wfq_tenant_round_robin(paged_engine):
    """Within one lane, tenants share equally: a 3-deep tenant cannot
    starve a 2-deep one — grants alternate."""
    sched = Scheduler(paged_engine)
    for i in range(3):
        _submit(sched, f"a{i}", [1, 2], priority="batch", tenant="acme")
    for i in range(2):
        _submit(sched, f"x{i}", [1, 2], priority="batch", tenant="xcorp")
    with sched._lock:
        order = [sched._pop_next().request_id for _ in range(5)]
    assert order == ["a0", "x0", "a1", "x1", "a2"]


def test_wfq_single_lane_is_exact_fifo(paged_engine):
    """The compatibility pin: every pre-PR-19 caller lands in one lane
    and one tenant, where WFQ degenerates to the bounded FIFO —
    defaults preserve today's order bit-for-bit."""
    sched = Scheduler(paged_engine)
    for i in range(6):
        _submit(sched, f"r{i}", [1, 2, 3])
    with sched._lock:
        order = [sched._pop_next().request_id for _ in range(6)]
    assert order == [f"r{i}" for i in range(6)]


def test_priority_and_tenant_validation(paged_engine):
    sched = Scheduler(paged_engine)
    with pytest.raises(ValueError, match="priority"):
        sched.submit(Request(prompt=[1], priority="urgent"))
    with pytest.raises(ValueError, match="tenant_id"):
        sched.submit(Request(prompt=[1], tenant_id=""))
    assert tuple(PRIORITIES) == ("interactive", "batch", "background")


def test_tenant_over_limit_typed(model_and_vars):
    """The per-tenant cap fails typed — TenantOverLimit IS a QueueFull
    (existing 503 handlers keep working) but names the tenant, and
    other tenants keep admitting below the global bound."""
    model, variables = model_and_vars
    engine = Engine(model, variables,
                    dataclasses.replace(PCFG, tenant_queue_cap=2))
    sched = Scheduler(engine)
    _submit(sched, "a0", [1, 2], tenant="acme")
    _submit(sched, "a1", [1, 2], tenant="acme")
    with pytest.raises(TenantOverLimit):
        _submit(sched, "a2", [1, 2], tenant="acme")
    assert issubclass(TenantOverLimit, QueueFull)
    _submit(sched, "x0", [1, 2], tenant="xcorp")   # not affected
    assert sched.tenant_queue_depths() == {"acme": 2, "xcorp": 1}
    # The cap is per-tenant-across-lanes, not per (tenant, lane).
    with pytest.raises(TenantOverLimit):
        _submit(sched, "a3", [1, 2], tenant="acme", priority="batch")


def test_preemption_off_never_fires(model_and_vars):
    """The default config never preempts — _maybe_preempt is a no-op
    before it even looks for a victim."""
    model, variables = model_and_vars
    engine = Engine(model, variables,
                    dataclasses.replace(PCFG, preemption=False))
    sched = Scheduler(engine)
    target = _Live(req=Request(prompt=[1], priority="interactive"),
                   request_id="t", submit_t=0.0, deadline_t=None)
    with sched._lock:
        assert sched._maybe_preempt(target, 0) is False


# ----------------------------------------------------------- preemption
def _run_reference(engine, rid, prompt, max_new):
    """Uninterrupted greedy run of one request -> its token stream."""
    sched = Scheduler(engine)
    _submit(sched, rid, prompt, priority="background", max_new=max_new)
    _drain(sched)
    res = sched.results[rid]
    assert res.finish_reason == FinishReason.LENGTH
    return res.tokens


def _preempt_resume_case(engine):
    """Shared body of the bit-identical preempt -> resume check: a
    background decode is suspended mid-stream by two interactive
    arrivals, demoted (blocks -> trie -> host tier on the paged
    layout; a cold re-prefill on dense), resumed, and must emit
    exactly the uninterrupted stream."""
    prompt = [5, 9, 14, 20, 27, 35]
    ref = _run_reference(engine, "ref", prompt, max_new=12)

    sched = Scheduler(engine)
    _submit(sched, "bg", prompt, priority="background", max_new=12)
    sched.step()
    with sched._lock:
        (bg_live,) = sched._live.values()
        assert len(bg_live.tokens) >= 1    # suspended MID-stream
    _submit(sched, "i0", [2, 4, 6], max_new=4)
    _submit(sched, "i1", [3, 5, 7], max_new=4)
    sched.step()
    # The second interactive could only get its slot by suspending the
    # strictly-lower-priority background decode.
    assert sched.preempted_count == 1
    _drain(sched)
    assert sched.preempted_count == 0
    for rid in ("i0", "i1"):
        assert sched.results[rid].finish_reason == FinishReason.LENGTH
    res = sched.results["bg"]
    assert res.finish_reason == FinishReason.LENGTH
    assert res.tokens == ref, "resume is not bit-identical"
    assert engine.pool.num_free == engine.cfg.max_batch_size


def test_preempt_resume_bit_identical_paged(paged_engine):
    _preempt_resume_case(paged_engine)
    paged_engine.pool.leak_check()


def test_preempt_resume_bit_identical_dense(dense_engine):
    _preempt_resume_case(dense_engine)


def test_deadline_while_preempted(paged_engine):
    """A deadline keeps ticking while a request sits suspended: it
    retires DEADLINE with the tokens it already emitted, never resumes,
    and leaks nothing."""
    sched = Scheduler(paged_engine)
    _submit(sched, "bg", [1, 2, 3, 4, 5, 6], priority="background",
            max_new=30, deadline_s=0.2)
    sched.step()
    _submit(sched, "i0", [2, 4, 6], max_new=3)
    _submit(sched, "i1", [3, 5, 7], max_new=3)
    sched.step()
    assert sched.preempted_count == 1
    time.sleep(0.3)
    sched.step()            # _expire_preempted runs before admission
    res = sched.results["bg"]
    assert res.finish_reason == FinishReason.DEADLINE
    assert 1 <= len(res.tokens) < 30
    assert sched.preempted_count == 0
    _drain(sched)
    assert paged_engine.pool.num_free == PCFG.max_batch_size


def test_preemption_budget_anti_thrash(paged_engine):
    """A victim at its preemption_budget is never suspended again — the
    interactive pick waits for ordinary retirement instead of thrashing
    one request between slot and host tier forever."""
    sched = Scheduler(paged_engine)
    _submit(sched, "bg", [1, 2, 3], priority="background", max_new=6)
    sched.step()
    with sched._lock:
        (victim,) = sched._live.values()
        victim.preempt_count = PCFG.preemption_budget
    _submit(sched, "i0", [2, 4, 6], max_new=3)
    _submit(sched, "i1", [3, 5, 7], max_new=3)
    sched.step()
    assert sched.preempted_count == 0      # budget pinned the victim
    assert sched.queue_depth == 1          # i1 waits its turn
    _drain(sched)
    assert sched.results["bg"].finish_reason == FinishReason.LENGTH
    assert len(sched.results["bg"].tokens) == 6


def test_scheduler_preempt_drill_victim_keeps_decoding(paged_engine):
    """The failed-demotion drill: an injected error at the
    scheduler.preempt fault point vetoes the suspend — the victim
    keeps decoding to completion, the interactive pick waits for a
    slot the ordinary way, and nobody sees an error."""
    faults.install(FaultPlan.parse("scheduler.preempt:error@1x*"))
    sched = Scheduler(paged_engine)
    _submit(sched, "bg", [1, 2, 3], priority="background", max_new=5)
    sched.step()
    _submit(sched, "i0", [2, 4, 6], max_new=3)
    _submit(sched, "i1", [3, 5, 7], max_new=3)
    sched.step()
    assert sched.preempted_count == 0      # every preempt vetoed
    _drain(sched)
    assert faults.active().injected_counts["scheduler.preempt"] >= 1
    for rid, n in (("bg", 5), ("i0", 3), ("i1", 3)):
        res = sched.results[rid]
        assert res.finish_reason == FinishReason.LENGTH
        assert len(res.tokens) == n
    assert paged_engine.pool.num_free == PCFG.max_batch_size


def test_slo_burn_widens_preemption_quota(paged_engine):
    """One admission pass preempts at most ONE victim — unless the
    wired interactive-TTFT SLO is burning, when the quota opens to the
    whole batch (the PR 16 control signal)."""
    sched = Scheduler(paged_engine)
    _submit(sched, "g0", [1, 2, 3], priority="background", max_new=10)
    _submit(sched, "g1", [4, 5, 6], priority="background", max_new=10)
    sched.step()
    with sched._lock:
        assert len(sched._live) == 2
    _submit(sched, "i0", [2, 4, 6], max_new=3)
    _submit(sched, "i1", [3, 5, 7], max_new=3)
    with sched._lock:
        sched._admit()                     # one pass, healthy SLO
    assert sched.preempted_count == 1      # gentle: one per pass
    assert sched.queue_depth == 1
    sched.slo_tracker = types.SimpleNamespace(
        cfg=types.SimpleNamespace(op="<", threshold=1e9),
        observe=lambda ok: None, burn_rate=lambda: 2.0)
    try:
        with sched._lock:
            sched._admit()                 # one pass, burning SLO
        assert sched.preempted_count == 2  # quota opened to the batch
        assert sched.queue_depth == 0
    finally:
        sched.slo_tracker = None
    _drain(sched)
    for rid in ("g0", "g1", "i0", "i1"):
        assert sched.results[rid].finish_reason == FinishReason.LENGTH
    assert len(sched.results["g0"].tokens) == 10
    assert len(sched.results["g1"].tokens) == 10
    assert paged_engine.pool.num_free == PCFG.max_batch_size


# ------------------------------------------------- chaos under churn
def test_chaos_16_requests_under_preemption_churn(model_and_vars,
                                                  tmp_path):
    """The PR 19 acceptance scenario: 16 mixed-priority requests from
    two tenants, open-loop at overcapacity on an int8 paged pool WITH
    a host tier, preemption on and a seeded scheduler.preempt veto in
    the middle of the churn. Every request completes to its full
    length (preempt -> resume is invisible to clients), zero
    slot/block/host leaks, the program set stays frozen at
    ``1 + len(prefill_buckets)``, and preemptions balance resumes."""
    model, variables = model_and_vars
    ccfg = dataclasses.replace(
        PCFG, max_batch_size=3, queue_capacity=4, kv_num_blocks=24,
        kv_dtype="int8", kv_host_blocks=8)
    run_dir = str(tmp_path / "churn")
    obs.start_run(run_dir, meta={"kind": "preemption_churn"})
    try:
        engine = Engine(model, variables, ccfg)
        sched = Scheduler(engine)
        faults.install(FaultPlan.parse("scheduler.preempt:error@2",
                                       seed=19))
        pris = ("background", "background", "background", "interactive")
        issued = 0
        while issued < 16 or sched.has_work():
            while issued < 16 and sched.queue_depth < ccfg.queue_capacity:
                n = 3 if issued % 2 == 0 else 6
                sched.submit(Request(
                    prompt=[(5 * issued + j + 1) % 97 for j in range(n)],
                    max_new_tokens=5, request_id=f"c{issued}",
                    priority=pris[issued % 4],
                    tenant_id="acme" if issued % 2 else "globex"))
                issued += 1
            sched.step()
        results = [sched.results[f"c{i}"] for i in range(16)]
        assert all(r.finish_reason == FinishReason.LENGTH
                   for r in results)
        assert all(len(r.tokens) == 5 for r in results)
        # Churn actually happened, and the books balance: every
        # suspension was resumed (no deadlines, no cancels).
        preempts = obs.counter("serve.preemptions_total").value
        resumes = obs.counter("serve.resumes_total").value
        assert preempts >= 1
        assert preempts == resumes
        assert sched.preempted_count == 0
        # Zero slot/block/host leaks; frozen program set.
        assert engine.pool.num_free == ccfg.max_batch_size
        engine.pool.leak_check()
        stats = engine.compile_stats()
        assert stats["entries"] == stats["misses"] == \
            1 + len(ccfg.prefill_buckets)
    finally:
        faults.clear()
        obs.end_run()
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []
    from nezha_tpu.obs.report import render_report
    report = render_report(run_dir)
    assert "preemption:" in report


# ------------------------------------------------------------ autoscale
class _FakeHandle:
    def __init__(self, port):
        self.port = port
        self._alive = True

    def alive(self):
        return self._alive

    def terminate(self):
        self._alive = False

    def kill(self):
        self._alive = False

    def wait(self, timeout_s=None):
        return True


class _FakeBackend:
    def __init__(self):
        self.spawned = []

    def spawn(self, rid, port):
        self.spawned.append(rid)
        return _FakeHandle(port)


def _fleet(cfg):
    """A supervisor over fake handles with every replica probed LIVE —
    no monitor thread, tests drive autoscale_tick(now=...) directly."""
    sup = Supervisor(_FakeBackend(), cfg)
    with sup._lock:
        for r in sup._replicas:
            sup._spawn(r)
    for r in sup.replicas():
        sup.mark_probe(r.rid, True, {"queued": 0})
    return sup


def _probe_all(sup, queued):
    for r in sup.replicas():
        if r.state in (STARTING, LIVE):
            sup.mark_probe(r.rid, True, {"queued": queued})


def _wait_stopped(sup, rid, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if sup.replicas()[rid].state == STOPPED:
            return
        time.sleep(0.01)
    raise AssertionError(f"replica {rid} never reached STOPPED")


def test_autoscale_off_by_default():
    cfg = RouterConfig(replicas=2)
    assert cfg.autoscale_enabled is False
    sup = _fleet(cfg)
    _probe_all(sup, queued=100)
    assert sup.autoscale_tick(now=1.0) is None
    assert len(sup.replicas()) == 2


def test_autoscale_config_validation():
    with pytest.raises(ValueError):
        RouterConfig(replicas=2, autoscale_min=1)       # one-sided
    with pytest.raises(ValueError):
        RouterConfig(replicas=2, autoscale_min=3, autoscale_max=4)
    with pytest.raises(ValueError):
        RouterConfig(replicas=2, autoscale_min=0, autoscale_max=3)
    with pytest.raises(ValueError):
        RouterConfig(replicas=2, autoscale_min=2, autoscale_max=1)
    with pytest.raises(ValueError):
        RouterConfig(replicas=2, roles=("prefill", "decode"),
                     autoscale_min=1, autoscale_max=3)
    cfg = RouterConfig(replicas=2, autoscale_min=1, autoscale_max=3)
    assert cfg.autoscale_enabled is True


def test_autoscale_ladder_up_and_down(tmp_path):
    """The elastic ladder: sustained queue pressure scales up one
    replica per action, a sustained fully-idle fleet scales back down,
    bounds hold at both ends, and scale-up after a drain REUSES the
    stopped record (the rid == index invariant the router's ledgers
    rely on)."""
    cfg = RouterConfig(replicas=2, autoscale_min=1, autoscale_max=3,
                       autoscale_sustain_ticks=2,
                       autoscale_cooldown_s=0.0)
    sup = _fleet(cfg)
    _probe_all(sup, queued=10)                 # per-live 5 >= 4: hot
    assert sup.autoscale_tick(now=1.0) is None  # sustain 1/2
    assert sup.autoscale_tick(now=2.0) == "up"
    assert len(sup.replicas()) == 3
    assert sup.replicas()[2].state == STARTING
    assert sup.autoscale_target() == 3
    sup.mark_probe(2, True, {"queued": 0})

    # At the max bound, sustained pressure holds scale.
    _probe_all(sup, queued=10)
    assert sup.autoscale_tick(now=3.0) is None
    assert sup.autoscale_tick(now=4.0) is None
    assert len(sup.replicas()) == 3

    # Fully idle (zero queued, zero in flight) -> drain the highest rid.
    _probe_all(sup, queued=0)
    assert sup.autoscale_tick(now=5.0) is None  # sustain 1/2
    assert sup.autoscale_tick(now=6.0) == "down"
    _wait_stopped(sup, 2)
    assert sup.autoscale_target() == 2
    assert [r.state for r in sup.replicas()[:2]] == [LIVE, LIVE]

    # Scale-up again: the STOPPED record is re-armed, not appended.
    _probe_all(sup, queued=10)
    assert sup.autoscale_tick(now=7.0) is None
    assert sup.autoscale_tick(now=8.0) == "up"
    assert len(sup.replicas()) == 3            # reused, not 4
    assert sup.replicas()[2].state == STARTING
    assert sup.backend.spawned == [0, 1, 2, 2]


def test_autoscale_hysteresis_deadband_and_cooldown():
    """A mixed reading resets BOTH sustain counters (the deadband), so
    a flapping queue never moves the fleet; after an action the
    cooldown gates the next one regardless of pressure."""
    cfg = RouterConfig(replicas=2, autoscale_min=1, autoscale_max=4,
                       autoscale_sustain_ticks=2,
                       autoscale_cooldown_s=0.0)
    sup = _fleet(cfg)
    for t in range(8):       # hot, neutral, hot, neutral ... never acts
        _probe_all(sup, queued=10 if t % 2 == 0 else 1)
        assert sup.autoscale_tick(now=float(t)) is None
    assert len(sup.replicas()) == 2
    assert sup.autoscale_target() == 2

    cfg2 = RouterConfig(replicas=2, autoscale_min=1, autoscale_max=4,
                        autoscale_sustain_ticks=1,
                        autoscale_cooldown_s=100.0)
    sup2 = _fleet(cfg2)
    _probe_all(sup2, queued=10)
    assert sup2.autoscale_tick(now=10.0) == "up"
    _probe_all(sup2, queued=10)
    assert sup2.autoscale_tick(now=11.0) is None    # inside cooldown
    assert sup2.autoscale_tick(now=111.0) == "up"   # cooldown elapsed
    assert len(sup2.replicas()) == 4


def test_supervisor_scale_drill_skips_action():
    """The supervisor.scale drill: an injected error at the decision
    skips that scale action — the fleet holds its size — and pressure
    simply re-evaluates next tick (the sustain counters are NOT
    consumed by a vetoed action)."""
    cfg = RouterConfig(replicas=2, autoscale_min=1, autoscale_max=3,
                       autoscale_sustain_ticks=1,
                       autoscale_cooldown_s=0.0)
    sup = _fleet(cfg)
    faults.install(FaultPlan.parse("supervisor.scale:error@1"))
    _probe_all(sup, queued=10)
    assert sup.autoscale_tick(now=1.0) is None      # vetoed
    assert len(sup.replicas()) == 2
    assert sup.autoscale_target() == 2
    assert faults.active().injected_counts == {"supervisor.scale": 1}
    _probe_all(sup, queued=10)
    assert sup.autoscale_tick(now=2.0) == "up"      # next tick acts
    assert len(sup.replicas()) == 3
